"""Continuous batching: the cross-job slab-packing scheduler.

The serve stack's missing fleet layer (ROADMAP item 1): the runner has
a warm persistent backend, a crash-safe journal, admission with tenant
quotas, and per-tenant SLO burn counters — yet jobs execute strictly
serially, one small job's slabs owning the device while every other
tenant queues.  This module is the continuous-batching insight every
LLM serving system converged on, applied to pileup slabs: drain the
admission queue, pack many small jobs into shared canonical slabs
(serve/packing.py) so N jobs ride ONE device dispatch sequence, then
extract per-job count partitions and run each job's tail/render through
the exact cold-run code path (``JaxBackend.run_from_counts``) — per-job
byte identity is structural, not asserted.

**Composition policy** reads the signals the telemetry plane already
computes: a tenant currently burning an SLO objective
(``AdmissionController.slo_burn_by_tenant``) gets LATENCY — its job
flushes the batch immediately instead of waiting for the batch to fill
or the ``--batch-window`` to lapse — while bulk tenants get THROUGHPUT
(full slabs).  ``--batch {off,auto,N}`` caps members per batch;
member/combined genome-length caps (S2C_BATCH_MAX_MEMBER_LEN /
S2C_BATCH_MAX_LEN) keep the shared tensor bounded.

**Eligibility** — a job packs only when packing cannot change its
semantics or violate an isolation decision already made: ``--pileup
auto|scatter`` only (an explicit host/pallas/mxu pin is the user's
placement decision), never paranoid (its contract is per-batch
revalidation against the job's OWN accumulator), never a
degraded-tenant-pinned job (pinning means "off the fleet's device
path"), and never a checkpointed job (serve already rejects those).
Everything else — journal mode, tolerant decode, tenants, SLO — composes.

**Failure discipline** (the PR-8 count-bank rule: private partitions
are handed out only on success):

* a member failing in ITS OWN phase (decode, tail) fails alone — the
  shared tensor never held co-tenants' corruption because extraction
  slices are disjoint and addition is exact;
* any fault inside the PACKED phases (merge, shared dispatch,
  extraction) demotes the whole batch: the shared tensor is discarded
  and every not-yet-finished member re-runs through the untouched
  serial path (``serve/batch_demotions``).  Co-tenant counts are never
  merged from a dispatch that did not complete;
* a crash mid-batch replays only uncommitted members: each member's
  journal lifecycle (started/committed/failed) is per-job, and a
  packed member's replay unit is the whole (small) job.

Every packed job's manifest carries the batch policy as a priced
ledger decision (``serve_batch``: predicted vs measured shared-phase
wall / jobs-per-sec, residual inside the drift band) plus the
``serve/batch`` gauge family (batch size, occupancy, pack seconds,
per-job dispatch share).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import observability as obs
from ..observability import telemetry as stele
from . import packing

logger = logging.getLogger("sam2consensus_tpu.serve.scheduler")

#: --batch auto: members per batch.  Eight is the committed bench
#: point (campaign serve_batch leg); override with S2C_BATCH_AUTO_JOBS.
DEFAULT_AUTO_JOBS = 8

#: default --batch-window: how long a filling batch may wait for more
#: eligible jobs before flushing (milliseconds).  Only meaningful for
#: live arrival streams; a pre-planned queue arrives all at once.
DEFAULT_WINDOW_MS = 50.0

#: a member packs only when its genome fits this many positions —
#: "small job" is a length statement (the oracle-noise-bound configs,
#: phix / target_capture class); big genomes keep the dedicated path
DEFAULT_MAX_MEMBER_LEN = 1 << 21
#: combined cap on the shared tensor (bounds the packed allocation)
DEFAULT_MAX_COMBINED_LEN = 1 << 23


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def parse_batch_mode(value) -> Tuple[str, int]:
    """``--batch {off,auto,N}`` -> ``(mode, max_jobs)``.

    ``off`` disables packing (max 1); ``auto`` packs up to the tuned
    default; an integer packs up to exactly N (N<=1 == off).  Raises
    ``ValueError`` on anything else — a typo'd batch policy must fail
    the server start, not silently serialize."""
    if value is None:
        return "off", 1
    v = str(value).strip().lower()
    if v in ("off", "0", ""):
        return "off", 1
    if v == "auto":
        return "auto", max(2, _env_int("S2C_BATCH_AUTO_JOBS",
                                       DEFAULT_AUTO_JOBS))
    try:
        n = int(v)
    except ValueError:
        raise ValueError(
            f"--batch {value!r}: use 'off', 'auto', or a job count")
    if n < 0:
        raise ValueError(f"--batch {value!r}: job count must be >= 0")
    return ("off", 1) if n <= 1 else ("fixed", n)


@dataclass
class Batch:
    """One composed batch: plan indices + why it flushed when it did."""

    indices: List[int] = field(default_factory=list)
    flush_reason: str = "drained"
    combined_len: int = 0


@dataclass
class _Member:
    """One member's execution state through the packed phases."""

    index: int
    entry: dict
    robs: object = None
    res: object = None
    layout: object = None
    contigs: object = None
    encoder: object = None
    batches: list = field(default_factory=list)
    cfg: object = None
    t0: float = 0.0
    failed: bool = False
    error: object = None
    pm: object = None           # this member's PackedMember slot
    ordinal: int = 0            # position within the batch's members
    #: decode-phase counter snapshot (phase/decode_sec, ingest/*,
    #: quarantine/*) — restored into rebuilt instruments when a
    #: shared-tail render fallback discards the originals
    decode_counters: dict = field(default_factory=dict)


class BatchScheduler:
    """Composes and executes packed batches for a ServeRunner."""

    def __init__(self, runner, batch="off", window_ms: Optional[float] = None):
        self.runner = runner
        self.mode, self.max_jobs = parse_batch_mode(batch)
        self.window_ms = DEFAULT_WINDOW_MS if window_ms is None \
            else float(window_ms)
        self.max_member_len = _env_int("S2C_BATCH_MAX_MEMBER_LEN",
                                       DEFAULT_MAX_MEMBER_LEN)
        self.max_combined_len = _env_int("S2C_BATCH_MAX_LEN",
                                         DEFAULT_MAX_COMBINED_LEN)
        self.batches_run = 0
        #: self-calibrating prediction rate (shared-phase seconds per
        #: input byte, EMA over finished batches) — the serve_batch
        #: ledger decision predicts from it; None until the first batch
        #: (which additionally bills the first-compile term)
        self._rate: Optional[float] = None
        #: shared-reference layout dedup (serve/packing.PanelGeometry):
        #: (header fingerprint, panel_len) -> the ONE canonical offset
        #: table a same-panel cohort reuses across every wave.  The
        #: ``batch/panel_plans`` / ``batch/panel_reuses`` counters are
        #: the cohort bench's zero-re-plans evidence.
        self._panel_geoms: Dict[Tuple[str, int],
                                packing.PanelGeometry] = {}
        #: cohort prefetch hand-off (serve/cohort.py): filename ->
        #: probe fields (total_len/handle/bytes/fingerprint) computed
        #: on the prefetch thread while the PREVIOUS wave dispatches —
        #: ``_probe_total_len`` consumes an entry instead of re-opening
        #: and re-sniffing the container on the critical path.
        self.probe_cache: Dict[str, dict] = {}

    @property
    def enabled(self) -> bool:
        return self.mode != "off"

    # -- eligibility -------------------------------------------------------
    def _probe_total_len(self, entry: dict) -> Optional[int]:
        """The job's genome length from its header (cached on the
        entry); None = unreadable here, which just means "not packable"
        — the serial path will surface the real error properly.  The
        OPEN handle is kept on the entry (``batch_handle``): each
        member's header parses exactly once — the decode phase resumes
        from it instead of re-opening and re-sniffing the container."""
        if "batch_total_len" in entry:
            return entry["batch_total_len"]
        pre = self.probe_cache.pop(entry["spec"].filename, None) \
            if self.probe_cache else None
        if pre is not None:
            entry.update(pre)
            return entry["batch_total_len"]
        total = None
        try:
            from ..config import resolve_decode_threads
            from ..encoder.events import GenomeLayout
            from ..formats import open_alignment_input

            ai = open_alignment_input(
                entry["spec"].filename,
                getattr(entry["cfg"], "input_format", "auto"),
                binary=True,
                threads=resolve_decode_threads(entry["cfg"]))
            total = GenomeLayout(ai.contigs).total_len
            entry["batch_handle"] = ai
            # the fingerprint is free here (the contigs are parsed) and
            # is what lets run_batch reuse a same-panel offset table
            entry["batch_ref_fp"] = packing.reference_fingerprint(
                ai.contigs)
            try:
                entry["batch_bytes"] = os.path.getsize(
                    entry["spec"].filename)
            except OSError:
                pass
        except Exception:
            total = None
        entry["batch_total_len"] = total
        return total

    def release_handles(self, plan: List[dict]) -> None:
        """Close probe handles whose entries did not end up packed
        (runner calls this after composition so demoted/ineligible
        entries never leak an open file)."""
        for entry in plan:
            ai = entry.pop("batch_handle", None)
            if ai is not None:
                ai.close()

    def eligible(self, entry: dict) -> bool:
        """Static (config-level) packability; size is checked during
        composition so the header probe runs once per candidate."""
        if entry["action"] != "run":
            return False
        cfg = entry["cfg"]
        if getattr(cfg, "pileup", "auto") not in ("auto", "scatter"):
            return False
        if getattr(cfg, "paranoid", False):
            return False
        if getattr(cfg, "incremental", False):
            return False        # incremental consensus (count cache):
            # the job's accumulator seeds from warm per-reference
            # state — packing it into a shared tensor would merge
            # co-tenants' counts into its combined output
        if getattr(cfg, "checkpoint_dir", None) and self.runner.journal \
                is None:
            return False            # explicit checkpoint job (serve
            # rejects these anyway; journal-injected homes are fine —
            # packed members replay whole)
        tenant = entry["spec"].tenant
        if tenant and self.runner.admission.pin_rung(tenant) is not None:
            return False            # pinned = off the device path
        return True

    def _burning(self, tenant: str) -> bool:
        # windowed read when the runner attached a burn monitor: a
        # tenant whose last breach aged out of the slow window stops
        # pre-empting batch fills (the lifetime dict would flush-on-
        # burn forever after a single historical breach)
        adm = self.runner.admission
        slo_burn = getattr(adm, "slo_burn", None)
        burn = slo_burn() if callable(slo_burn) \
            else getattr(adm, "slo_burn_by_tenant", {})
        return bool(burn.get(tenant or "", 0))

    def compose(self, plan: List[dict],
                arrivals: Optional[List[float]] = None) -> List[Batch]:
        """Group the plan's eligible entries into batches, in order.

        ``arrivals`` (one monotonic timestamp per plan entry) models a
        live queue: an entry may join the filling batch only when it
        arrived within ``window_ms`` of the batch's first member —
        later arrivals start the next batch.  A pre-planned
        ``submit_jobs`` queue passes None (everything arrived "now").

        A batch flushes (``flush_reason``) when it is ``full`` (max
        jobs), ``len_cap`` (combined genome cap), ``window`` (an
        arrival fell outside the window), ``slo_burn`` (a member's
        tenant is burning its SLO objective — latency beats occupancy:
        the batch ships NOW rather than waiting to fill), or
        ``drained`` (no more eligible entries).  Single-member batches
        are dropped — the serial path IS a batch of one.
        """
        out: List[Batch] = []
        cur = Batch()
        cur_t0: Optional[float] = None

        def flush(reason: str) -> None:
            nonlocal cur, cur_t0
            if cur.indices:
                cur.flush_reason = reason
                out.append(cur)
            cur = Batch()
            cur_t0 = None

        for i, entry in enumerate(plan):
            if not self.eligible(entry):
                continue
            total = self._probe_total_len(entry)
            if total is None or total <= 0 \
                    or total > self.max_member_len:
                continue
            t = arrivals[i] if arrivals is not None else 0.0
            if cur.indices and arrivals is not None \
                    and (t - cur_t0) * 1e3 > self.window_ms:
                flush("window")
            if cur.indices \
                    and cur.combined_len + total > self.max_combined_len:
                flush("len_cap")
            if not cur.indices:
                cur_t0 = t
            cur.indices.append(i)
            cur.combined_len += total
            if self._burning(entry["spec"].tenant):
                # latency for the burning tenant: ship the batch as-is,
                # never hold its job hostage to occupancy or the window
                flush("slo_burn")
            elif len(cur.indices) >= self.max_jobs:
                flush("full")
        flush("drained")
        return [b for b in out if len(b.indices) >= 2]

    # -- execution ---------------------------------------------------------
    def run_batch(self, batch: Batch, plan: List[dict], window_t0: float
                  ) -> Tuple[Dict[int, object], List[int]]:
        """Execute one composed batch.

        Returns ``(finished, leftovers)``: ``finished`` maps plan index
        -> finalized JobResult (success, per-member failure, or
        decode-time failure); ``leftovers`` are indices that must
        re-run through the serial path because the packed phases
        demoted (``serve/batch_demotions``).  The runner's loop treats
        leftovers exactly like never-batched entries."""
        runner = self.runner
        finished: Dict[int, object] = {}
        members: List[_Member] = []
        for i in batch.indices:
            entry = plan[i]
            tenant = entry["spec"].tenant
            if tenant and runner.admission.pin_rung(tenant) is not None:
                # the tenant was degraded AFTER composition (an earlier
                # job of this very queue): honor the pin — serial path
                return self._demote_all(members, finished,
                                        batch.indices, "tenant_pinned")
            members.append(_Member(index=i, entry=entry))
        t_batch0 = time.perf_counter()
        queue_wait = max(0.0, t_batch0 - window_t0)
        first_batch = self.batches_run == 0
        bid = f"batch{self.batches_run}"
        runner.health.job_started(
            f"{bid}[{len(members)}:"
            f"{os.path.basename(members[0].entry['spec'].filename)}+]")
        for m in members:
            runner._journal_append(
                "started", job=m.entry["job_id"], key=m.entry["key"],
                ckpt="", packed=bid)
            # flight recorder: the journal-measured queue wait counts
            # to HERE (batch members start together)
            m.entry["started_unix"] = round(time.time(), 3)
        # admitted accounting happens where a job actually executes:
        # the serial loop counts its own entries, so packed members
        # count here (and are un-counted on a demotion hand-back — the
        # serial path will re-count them)
        runner.registry.add("serve/admission_admitted", len(members))

        # -- phases 1-3: decode ∥ pack ∥ dispatch, overlapped in waves.
        #    The pack plan's offset table comes from the compose-time
        #    header probes, so the shared accumulator exists BEFORE any
        #    member decodes; members decode concurrently on a small
        #    pool (the C text decoder releases the GIL) with their own
        #    instruments thread-bound, and whichever members have
        #    finished get their rows remapped + merged into shared
        #    slabs and dispatched WHILE the rest still decode — the
        #    packed path's own decode/dispatch pipeline, the cross-JOB
        #    analogue of the serial path's prefetcher.  Failure
        #    bookkeeping (journal, admission, fold) is deferred to THIS
        #    thread — those surfaces are not concurrent-safe.
        plan_pk = self._plan_members(members)
        for j, (m, pm) in enumerate(zip(members, plan_pk.members)):
            m.pm = pm
            m.ordinal = j
            m.cfg = dataclasses.replace(m.entry["cfg"],
                                        checkpoint_dir=None)
        batch_robs = obs.prepare_run(config=None)
        dlog: List[Tuple[float, float]] = []
        counts = None
        bytes_total = sum(m.entry.get("batch_bytes") or 0
                          for m in members)
        predicted_wall = self._predict_wall(len(members), bytes_total,
                                            self._accum_host_rung())
        spec0 = getattr(members[0].cfg, "fault_inject", "") or None
        workers = max(1, min(len(members),
                             _env_int("S2C_BATCH_DECODE_WORKERS",
                                      os.cpu_count() or 1)))
        try:
            import jax

            from ..ops.pileup import (HostPileupAccumulator,
                                      PileupAccumulator)

            # the shared accumulator follows the SAME placement gate
            # the backend's --pileup auto consults: on a link-free
            # default backend ("device" shares host memory) the native
            # host accumulate runs at memory speed where the XLA-CPU
            # scatter pays ~100 ns/cell, and there is no wire to
            # amortize — so the packed rung routes host there.  A real
            # accelerator keeps the device scatter: merged slabs riding
            # one dispatch sequence IS the point of packing on a link.
            # Byte identity is rung-independent (the repo-wide
            # contract), so this is pure placement policy.
            from .. import native

            self._link_free = jax.default_backend() == "cpu"
            host_rung = self._link_free and native.load() is not None
            with obs.bind_run_to_thread(batch_robs):
                acc = HostPileupAccumulator(plan_pk.total_len) \
                    if host_rung else \
                    PileupAccumulator(plan_pk.total_len,
                                      strategy="scatter")
                batch_robs.registry.gauge("dispatch/pileup").set_info(
                    {"path": "packed_shared",
                     "strategy": "host" if host_rung else "scatter",
                     "total_len": int(plan_pk.total_len)})
            # wave size: how many decoded members accumulate before a
            # merged dispatch.  On a link-free rig the default is the
            # whole batch (XLA/native accumulation already uses every
            # core, so overlapping decode with it just contends); on an
            # accelerator, waves of ~2x the decode workers pipeline
            # member decode under the in-flight device dispatches.
            wave_min = _env_int("S2C_BATCH_WAVE_MIN", 0)
            if wave_min <= 0:
                wave_min = len(members) if self._link_free \
                    else max(2, workers)
            if workers > 1:
                from concurrent.futures import (FIRST_COMPLETED,
                                                ThreadPoolExecutor)
                from concurrent.futures import wait as _fwait

                with ThreadPoolExecutor(
                        max_workers=workers,
                        thread_name_prefix="serve-batch-decode") as ex:
                    futs = {ex.submit(self._decode_member, m): m
                            for m in members}
                    pending: List[_Member] = []
                    while futs:
                        done, _ = _fwait(set(futs),
                                         return_when=FIRST_COMPLETED)
                        pending.extend(futs.pop(f) for f in done)
                        if len(pending) >= wave_min or not futs:
                            self._dispatch_wave(pending, plan_pk, acc,
                                                batch_robs, dlog,
                                                spec0)
                            pending = []
            else:
                for m in members:
                    self._decode_member(m)
                self._dispatch_wave(members, plan_pk, acc, batch_robs,
                                    dlog, spec0)
            # ONE combined host fetch for the whole batch
            with obs.bind_run_to_thread(batch_robs):
                counts = acc.counts_host()
        except BaseException as exc:
            # the count-bank rule: a dispatch that did not complete
            # merges nothing — discard the shared tensor, demote every
            # live member to the serial path untouched
            logger.warning(
                "%s: packed dispatch failed (%s: %s) — demoting "
                "member(s) to the serial path", bid,
                type(exc).__name__, exc)
            runner.registry.add("batch/demotions", 1)
            runner.registry.gauge("serve/batch").set_info(
                {"batch": bid, "demoted": True,
                 "error": f"{type(exc).__name__}: {exc}"})
            for m in members:
                self._close_member(m)
            runner.health.job_finished()
            # every member (decode-failed ones included — they are not
            # in `finished`) re-runs through the serial loop, which
            # re-counts admission for the entries it executes
            runner.registry.add("serve/admission_admitted",
                                -len(members))
            return finished, [m.index for m in members
                              if not m.failed]
        for m in members:
            if m.failed:
                runner._note_poison(m.entry["spec"], m.error, m.res)
                m.res.error = f"{type(m.error).__name__}: {m.error}"
                runner._finalize_job(m.entry, m.res, m.robs,
                                     m.entry["spec"],
                                     queue_wait=queue_wait,
                                     echo_suffix=" [packed decode]")
        live = [m for m in members if not m.failed]
        if live and any(m.failed for m in members):
            # the failed members' finalize cleared in_flight; the live
            # remainder is still executing
            runner.health.job_started(f"{bid}[{len(live)} live]")
        tap = getattr(runner, "count_tap", None)
        if tap is not None and counts is not None:
            # cohort concordance feed (serve/cohort.py): each live
            # member's private partition sliced from the combined
            # tensor the batch just fetched — zero extra device work.
            # Absorbed on failure: the tap is an observer, never a
            # reason a job fails.
            for m in live:
                try:
                    tap(m.entry["job_id"],
                        packing.extract_member(counts, m.pm))
                except Exception:
                    runner.registry.add("batch/tap_failed", 1)
        total_events = sum(mm.n_events for mm in plan_pk.members) or 1
        dispatch_sec = sum(t1 - t0 for t0, t1 in dlog)
        shared_wall = time.perf_counter() - t_batch0
        self._note_rate(shared_wall, bytes_total, len(members))
        # batch-scope counters -> server aggregate.  The dispatch
        # seconds are share-billed to the members below and reach the
        # aggregate through THEIR folds; zero the batch copy first or
        # the fleet's s2c_phase_seconds_total{phase="pileup_dispatch"}
        # would double-count every packed batch
        batch_robs.registry.add("phase/pileup_dispatch_sec",
                                -dispatch_sec)
        try:
            runner.registry.fold(batch_robs.registry, job_id=bid)
        except Exception:
            runner.registry.add("telemetry/fold_failed", 1)

        # -- server-lifetime batch gauges (the serve/batch family) -----
        n = len(live)
        reg = runner.registry
        reg.add("batch/batches", 1)
        reg.add("batch/packed_jobs", n)
        reg.add("batch/pack_sec", max(0.0, shared_wall - dispatch_sec))
        reg.gauge("batch/size").set(float(n))
        reg.gauge("batch/occupancy_pct").set(
            round(100.0 * plan_pk.occupancy, 2))
        # raw merge accounting: the cohort driver reads real_rows to
        # learn rows-per-member, which its occupancy-aware wave sizing
        # snaps against pow2 pad boundaries (serve/cohort.py size_wave)
        reg.gauge("batch/real_rows").set(float(plan_pk.real_rows))
        reg.gauge("batch/padded_rows").set(float(plan_pk.padded_rows))
        reg.gauge("batch/jobs_per_sec").set(
            round(n / shared_wall, 3) if shared_wall > 0 else 0.0)
        binfo = {"batch": bid, "jobs": n,
                 "flush_reason": batch.flush_reason,
                 "occupancy": round(plan_pk.occupancy, 4),
                 "merged_slabs": plan_pk.merged_slabs,
                 "events": int(total_events),
                 "shared_wall_sec": round(shared_wall, 4),
                 "dispatch_sec": round(dispatch_sec, 4)}
        reg.gauge("serve/batch").set_info(binfo)
        self.batches_run += 1

        # -- phase 4: the tail.  One SHARED tail over the combined
        #    tensor when every member votes under the same knobs
        #    (thresholds + min_depth — the only config the tail math
        #    reads; everything else is encode-time or render-time):
        #    the vote is per-position and insertion sites are keyed
        #    (contig, local), so each member's slice of the combined
        #    outputs is bit-for-bit its own tail's outputs.  Members
        #    with incompatible knobs, or any shared-tail failure, take
        #    the per-member extraction tail (run_from_counts) instead —
        #    same bytes either way, different amortization.
        shared = None
        if len(live) > 1 and counts is not None \
                and self._tail_compatible(live) \
                and os.environ.get("S2C_BATCH_SHARED_TAIL", "1") != "0":
            try:
                shared = self._shared_tail(members, live, plan_pk,
                                           counts, batch_robs)
            except Exception as exc:
                runner.registry.add("batch/tail_demotions", 1)
                logger.warning(
                    "%s: shared tail failed (%s: %s) — per-member "
                    "extraction tails", bid, type(exc).__name__, exc)
        for m in live:
            pm = m.pm
            share = dispatch_sec * (pm.n_events / total_events)

            def bill(m=m, pm=pm, share=share):
                """Member batch accounting into the member's CURRENT
                instruments: the serve/batch counter family (the ledger
                decision's measured join reads them) plus the decision
                itself.  Re-applied when a shared-tail render fallback
                rebuilds the member's instruments."""
                r = m.robs.registry
                r.add("phase/pileup_dispatch_sec", share)
                r.add("serve/batched", 1)
                r.add("serve/batch_jobs", n)
                r.add("serve/batch_wall_sec", shared_wall)
                r.add("serve/batch_share_sec", share)
                r.gauge("serve/batch").set_info(
                    {**binfo, "share_sec": round(share, 4),
                     "events": pm.n_events})
                # rate-card cross-check (observability/ratecard.py):
                # the learned packed-jobs rate rides the inputs as
                # provenance — the scheduler's own shared-phase EMA
                # stays the prediction (it models THIS batch's shape;
                # the card models the fleet-visible average)
                from ..observability import ratecard as _rc

                _jps_rc, _jps_prov = _rc.consult(
                    "packed_jobs_per_sec", n / predicted_wall)
                with obs.bind_run_to_thread(m.robs):
                    obs.record_decision(
                        "serve_batch", str(n),
                        inputs={"mode": self.mode,
                                "flush_reason": batch.flush_reason,
                                "window_ms": self.window_ms,
                                "jobs": n,
                                "occupancy": round(plan_pk.occupancy,
                                                   4),
                                "events": int(total_events),
                                "predicted_jobs_per_sec": round(
                                    n / predicted_wall, 3)},
                        provenance=_jps_prov,
                        predicted={"sec": predicted_wall,
                                   "jobs_per_sec": n / predicted_wall},
                        measured={"sec": {"counters":
                                          ["serve/batch_wall_sec"]},
                                  "jobs_per_sec": {
                                      "num": ["serve/batch_jobs"],
                                      "den": ["serve/batch_wall_sec"]}},
                        # the server's first batch absorbs an
                        # unknowable share of process cold start:
                        # residual recorded, drift never fired on it
                        # (the shard_mode precedent); warm batches are
                        # band-enforced
                        band=0 if first_batch else None)

            bill()
            done_shared = False
            if shared is not None:
                done_shared = self._render_member(m, shared, t_batch0,
                                                  rebill=bill)
            if not done_shared:
                self._tail_member(m,
                                  packing.extract_member(counts, pm),
                                  pm, t_batch0)
            runner._finalize_job(
                m.entry, m.res, m.robs, m.entry["spec"],
                queue_wait=queue_wait,
                echo_suffix=f" [packed x{n}, {bid}]")
            finished[m.index] = m.res
            if m is not live[-1]:
                # _finalize_job cleared in_flight for ITS member; the
                # batch is still executing — re-assert so a tail that
                # wedges mid-batch stays visible to the health
                # snapshot/watchdog gauges (the PR-10 contract)
                runner.health.job_started(f"{bid}[{n - len(finished)}"
                                          f" remaining]")
        for m in members:
            if m.failed and m.index not in finished:
                finished[m.index] = m.res
        runner.health.job_finished()
        return finished, []

    def _plan_members(self, members: List[_Member]) -> packing.PackPlan:
        """Offset-plan a batch, deduplicating shared-reference layouts.

        When every member declares the same header fingerprint (hence
        the same panel length), the batch takes its offsets from the
        cached :class:`~.packing.PanelGeometry` table — planned once
        per (fingerprint, panel_len) and reused verbatim by every
        later same-panel batch/wave.  ``batch/panel_plans`` counts the
        builds and ``batch/panel_reuses`` the table hits: the cohort
        bench's zero-re-plans-after-wave-1 evidence.  Mixed-stranger
        batches keep the per-batch ``plan_pack`` path unchanged."""
        fps = {m.entry.get("batch_ref_fp") for m in members}
        lens = {m.entry["batch_total_len"] for m in members}
        if len(fps) == 1 and None not in fps and len(lens) == 1:
            key = (next(iter(fps)), int(next(iter(lens))))
            geom = self._panel_geoms.get(key)
            if geom is None or geom.max_jobs < len(members):
                geom = packing.PanelGeometry(
                    fingerprint=key[0], panel_len=key[1],
                    max_jobs=max(len(members), self.max_jobs))
                self._panel_geoms[key] = geom
                self.runner.registry.add("batch/panel_plans", 1)
            else:
                self.runner.registry.add("batch/panel_reuses", 1)
            return geom.plan_wave([m.entry["job_id"] for m in members])
        return packing.plan_pack(
            [(m.entry["job_id"], m.entry["batch_total_len"])
             for m in members])

    # -- phases ------------------------------------------------------------
    def _decode_member(self, m: _Member) -> None:
        """Decode one member fully (bounded: members passed the size
        gate), instruments thread-bound so phase seconds, quarantine
        counters and strict errors all land in the member's own job.
        ``m.cfg`` was prepared by the caller with ``checkpoint_dir``
        stripped — packed members replay whole on a crash: the
        journal-injected per-job checkpoint home stays empty (serial
        decode with stream-consistent snapshots is the checkpoint
        contract, and the members are small by the eligibility gate)."""
        from ..config import resolve_decode_threads
        from ..encoder.events import GenomeLayout
        from ..formats import open_alignment_input
        from ..ingest.badrecords import (BadRecordBudgetExceeded,
                                         abort_bookkeeping)
        from .runner import JobResult

        runner = self.runner
        entry = m.entry
        spec = entry["spec"]
        m.robs = obs.prepare_run(
            trace_out=runner._job_out(m.cfg.trace_out, "S2C_TRACE_OUT",
                                      entry["jobnum"]),
            metrics_out=runner._job_out(m.cfg.metrics_out,
                                        "S2C_METRICS_OUT",
                                        entry["jobnum"]),
            config=m.cfg)
        runner._stamp_trace(m.robs, entry)
        m.res = JobResult(job_id=entry["job_id"], filename=spec.filename,
                          index=m.index, admission=entry["admission"])
        m.t0 = time.perf_counter()
        handle = None
        with obs.bind_run_to_thread(m.robs):
            stele.set_log_context(job_id=entry["job_id"],
                                  tenant=spec.tenant, rung="packed")
            reg = obs.metrics()
            tr = obs.tracer()
            try:
                # the compose probe already opened + header-parsed this
                # input; resume from that handle instead of re-opening
                handle = entry.pop("batch_handle", None)
                if handle is None:
                    handle = open_alignment_input(
                        spec.filename,
                        getattr(m.cfg, "input_format", "auto"),
                        binary=True,
                        threads=resolve_decode_threads(m.cfg))
                m.contigs = handle.contigs
                m.layout = GenomeLayout(m.contigs)
                encoder, gen = runner.backend._make_encoder(
                    m.layout, handle.stream, m.cfg, None)
                m.encoder = encoder
                # decode clock starts AFTER open/encoder construction,
                # mirroring the serial path's _timed_iter discipline —
                # one-time costs (native library load, pool spin-up)
                # must not pollute the decode_threads ledger join
                td = time.perf_counter()
                with tr.span("decode"):
                    for batch in gen:
                        m.batches.append(batch)
                reg.add("phase/decode_sec", time.perf_counter() - td)
                rec = obs.ledger().get("decode_threads")
                if rec is not None:
                    # pool-concurrent member decode: the wall includes
                    # co-members' core contention, which the single-job
                    # thread model does not price — keep the residual
                    # in the manifest, never fire drift on it (band=0,
                    # the shard_mode precedent)
                    rec.band = 0
                bad_sink = getattr(encoder, "bad_sink", None)
                if bad_sink is not None:
                    total = int(getattr(handle.stream, "n_lines", 0) or 0)
                    if total <= 0:
                        total = encoder.n_reads + encoder.n_skipped
                    bad_sink.finish(total)
                    bad_sink.publish(reg)
                m.decode_counters = dict(
                    m.robs.registry.snapshot()["counters"])
            except BaseException as exc:
                if isinstance(exc, BadRecordBudgetExceeded):
                    abort_bookkeeping(exc, reg)
                m.failed = True
                m.error = exc       # finalized on the batch thread —
                # journal/admission/fold are not concurrent-safe
                m.res.elapsed_sec = time.perf_counter() - m.t0
            finally:
                if handle is not None:
                    handle.close()
                stele.set_log_context()

    def _accum_host_rung(self) -> bool:
        """True when the shared accumulation will route host-side (the
        link-free placement gate — see run_batch): no XLA compile to
        bill then, and nothing device-shaped in the prediction."""
        try:
            import jax

            from .. import native

            return jax.default_backend() == "cpu" \
                and native.load() is not None
        except Exception:
            return False

    def _predict_wall(self, n_members: int, bytes_total: int,
                      host_rung: bool) -> float:
        """The shared-phase wall the ledger decision predicts, at the
        moment the POLICY decides to pack: per-member fixed overhead +
        input bytes at the scheduler's self-calibrating rate (EMA over
        previous WARM batches' measured shared wall per byte, seeded by
        S2C_BATCH_SEC_PER_MB — the committed cpu-fallback artifact's
        rig measures ~0.1 s/MB; accelerator rigs tune via env).  The
        server's FIRST batch additionally bills a cold-start term
        (S2C_BATCH_COMPILE_SEC: first jit compiles on the device rung,
        native-library/first-touch warmup on the host rung) — and is
        recorded band=0 (informational), because how much of the
        process's cold start lands in it depends on what ran before."""
        fixed = float(os.environ.get("S2C_BATCH_MEMBER_SEC", "0.002"))
        seed_rate = float(os.environ.get("S2C_BATCH_SEC_PER_MB",
                                         "0.1")) / 1e6
        compile_sec = float(os.environ.get("S2C_BATCH_COMPILE_SEC",
                                           "0.5"))
        rate = self._rate if self._rate is not None else seed_rate
        pred = n_members * fixed + max(1, bytes_total) * rate
        if self.batches_run == 0:
            pred += compile_sec
        return pred

    def _note_rate(self, shared_wall: float, bytes_total: int,
                   n_members: int) -> None:
        """Fold one WARM batch's measured shared wall into the
        prediction rate.  The server's first batch is never folded —
        its wall carries an unknowable share of process cold start
        (first compiles, library loads, page cache), and seeding the
        EMA with it mis-prices every batch that follows.  The
        observation subtracts the per-member fixed term the prediction
        adds back, so the model cannot double-count it."""
        if self.batches_run == 0:
            return
        fixed = float(os.environ.get("S2C_BATCH_MEMBER_SEC", "0.002"))
        wall = shared_wall - n_members * fixed
        obs_rate = max(1e-12, wall) / max(1, bytes_total)
        self._rate = obs_rate if self._rate is None \
            else 0.6 * self._rate + 0.4 * obs_rate

    def _dispatch_wave(self, wave: List[_Member],
                       plan_pk: packing.PackPlan, acc, batch_robs,
                       dlog: List[Tuple[float, float]],
                       fault_spec) -> None:
        """Merge + dispatch the rows of whichever members just finished
        decoding — runs on the batch thread while other members still
        decode on the pool.  Dispatch cost lands in the batch-scope
        registry (folded into the server aggregate at batch end) and is
        share-billed to members by event count afterwards.  Any failure
        propagates to the caller's demotion path — nothing partial is
        ever handed to a member."""
        from ..resilience import faultinject

        runner = self.runner
        pairs = []
        for m in wave:
            if m.failed:
                continue
            if m.layout.total_len != m.pm.total_len:
                # the input's header changed between the compose probe
                # and the decode: this member's offsets are wrong — it
                # fails alone, its rows never reach the shared tensor
                m.failed = True
                m.error = RuntimeError(
                    "reference layout changed between admission and "
                    f"decode ({m.pm.total_len} -> "
                    f"{m.layout.total_len} positions)")
                continue
            pairs.append((m.pm, m.batches))
        if not pairs:
            return
        from ..ops.pileup import HostPileupAccumulator

        host_rung = isinstance(acc, HostPileupAccumulator)
        with obs.bind_run_to_thread(batch_robs):
            faultinject.configure(fault_spec)
            try:
                tr = obs.tracer()
                reg = obs.metrics()
                merged = packing.merge_batches(plan_pk, pairs)
                # residency: the combined tensors pin every member's
                # rows until the wave dispatches
                # (observability/memplane.py packed_batch family)
                from ..observability import memplane

                for mb in merged:
                    memplane.track_obj("packed_batch", mb,
                                       memplane.batch_nbytes(mb))
                for m in wave:
                    m.batches = []          # rows now live in the slabs
                for mb in merged:
                    ta = time.perf_counter()
                    with tr.span("pileup_dispatch",
                                 n_events=mb.n_events):
                        if host_rung:
                            # the device accumulator checks this site
                            # itself; the host rung must stay
                            # injectable too (the demote-on-fault
                            # contract is rung-independent)
                            faultinject.fault_check("pileup_dispatch")
                        acc.add(mb)
                    tb = time.perf_counter()
                    reg.add("phase/pileup_dispatch_sec", tb - ta)
                    dlog.append((ta, tb))
                    runner.health.beat()
                    runner.telemetry_tick()
            finally:
                faultinject.configure("")

    @staticmethod
    def _tail_compatible(live: List[_Member]) -> bool:
        """True when every member's tail math reads the same knobs.
        ``thresholds`` and ``min_depth`` enter the vote, and ``fill``
        now enters the TAIL too (the device-resident epilogue
        substitutes the fill byte inside the vote's emit select —
        backends/jax_backend.py); maxdel / strict / py2-compat act at
        encode time (already per-member) and prefix / nchar at render
        time (per-member too).  Members with a different fill take the
        per-member extraction tail — same bytes, less amortization."""
        key = (tuple(live[0].cfg.thresholds), live[0].cfg.min_depth,
               live[0].cfg.fill)
        return all((tuple(m.cfg.thresholds), m.cfg.min_depth,
                    m.cfg.fill) == key for m in live)

    def _shared_tail(self, members: List[_Member], live: List[_Member],
                     plan_pk: packing.PackPlan, counts: np.ndarray,
                     batch_robs) -> dict:
        """ONE post-accumulation tail over the whole packed batch.

        Builds a combined layout (member contigs under collision-proof
        ``b<k>::`` names — serving queues routinely carry the same
        reference in every job; a failed member's window keeps a
        placeholder contig so the offset table stays exactly the pack
        plan's), merges the members' insertion events with contig ids
        rebased into the combined index space, and runs the backend's
        ordinary ``_tail`` over the combined counts under the members'
        (shared) vote knobs.  Returns the combined outputs plus the
        per-ordinal contig bases ``base_ci`` the slicer uses.  Exact by
        construction: the vote is per-position, site keys are (contig,
        local), and per-contig sums follow contig boundaries — nothing
        in the tail mixes positions across member windows."""
        from ..backends.base import BackendStats
        from ..encoder.events import GenomeLayout, InsertionEvents
        from ..io.sam import Contig
        from ..ops.pileup import HostPileupAccumulator
        from ..resilience.policy import RetryPolicy

        comb_contigs: List[Contig] = []
        base_ci = [0]
        ins_comb = InsertionEvents()
        for k, m in enumerate(members):
            bias = base_ci[-1]
            if m.failed or m.layout is None:
                # zero-count placeholder window: pruned at render, but
                # it keeps every later member's offset/contig base true
                comb_contigs.append(Contig(name=f"b{k}::__failed__",
                                           length=int(m.pm.total_len)))
                base_ci.append(bias + 1)
                continue
            for name, length in zip(m.layout.names, m.layout.lengths):
                comb_contigs.append(Contig(name=f"b{k}::{name}",
                                           length=int(length)))
            base_ci.append(bias + len(m.layout.names))
            ev = m.encoder.insertions
            if len(ev):
                ins_comb.contig_ids.extend(c + bias
                                           for c in ev.contig_ids)
                ins_comb.local_pos.extend(ev.local_pos)
                ins_comb.motifs.extend(ev.motifs)
                for c, loc, ml, ch in ev.array_chunks:
                    ins_comb.array_chunks.append((c + bias, loc, ml, ch))
        comb_layout = GenomeLayout(comb_contigs)
        if comb_layout.total_len != plan_pk.total_len:
            raise RuntimeError(
                "combined layout length diverged from the pack plan "
                f"({comb_layout.total_len} != {plan_pk.total_len})")
        acc = HostPileupAccumulator(comb_layout.total_len)
        acc.set_counts(counts)

        class _Carrier:
            pass

        carrier = _Carrier()
        carrier.insertions = ins_comb
        stats = BackendStats()
        stats.aligned_bases = sum(m.n_events for m in plan_pk.members)
        cfg0 = live[0].cfg
        backend = self.runner.backend
        policy = RetryPolicy.from_config(cfg0)
        t0 = time.perf_counter()
        with obs.bind_run_to_thread(batch_robs):
            (syms, ins_syms, contig_sums, site_cov, ins, _out,
             _link_free, dash_counts) = policy.run(
                lambda: backend._tail(acc, cfg0, comb_layout, carrier,
                                      stats, use_sharded=False),
                site="tail")
        return {
            "syms": np.asarray(syms),
            "ins_syms": None if ins_syms is None else
            np.asarray(ins_syms),
            "contig_sums": np.asarray(contig_sums),
            "site_cov": None if site_cov is None else
            np.asarray(site_cov),
            "ins": ins,
            # device-resident epilogue: per-(T, comb-contig) dash
            # totals slice per member exactly like contig_sums
            "dash_counts": None if dash_counts is None else
            np.asarray(dash_counts),
            "base_ci": base_ci,
            "total_len": comb_layout.total_len,
            "tail_sec": time.perf_counter() - t0,
        }

    def _render_member(self, m: _Member, shared: dict,
                       t_batch0: float, rebill=None) -> bool:
        """Render one member from its slice of the shared tail outputs;
        returns False (caller falls back to the extraction tail) when
        the render fails for a reason worth retrying per-member."""
        runner = self.runner
        pm = m.pm
        off = pm.offset
        L = m.layout.total_len
        lo_ci = shared["base_ci"][m.ordinal]
        hi_ci = shared["base_ci"][m.ordinal + 1]
        syms_k = shared["syms"][:, off:off + L]
        contig_sums_k = shared["contig_sums"][lo_ci:hi_ci]
        dash_k = None if shared.get("dash_counts") is None \
            else shared["dash_counts"][:, lo_ci:hi_ci]
        ins = shared["ins"]
        ins_k = ins_syms_k = site_cov_k = None
        if ins is not None:
            kc = ins["key_contig"]
            lo = int(np.searchsorted(kc, lo_ci))
            hi = int(np.searchsorted(kc, hi_ci))
            if lo != hi:
                # key_contig is sorted by construction
                # (group_insertions), so a member's sites are one
                # contiguous row range; rebase contig ids into the
                # member's own index space
                ins_k = {"key_contig": (kc[lo:hi] - lo_ci),
                         "key_local": ins["key_local"][lo:hi]}
                ins_syms_k = shared["ins_syms"][:, lo:hi, :]
                site_cov_k = shared["site_cov"][lo:hi]
        # the member's share of the shared tail, into ITS vote phase
        m.robs.registry.add(
            "phase/vote_sec", shared["tail_sec"]
            * (L / max(1, shared["total_len"])))
        stele.set_log_context(job_id=m.entry["job_id"],
                              tenant=m.entry["spec"].tenant,
                              rung="packed")
        runner.backend.serve_prepared_obs = m.robs
        try:
            out = runner.backend.assemble_partition(
                m.contigs, m.cfg, syms_k, contig_sums_k, ins_k,
                ins_syms_k, site_cov_k,
                n_reads=m.encoder.n_reads,
                n_skipped=m.encoder.n_skipped,
                aligned_bases=pm.n_events,
                dash_counts=dash_k)
        except Exception as exc:
            runner.backend.serve_prepared_obs = None
            logger.warning("packed job %s: shared-tail render failed "
                           "(%s: %s) — extraction tail",
                           m.entry["job_id"], type(exc).__name__, exc)
            # the member's instruments were consumed by the failed
            # render run: rebuild them on the SAME export paths (the
            # fallback's finish_run overwrites the failed attempt's
            # files — no concurrent writer here, unlike the watchdog
            # retry), restore the decode-phase counters the job
            # already earned, and re-apply the batch accounting
            old = m.robs
            m.robs = obs.prepare_run(trace_out=old.trace_out,
                                     metrics_out=old.metrics_out,
                                     config=m.cfg)
            for key, val in m.decode_counters.items():
                m.robs.registry.add(key, val)
            if rebill is not None:
                rebill()
            return False
        finally:
            stele.set_log_context()
        m.res.fastas, m.res.stats = out.fastas, out.stats
        m.res.error = None
        m.res.elapsed_sec = time.perf_counter() - t_batch0
        return True

    def _tail_member(self, m: _Member, part: np.ndarray,
                     pm: packing.PackedMember, t_batch0: float) -> None:
        """One member's extraction tail: the cold-run tail/render over
        its private count partition, journaled/finalized by the caller."""
        runner = self.runner
        stele.set_log_context(job_id=m.entry["job_id"],
                              tenant=m.entry["spec"].tenant,
                              rung="packed")
        runner.backend.serve_prepared_obs = m.robs
        try:
            out = runner.backend.run_from_counts(
                m.contigs, m.cfg, part, m.encoder.insertions,
                n_reads=m.encoder.n_reads,
                n_skipped=m.encoder.n_skipped,
                aligned_bases=pm.n_events)
        except Exception as exc:
            runner._note_poison(m.entry["spec"], exc, m.res)
            m.res.error = f"{type(exc).__name__}: {exc}"
            logger.warning("packed job %s failed: %s",
                           m.entry["job_id"], m.res.error)
        else:
            m.res.fastas, m.res.stats = out.fastas, out.stats
            m.res.error = None
        finally:
            runner.backend.serve_prepared_obs = None
            stele.set_log_context()
        m.res.elapsed_sec = time.perf_counter() - t_batch0

    # -- helpers -----------------------------------------------------------
    def _close_member(self, m: _Member) -> None:
        m.batches = []
        m.encoder = None

    def _demote_all(self, members: List[_Member], finished: dict,
                    indices: List[int], reason: str):
        """Pre-execution demotion (nothing started yet): hand every
        index back to the serial path."""
        self.runner.registry.add("batch/demotions", 1)
        logger.info("batch demoted before dispatch (%s)", reason)
        done = {m.index for m in members if m.failed}
        return finished, [i for i in indices if i not in done]
