"""Crash-safe job journal: a serve queue that survives ``kill -9``.

The PR-5 runner kept the queue in process memory — a crash mid-queue
lost every pending job and forgot which jobs already ran, so a naive
re-launch either dropped work or ran it twice.  The journal makes the
queue durable with the cheapest discipline that is actually
crash-safe on POSIX: an append-only sequence of single-event JSON
SEGMENTS, each written to a temp file, fsynced, and PUBLISHED with
``os.link`` — an O_EXCL-style rename that FAILS when the target
sequence number is already taken, which is what makes the journal safe
for MULTIPLE writer processes (the fleet, below): two workers racing
for segment N cannot tear or overwrite each other; exactly one wins N,
the loser re-scans and takes N+1.  A ``kill -9`` at any instant leaves
only whole events behind — there is no shared append file whose torn
last line needs heuristic repair, and replay order is the segment
sequence number, not mtime.

Event vocabulary (one JSON object per segment)::

    submitted     {job, key, filename, seq}
    started       {job, key, ckpt[, worker, tenant]}
    committed     {job, key, outputs: {path: fingerprint}, elapsed_sec
                   [, worker, tenant]}
    failed        {job, key, error}
    rejected      {job, key, reason}       # admission control audit
    resumed       {job, key, mode}         # restart bookkeeping (audit)
    claimed       {job, key, worker, expires_unix}   # fleet: lease open
    lease_renewed {key, worker, expires_unix}        # fleet: TTL push
    lease_expired {key, worker, reaper}              # fleet: lease reap
    session_open  {key, tenant, header_sha, refs}    # stream: session born
    wave_received {key, wave, sha, reads, bytes}     # stream: durable intent
    wave_absorbed {key, wave, sha, reads_total, digest
                   [, worker, claim_seq]}            # stream: counted once
    wave_rejected {key, wave, reason}                # stream: DATA-class audit
    session_stable{key, wave, digest, waves_stable}  # stream: read-until
    session_closed{key, worker, outputs, digest}     # stream: terminal

A job's IDENTITY (``key``) hashes its input path plus every config
field that changes the output bytes — so a restarted server given the
same queue recognizes its jobs even though Python object identity is
gone, while a changed threshold/outfolder reads as a different job.

Replay semantics (:meth:`JobJournal.replay`):

* a key whose last lifecycle event is ``committed`` AND whose recorded
  output files still match their fingerprints is SKIPPED on restart
  (zero duplicated jobs — the fingerprint is the audit, not trust);
* a key with ``started`` but no terminal event was IN FLIGHT when the
  process died: it re-runs, resuming from its per-job checkpoint dir
  (the PR-2 emergency/periodic checkpoints) when one survived;
* everything else re-runs from scratch (zero lost jobs).

Claim/lease semantics (serve/fleet.py drives these; replay just keeps
the state machine):

* the FIRST ``claimed`` event for a key — in segment order, which the
  O_EXCL publication makes a total order — opens that key's lease;
  later ``claimed`` events while a lease is open are LOSING claims and
  are ignored (the loser observes this on replay and moves on);
* ``lease_renewed`` by the holding worker pushes ``expires_unix``;
* ``lease_expired`` (appended by a REAPER that observed the wall-clock
  expiry) closes the lease, so the next ``claimed`` can win — this is
  how a SIGKILL'd or frozen worker's in-flight job gets re-claimed;
* ``committed``/``failed`` close the lease terminally.

Streaming-session semantics (serve/session.py drives these; the
journal is again just the durable state machine):

* a SESSION is a journal entity whose key is its session id; it reuses
  the claim/lease trio above unchanged (the lease code is key-generic),
  so a SIGKILL'd worker's open session is reaped and stolen exactly
  like an in-flight job;
* ``wave_received`` is the durable INTENT — appended before any ingest
  work, carrying the wave body's sha256, so a steal replays exactly the
  waves whose intent exists but whose ``wave_absorbed`` does not;
* ``wave_absorbed`` is the exactly-once COMMIT of one wave into the
  session's count tensors.  It is lease-FENCED like ``committed``: once
  the session key has ever been claimed, an absorb not matching the
  open lease's (worker, claim_seq) lineage is VOID on replay — a zombie
  mid-wave when its lease was stolen cannot double-count the wave;
* ``wave_rejected`` audits a DATA-class wave (malformed body, torn
  spool detected by sha mismatch) — never absorbed, never retried;
* ``session_stable`` records the read-until verdict (consensus digest
  unchanged for N consecutive waves); ``session_closed`` is terminal
  and closes the lease like ``committed``.

Replay cursor/compaction: every ``checkpoint_every`` appends the
journal writes a ``checkpoint-NNNNNNNN.json`` summary segment — the
full :class:`ReplayState` as of segment N, built from a fresh disk
replay (never from a possibly-stale in-memory mirror).  ``replay()``
loads the newest readable checkpoint and applies only the segments
past it, so a long-lived fleet journal replays O(tail), not
O(lifetime); ``replay(full=True)`` ignores checkpoints (the audit path
that proves compacted replay == full replay), and :meth:`prune`
deletes the segments a checkpoint already covers.

The ``journal_write`` fault-injection site fires on every segment
append (resilience/faultinject.py; the serve runner checks it against
its queue-lifetime injector).  An append failure is surfaced to the
caller — the runner decides the policy (a failed COMMIT append leaves
the job to be re-verified-by-fingerprint on the next restart, which is
the safe direction: re-checking work is cheap, losing it is not).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger("sam2consensus_tpu.serve.journal")

SCHEMA = "s2c-journal/1"
CKPT_SCHEMA = "s2c-journal-checkpoint/1"

#: fields of RunConfig that change the OUTPUT BYTES of a job — the job
#: key hashes exactly these, so a re-queued job with a different
#: threshold/outfolder is a different job, while backend-side knobs
#: (pileup strategy, wire codec, retries) keep the same identity: they
#: must produce byte-identical outputs anyway
KEY_FIELDS = ("thresholds", "min_depth", "fill", "maxdel", "prefix",
              "nchar", "outfolder", "py2_compat", "strict")

#: lifecycle events; ``rejected``/``resumed`` are audit-only, the
#: ``claimed``/``lease_*`` trio is the fleet's work-stealing layer,
#: and the ``session_*``/``wave_*`` family is the streaming-session
#: materialized view (serve/session.py)
EVENTS = ("submitted", "started", "committed", "failed", "rejected",
          "resumed", "claimed", "lease_renewed", "lease_expired",
          "session_open", "wave_received", "wave_absorbed",
          "wave_rejected", "session_stable", "session_closed",
          "cohort_wave")
#: ``cohort_wave`` (serve/cohort.py) marks one manifest wave fully
#: finalized — the cohort driver's resume position.  Replay ignores it
#: for job state (member jobs carry their own per-job lifecycles; the
#: wave marker is an audit/progress record, not a commit fence).

#: default appends between checkpoint segments (S2C_JOURNAL_CKPT_EVERY
#: overrides; 0 disables).  Small enough that a busy fleet journal's
#: replay tail stays a few hundred segments, large enough that the
#: full-replay cost of writing one is paid rarely.
DEFAULT_CHECKPOINT_EVERY = 512

#: bounded retry for the O_EXCL segment-number race — each loss means
#: another writer PUBLISHED a segment, so 64 losses in a row would
#: need 64 concurrent appends landing between our rescans
_APPEND_ATTEMPTS = 64


def _session_view(st: "ReplayState", key: str) -> dict:
    """The (lazily created) replay view of one streaming session."""
    return st.sessions.setdefault(key, {
        "status": "open", "waves": {}, "absorbed": {},
        "absorb_counts": {}, "rejected": {}, "reads_total": 0,
        "digest": "", "stable": False, "stable_wave": None,
        "opened_t": 0.0, "last_wave_t": 0.0})


def effective_rejections(view: dict) -> set:
    """Wave numbers (string keys) of one session view whose rejection
    actually gates replay.

    A ``wave_rejected`` record is EFFECTIVE when the wave was never
    received at all (a pre-receive rejection — declared-sha mismatch,
    malformed body: there is nothing to replay) or when the rejection
    was journaled AFTER the wave's durable intent (a torn spool).  A
    rejection OLDER than the intent names a previous use of the wave
    number — honoring it would silently drop an ACKed-but-unabsorbed
    wave on crash recovery or steal with a clean audit, which is
    exactly the lost-reads failure the journal exists to make
    impossible.  The session layer no longer reuses wave numbers at
    all (rejections consume theirs), so this fence is the structural
    backstop for journals written before that rule."""
    out = set()
    waves = view.get("waves") or {}
    for w, rej in (view.get("rejected") or {}).items():
        rej_seq = int(rej.get("seq", 0)) if isinstance(rej, dict) else 0
        wave = waves.get(w)
        if wave is None or rej_seq > int(wave.get("seq", 0)):
            out.add(w)
    return out


def job_key(filename: str, config) -> str:
    """Stable identity of (input, output-relevant config)."""
    cfg = {f: getattr(config, f, None) for f in KEY_FIELDS}
    blob = json.dumps({"filename": os.path.abspath(filename), **cfg},
                      sort_keys=True, default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


def file_sha256(path: str) -> Optional[str]:
    try:
        h = hashlib.sha256()
        with open(path, "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                h.update(chunk)
        return "sha256:" + h.hexdigest()
    except OSError:
        return None


def file_fingerprint(path: str) -> Optional[dict]:
    """Commit-time output fingerprint: content hash PLUS the stat pair
    (size, mtime) that lets the resume-time verifier skip the re-hash
    when the file demonstrably never changed (see
    :meth:`JobJournal.verify_outputs`)."""
    sha = file_sha256(path)
    if sha is None:
        return None
    try:
        st = os.stat(path)
    except OSError:
        return None
    return {"sha256": sha, "size": st.st_size,
            "mtime": round(st.st_mtime, 6)}


@dataclass
class ReplayState:
    """What a restarted server knows about its queue."""

    #: key -> the committed event dict (outputs fingerprints inside)
    committed: Dict[str, dict] = field(default_factory=dict)
    #: key -> last failure reason (terminal in its process; re-run-able)
    failed: Dict[str, str] = field(default_factory=dict)
    #: keys started but never committed/failed — in flight at the crash
    inflight: Dict[str, dict] = field(default_factory=dict)
    #: per-key count of committed events across the whole journal — the
    #: duplication audit (anything > 1 means a job ran twice)
    commit_counts: Dict[str, int] = field(default_factory=dict)
    #: every key ever journaled as submitted (restart re-submits are
    #: deduped against this)
    submitted: set = field(default_factory=set)
    #: key -> the OPEN lease: {worker, claim_seq, expires_unix} — the
    #: winning claim per key (fleet mode; see the module docstring)
    claims: Dict[str, dict] = field(default_factory=dict)
    #: keys that have EVER been claimed — once a key's lifecycle uses
    #: leases, its commits are FENCED: a ``committed`` event must come
    #: from the holder of the key's open lease (worker + claim_seq) or
    #: it is void on replay.  This is what makes duplicated=0
    #: structural under split-brain: a zombie whose pending commit
    #: append lands AFTER the thief's commit is rejected by journal
    #: order, not by a racy pre-append check.
    claimed_ever: set = field(default_factory=set)
    #: key -> count of commit events VOIDED by the lease fence (a
    #: zombie's stale append) — forensic, not part of commit_counts
    stale_commits: Dict[str, int] = field(default_factory=dict)
    #: key -> tenant label, from started events that carried one (the
    #: journal-visible input to fleet-global admission accounting)
    tenants: Dict[str, str] = field(default_factory=dict)
    #: key -> wall time of the FIRST submitted event — the flight
    #: recorder's queue-wait epoch (observability/flight.py): journal-
    #: measured queue wait is started.t - submit_times[key], which
    #: survives restarts and steals where a process-local window epoch
    #: cannot
    submit_times: Dict[str, float] = field(default_factory=dict)
    #: key -> streaming-session view (serve/session.py): status,
    #: received waves (``waves``), effective absorbs (``absorbed``),
    #: per-wave absorb counts (the duplication audit — anything > 1
    #: means a wave was counted twice), rejected waves, cumulative
    #: read count, last consensus digest and the stability verdict.
    #: Wave numbers are STRING keys so the dict round-trips through
    #: JSON checkpoints unchanged.
    sessions: Dict[str, dict] = field(default_factory=dict)
    last_seq: int = 0
    events: int = 0
    corrupt_segments: int = 0

    # -- checkpoint (de)serialization ----------------------------------
    def to_blob(self) -> dict:
        return {"schema": CKPT_SCHEMA,
                "committed": self.committed, "failed": self.failed,
                "inflight": self.inflight,
                "commit_counts": self.commit_counts,
                "submitted": sorted(self.submitted),
                "claims": self.claims, "tenants": self.tenants,
                "claimed_ever": sorted(self.claimed_ever),
                "stale_commits": self.stale_commits,
                "submit_times": self.submit_times,
                "sessions": self.sessions,
                "last_seq": self.last_seq, "events": self.events,
                "corrupt_segments": self.corrupt_segments}

    @classmethod
    def from_blob(cls, blob: dict) -> "ReplayState":
        st = cls()
        st.committed = dict(blob.get("committed") or {})
        st.failed = dict(blob.get("failed") or {})
        st.inflight = dict(blob.get("inflight") or {})
        st.commit_counts = dict(blob.get("commit_counts") or {})
        st.submitted = set(blob.get("submitted") or ())
        st.claims = dict(blob.get("claims") or {})
        st.tenants = dict(blob.get("tenants") or {})
        st.claimed_ever = set(blob.get("claimed_ever") or ())
        st.stale_commits = dict(blob.get("stale_commits") or {})
        st.submit_times = dict(blob.get("submit_times") or {})
        st.sessions = dict(blob.get("sessions") or {})
        st.last_seq = int(blob.get("last_seq", 0))
        st.events = int(blob.get("events", 0))
        st.corrupt_segments = int(blob.get("corrupt_segments", 0))
        return st


class JobJournal:
    """Append-only journal over atomic single-event segments.

    Safe for CONCURRENT writer processes sharing ``root`` (the fleet):
    appends publish via ``os.link`` so a sequence-number race has
    exactly one winner, never a torn or overwritten segment.

    ``fault_cb`` (the serve runner's queue-lifetime injector hook) is
    called with site ``journal_write`` before every append.
    """

    def __init__(self, root: str,
                 fault_cb: Optional[Callable[[str], None]] = None,
                 checkpoint_every: Optional[int] = None):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.fault_cb = fault_cb
        if checkpoint_every is None:
            try:
                checkpoint_every = int(os.environ.get(
                    "S2C_JOURNAL_CKPT_EVERY", DEFAULT_CHECKPOINT_EVERY))
            except ValueError:
                checkpoint_every = DEFAULT_CHECKPOINT_EVERY
        self.checkpoint_every = max(0, checkpoint_every)
        #: serializes THIS process's appends: the O_EXCL link already
        #: arbitrates across processes, but concurrent handler threads
        #: (the streaming front door) would otherwise race on _seq /
        #: the mirror and burn link-collision retries for nothing
        self._append_lock = threading.Lock()
        self._seq = self._max_seq() + 1
        #: in-memory mirror of ReplayState, maintained incrementally by
        #: append() so position() (called at every health publish) does
        #: not re-read the whole segment directory per job.  The mirror
        #: only sees THIS process's appends plus whatever the last
        #: replay() read — fleet coordination (serve/fleet.py) always
        #: arbitrates from a fresh replay(), never from the mirror.
        self._mirror: Optional[ReplayState] = None

    # -- segment mechanics -------------------------------------------------
    def _seg_path(self, seq: int) -> str:
        return os.path.join(self.root, f"ev-{seq:08d}.json")

    def _ckpt_path(self, seq: int) -> str:
        return os.path.join(self.root, f"checkpoint-{seq:08d}.json")

    def _listing(self, prefix: str) -> List[Tuple[int, str]]:
        """(seq, path) for every ``<prefix>-NNNNNNNN.json`` in root,
        seq-sorted."""
        out: List[Tuple[int, str]] = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        head = prefix + "-"
        for n in names:
            if not (n.startswith(head) and n.endswith(".json")):
                continue
            try:
                out.append((int(n[len(head):-5]),
                            os.path.join(self.root, n)))
            except ValueError:
                continue
        out.sort()
        return out

    def _segments(self) -> List[str]:
        return [p for _, p in self._listing("ev")]

    def _max_seq(self) -> int:
        """Highest sequence number the journal knows about — segments
        AND checkpoints (after :meth:`prune` the checkpoint may be the
        only record of where the sequence got to)."""
        segs = self._listing("ev")
        ckpts = self._listing("checkpoint")
        top = 0
        if segs:
            top = max(top, segs[-1][0])
        if ckpts:
            top = max(top, ckpts[-1][0])
        return top

    def append(self, ev: str, **fields) -> int:
        """Durably record one event; returns its sequence number.

        tmp + fsync + ``os.link``: after this returns, the event
        survives ``kill -9``; if the process dies inside, the journal
        simply does not contain the event — never half of it.  The link
        (not a rename) is what makes MULTI-process appends safe: it
        fails with EEXIST when another writer already owns the target
        sequence number, and the loser retries on the next free one."""
        assert ev in EVENTS, ev
        if self.fault_cb is not None:
            self.fault_cb("journal_write")
        last_exc: Optional[BaseException] = None
        # one intra-process writer at a time (tmp-file names collide
        # per-pid, _seq/mirror updates stay coherent); cross-PROCESS
        # arbitration stays with the O_EXCL link below
        with self._append_lock:
            for _ in range(_APPEND_ATTEMPTS):
                seq = self._seq
                rec = {"schema": SCHEMA, "seq": seq, "ev": ev,
                       "t": round(time.time(), 3), **fields}
                path = self._seg_path(seq)
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "w", encoding="utf-8") as fh:
                    json.dump(rec, fh, sort_keys=True)
                    fh.write("\n")
                    fh.flush()
                    os.fsync(fh.fileno())
                try:
                    os.link(tmp, path)
                except FileExistsError as exc:
                    # another writer published this seq between our
                    # scan and our link: re-anchor past everything
                    # visible now
                    last_exc = exc
                    os.unlink(tmp)
                    self._seq = max(self._seq + 1, self._max_seq() + 1)
                    continue
                os.unlink(tmp)
                self._seq = seq + 1
                if self._mirror is not None:  # keep the mirror current
                    self._apply(self._mirror, rec)
                if self.checkpoint_every \
                        and seq % self.checkpoint_every == 0:
                    try:
                        self.write_checkpoint()
                    except Exception as exc:  # compaction is optional
                        logger.warning(
                            "journal checkpoint at seq %d failed "
                            "(%s: %s): replay stays O(lifetime)",
                            seq, type(exc).__name__, exc)
                return seq
        raise OSError(
            f"journal append lost the segment race {_APPEND_ATTEMPTS} "
            f"times in a row ({last_exc}) — is something flooding "
            f"{self.root}?")

    def events(self, from_seq: int = 0) -> List[dict]:
        """Every readable event with seq > ``from_seq`` in sequence
        order; corrupt/truncated segments (possible only from external
        damage — appends are atomic) are skipped with a warning, not
        raised.  A numbering GAP below the visible maximum triggers one
        re-list: a concurrent writer links segment N strictly before
        anyone can create N+1, but a directory scan racing both may
        catch the newer entry first."""
        listing = [(s, p) for s, p in self._listing("ev")
                   if s > from_seq]
        if listing:
            want = set(range(listing[0][0], listing[-1][0] + 1))
            have = {s for s, _ in listing}
            # a gap at the FRONT is expected after prune(); only
            # re-list for holes between visible segments
            if want - have:
                listing = [(s, p) for s, p in self._listing("ev")
                           if s > from_seq]
        out: List[dict] = []
        for _, p in listing:
            try:
                with open(p, encoding="utf-8") as fh:
                    out.append(json.load(fh))
            except Exception as exc:
                logger.warning("journal segment %s unreadable (%s: %s): "
                               "skipped", p, type(exc).__name__, exc)
                out.append({"ev": "_corrupt", "path": p})
        return out

    # -- replay ------------------------------------------------------------
    @staticmethod
    def _apply(st: ReplayState, rec: dict) -> None:
        """One event's state transition — shared by the full-disk replay
        and the incremental in-memory mirror, so they cannot drift."""
        ev = rec.get("ev")
        if ev == "_corrupt":
            st.corrupt_segments += 1
            return
        st.events += 1
        st.last_seq = max(st.last_seq, int(rec.get("seq", 0)))
        key = rec.get("key")
        if not key:
            return
        if ev == "submitted":
            st.submitted.add(key)
            if key not in st.submit_times:
                try:
                    st.submit_times[key] = float(rec.get("t", 0.0))
                except (TypeError, ValueError):
                    st.submit_times[key] = 0.0
            if rec.get("tenant"):
                st.tenants[key] = rec["tenant"]
        elif ev == "started":
            st.inflight[key] = rec
            st.failed.pop(key, None)
            if rec.get("tenant"):
                st.tenants[key] = rec["tenant"]
        elif ev == "committed":
            if key in st.claimed_ever:
                # lease fencing: once a key's lifecycle uses claims,
                # only the holder of its OPEN lease may commit.  A
                # zombie that passed its pre-append lease check, then
                # stalled past the TTL while a thief re-claimed,
                # re-ran and committed, lands its stale append HERE —
                # with no open claim (the thief's commit closed it) or
                # the wrong lineage — and is void: the thief's record
                # (whose output fingerprints describe the files
                # actually on disk) stays authoritative, and
                # duplicated=0 is structural.
                cur = st.claims.get(key)
                cs = rec.get("claim_seq")
                if cur is None or cur["worker"] != rec.get("worker") \
                        or (cs is not None
                            and cs != cur.get("claim_seq")):
                    st.stale_commits[key] = \
                        st.stale_commits.get(key, 0) + 1
                    return
            st.committed[key] = rec
            st.inflight.pop(key, None)
            st.failed.pop(key, None)
            st.claims.pop(key, None)
            st.commit_counts[key] = st.commit_counts.get(key, 0) + 1
        elif ev == "failed":
            st.failed[key] = str(rec.get("error", ""))
            st.inflight.pop(key, None)
            st.claims.pop(key, None)
        elif ev == "claimed":
            st.claimed_ever.add(key)
            # first live claim wins; later claims while a lease is open
            # are the LOSERS of the race (they observe this on replay)
            if key not in st.claims:
                st.claims[key] = {
                    "worker": rec.get("worker", ""),
                    "claim_seq": int(rec.get("seq", 0)),
                    "expires_unix": float(rec.get("expires_unix", 0.0)),
                    # last lease sign-of-life wall time: the epoch a
                    # thief's steal gap is measured from (flight.py)
                    "t": float(rec.get("t", 0.0))}
        elif ev == "lease_renewed":
            cur = st.claims.get(key)
            if cur is not None and cur["worker"] == rec.get("worker"):
                cur["expires_unix"] = float(rec.get("expires_unix", 0.0))
                cur["t"] = float(rec.get("t", 0.0))
        elif ev == "lease_expired":
            # effective only if the lease was genuinely expired when
            # the reap event was APPENDED — a renewal that published
            # first pushed expires_unix forward and voids a stale reap
            # (the reaper's subsequent claim then simply loses)
            cur = st.claims.get(key)
            if cur is not None and cur["worker"] == rec.get("worker") \
                    and float(rec.get("t", 0.0)) >= cur["expires_unix"]:
                del st.claims[key]
        elif ev == "session_open":
            s = _session_view(st, key)
            s["status"] = "open"
            s["opened_t"] = float(rec.get("t", 0.0))
            if rec.get("tenant"):
                st.tenants[key] = rec["tenant"]
        elif ev == "wave_received":
            s = _session_view(st, key)
            w = str(rec.get("wave"))
            # first intent wins: a duplicate intent append for a wave
            # number (a retried client racing its own ACK) is a no-op
            # on replay — the session layer never reuses numbers, so
            # a second intent can only be the same wave re-declared
            if w not in s["waves"]:
                s["waves"][w] = {"sha": rec.get("sha", ""),
                                 "reads": int(rec.get("reads", 0)),
                                 "seq": int(rec.get("seq", 0)),
                                 "t": float(rec.get("t", 0.0))}
            s["last_wave_t"] = float(rec.get("t", 0.0))
        elif ev == "wave_absorbed":
            if key in st.claimed_ever:
                # same lease fence as ``committed``: once a session's
                # lifecycle uses leases, only the open lease's holder
                # may absorb.  A zombie's stale absorb append (its
                # lease stolen mid-wave, the thief already replayed
                # the wave) is VOID — the count bank stays exact.
                cur = st.claims.get(key)
                cs = rec.get("claim_seq")
                if cur is None or cur["worker"] != rec.get("worker") \
                        or (cs is not None
                            and cs != cur.get("claim_seq")):
                    st.stale_commits[key] = \
                        st.stale_commits.get(key, 0) + 1
                    return
            s = _session_view(st, key)
            w = str(rec.get("wave"))
            s["absorbed"][w] = {"sha": rec.get("sha", ""),
                                "reads_total": int(
                                    rec.get("reads_total", 0)),
                                "worker": rec.get("worker", ""),
                                "t": float(rec.get("t", 0.0))}
            s["absorb_counts"][w] = s["absorb_counts"].get(w, 0) + 1
            s["reads_total"] = int(rec.get("reads_total",
                                           s["reads_total"]))
            if rec.get("digest"):
                s["digest"] = rec["digest"]
            # an absorb is NOT terminal: the lease stays open for the
            # next wave (unlike ``committed``, which closes it)
        elif ev == "wave_rejected":
            s = _session_view(st, key)
            # the seq records WHEN the rejection landed relative to
            # the wave's intent — recovery honors a rejection only
            # when it post-dates (or precedes any) wave_received for
            # the number (see effective_rejections)
            s["rejected"][str(rec.get("wave"))] = {
                "reason": str(rec.get("reason", "")),
                "seq": int(rec.get("seq", 0))}
        elif ev == "session_stable":
            s = _session_view(st, key)
            s["stable"] = True
            s["stable_wave"] = rec.get("wave")
            if rec.get("digest"):
                s["digest"] = rec["digest"]
        elif ev == "session_closed":
            s = _session_view(st, key)
            s["status"] = "closed"
            if rec.get("digest"):
                s["digest"] = rec["digest"]
            st.claims.pop(key, None)    # terminal, like committed

    # -- checkpoint / compaction -------------------------------------------
    def _latest_checkpoint(self) -> Tuple[int, Optional[ReplayState]]:
        """Newest READABLE checkpoint (seq, state); unreadable ones
        fall back to the next older, then to genesis (0, None)."""
        for seq, path in reversed(self._listing("checkpoint")):
            try:
                with open(path, encoding="utf-8") as fh:
                    blob = json.load(fh)
                if blob.get("schema") != CKPT_SCHEMA:
                    raise ValueError(f"schema {blob.get('schema')!r}")
                return seq, ReplayState.from_blob(blob)
            except Exception as exc:
                logger.warning("journal checkpoint %s unreadable "
                               "(%s: %s): falling back", path,
                               type(exc).__name__, exc)
        return 0, None

    def _replay_from_disk(self, full: bool = False) -> ReplayState:
        st = ReplayState()
        base = 0
        if not full:
            base, loaded = self._latest_checkpoint()
            if loaded is not None:
                st = loaded
            else:
                base = 0
        for rec in self.events(from_seq=base):
            self._apply(st, rec)
        return st

    def replay(self, full: bool = False) -> ReplayState:
        import copy

        st = self._replay_from_disk(full=full)
        # the mirror must be a SEPARATE copy: later appends update it
        # incrementally, and mutating the state just handed to the
        # caller would corrupt its view (the runner reads replay()
        # AFTER journaling the new queue as submitted)
        self._mirror = copy.deepcopy(st)
        return st

    def read_state(self, full: bool = False) -> ReplayState:
        """Replay WITHOUT refreshing the :meth:`position` mirror — the
        fleet's arbitration hot path (several reads per second per
        worker) skips the full-state deepcopy that :meth:`replay` pays
        to keep health reporting cheap."""
        return self._replay_from_disk(full=full)

    def write_checkpoint(self) -> Optional[str]:
        """Summarize the journal so far into a checkpoint segment.

        The state is rebuilt from DISK (newest checkpoint + tail) at
        write time — never from the in-memory mirror, which in a fleet
        misses other workers' appends.  Published with the same O_EXCL
        link as event segments; a concurrent writer checkpointing the
        same seq is absorbed (both built the same state)."""
        st = self._replay_from_disk()
        if st.last_seq <= 0:
            return None
        path = self._ckpt_path(st.last_seq)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(st.to_blob(), fh, sort_keys=True)
            fh.write("\n")
            fh.flush()
            os.fsync(fh.fileno())
        try:
            os.link(tmp, path)
        except FileExistsError:
            pass                        # a peer already wrote this one
        os.unlink(tmp)
        return path

    def prune(self) -> int:
        """Delete event segments the newest checkpoint already covers
        (and all older checkpoints); returns the number of files
        removed.  Replay state is unchanged — the checkpoint IS the
        prefix — but ``replay(full=True)``/forensics lose the pruned
        tail, so pruning is explicit, never automatic."""
        base, loaded = self._latest_checkpoint()
        if loaded is None:
            return 0
        removed = 0
        for seq, path in self._listing("ev"):
            if seq <= base:
                try:
                    os.unlink(path)
                    removed += 1
                except OSError:
                    pass
        for seq, path in self._listing("checkpoint"):
            if seq < base:
                try:
                    os.unlink(path)
                    removed += 1
                except OSError:
                    pass
        return removed

    def verify_outputs(self, committed_rec: dict,
                       mode: str = "fast") -> bool:
        """True iff every output file the commit recorded still exists
        with its recorded fingerprint — the skip-on-restart gate.  A
        missing or drifted file re-runs the job (the journal is an
        audit trail, not a trust store).

        ``mode="fast"`` (default): a file whose (size, mtime) both
        match the commit-time stat is accepted WITHOUT re-hashing —
        resume over a large committed queue is O(stat), not O(bytes).
        Any stat drift falls through to the content hash, so a
        touched-but-identical file still verifies and a corrupted one
        still fails; ``mode="full"`` (``--verify-outputs full``)
        re-hashes everything unconditionally.  Legacy string
        fingerprints (``"sha256:..."``, pre-fleet commits) always
        re-hash."""
        outputs = committed_rec.get("outputs") or {}
        if not outputs:
            return False
        for path, want in outputs.items():
            # a null recorded fingerprint (commit-time hash failure)
            # must NOT match a null re-hash of a missing file —
            # unknown never verifies, the job re-runs
            if want is None:
                return False
            if isinstance(want, str):
                if file_sha256(path) != want:
                    return False
                continue
            try:
                st = os.stat(path)
            except OSError:
                return False
            if st.st_size != want.get("size"):
                return False            # content hash cannot match
            if mode != "full" \
                    and round(st.st_mtime, 6) == want.get("mtime"):
                continue                # demonstrably untouched
            if file_sha256(path) != want.get("sha256"):
                return False
        return True

    # -- per-job checkpoint homes ------------------------------------------
    def ckpt_dir(self, key: str) -> str:
        """The PR-2 checkpoint home the runner assigns a journaled job
        (created lazily by the checkpoint writer)."""
        return os.path.join(self.root, "ckpt", key)

    def drop_ckpt(self, key: str) -> None:
        """A committed job's checkpoint is dead weight: remove it."""
        d = self.ckpt_dir(key)
        if os.path.isdir(d):
            shutil.rmtree(d, ignore_errors=True)

    # -- health / audit ----------------------------------------------------
    def position(self) -> dict:
        """The journal's place in the world, for health snapshots.
        Served from the in-memory mirror (one full replay at first use,
        incremental per append after) — health publishes happen at
        every job boundary, and re-reading the whole segment directory
        each time would grow per-job cost linearly with history.  In
        fleet mode the mirror may lag peers' appends between replays;
        the drain loop's frequent replay() keeps it near-fresh."""
        st = self._mirror if self._mirror is not None else self.replay()
        return {"root": self.root, "last_seq": st.last_seq,
                "events": st.events, "committed": len(st.committed),
                "inflight": len(st.inflight), "failed": len(st.failed),
                "claims": len(st.claims),
                "corrupt_segments": st.corrupt_segments}

    def audit(self, full: bool = False) -> dict:
        """Duplication/loss audit over the whole journal: per-key commit
        counts plus the set of keys ever submitted — the chaos-soak
        harness asserts ``max(commit_counts.values()) <= 1`` per cycle
        and ``submitted ⊆ committed`` at cycle end.  ``full=True``
        bypasses checkpoints (the compaction audit)."""
        st = self.replay(full=full)
        out = {"submitted": sorted(st.submitted),
               "commit_counts": dict(st.commit_counts),
               "duplicated": sorted(k for k, n in st.commit_counts.items()
                                    if n > 1),
               "lost": sorted(st.submitted - set(st.committed)),
               # commits VOIDED by the lease fence (zombie appends):
               # forensic — these are the protocol WORKING, not a
               # duplication
               "stale_commits": dict(st.stale_commits)}
        if st.sessions:
            # streaming sessions: the same 0-lost / 0-duplicated audit
            # at WAVE granularity — a rejected (DATA-class) wave is
            # accounted, never "lost".  Only EFFECTIVE rejections
            # excuse a wave (a stale rejection naming a later wave's
            # number must not launder that wave out of lost_waves)
            out["sessions"] = {}
            for key, s in sorted(st.sessions.items()):
                rej = effective_rejections(s)
                out["sessions"][key] = {
                    "waves": len(s["waves"]),
                    "absorbed": len(s["absorbed"]),
                    "duplicated_waves": sorted(
                        w for w, n in s["absorb_counts"].items()
                        if n > 1),
                    "lost_waves": sorted(
                        w for w in s["waves"]
                        if w not in s["absorbed"] and w not in rej),
                    "rejected_waves": sorted(s["rejected"]),
                    "reads_total": s["reads_total"],
                    "status": s["status"], "stable": s["stable"]}
        return out
