"""Crash-safe job journal: a serve queue that survives ``kill -9``.

The PR-5 runner kept the queue in process memory — a crash mid-queue
lost every pending job and forgot which jobs already ran, so a naive
re-launch either dropped work or ran it twice.  The journal makes the
queue durable with the cheapest discipline that is actually
crash-safe on POSIX: an append-only sequence of single-event JSON
SEGMENTS, each written to a temp file and ``os.replace``d into place
(the same atomicity utils/checkpoint.py relies on).  A ``kill -9`` at
any instant leaves only whole events behind — there is no shared
append file whose torn last line needs heuristic repair, and replay
order is the segment sequence number, not mtime.

Event vocabulary (one JSON object per segment)::

    submitted  {job, key, filename, seq}
    started    {job, key, ckpt}          # ckpt = per-job checkpoint dir
    committed  {job, key, outputs: {path: "sha256:..."}, elapsed_sec}
    failed     {job, key, error}
    rejected   {job, key, reason}        # admission control audit
    resumed    {job, key, mode}          # restart bookkeeping (audit)

A job's IDENTITY (``key``) hashes its input path plus every config
field that changes the output bytes — so a restarted server given the
same queue recognizes its jobs even though Python object identity is
gone, while a changed threshold/outfolder reads as a different job.

Replay semantics (:meth:`JobJournal.replay`):

* a key whose last lifecycle event is ``committed`` AND whose recorded
  output files still match their fingerprints is SKIPPED on restart
  (zero duplicated jobs — the fingerprint is the audit, not trust);
* a key with ``started`` but no terminal event was IN FLIGHT when the
  process died: it re-runs, resuming from its per-job checkpoint dir
  (the PR-2 emergency/periodic checkpoints) when one survived;
* everything else re-runs from scratch (zero lost jobs).

The ``journal_write`` fault-injection site fires on every segment
append (resilience/faultinject.py; the serve runner checks it against
its queue-lifetime injector).  An append failure is surfaced to the
caller — the runner decides the policy (a failed COMMIT append leaves
the job to be re-verified-by-fingerprint on the next restart, which is
the safe direction: re-checking work is cheap, losing it is not).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

logger = logging.getLogger("sam2consensus_tpu.serve.journal")

SCHEMA = "s2c-journal/1"

#: fields of RunConfig that change the OUTPUT BYTES of a job — the job
#: key hashes exactly these, so a re-queued job with a different
#: threshold/outfolder is a different job, while backend-side knobs
#: (pileup strategy, wire codec, retries) keep the same identity: they
#: must produce byte-identical outputs anyway
KEY_FIELDS = ("thresholds", "min_depth", "fill", "maxdel", "prefix",
              "nchar", "outfolder", "py2_compat", "strict")

#: lifecycle events; ``rejected``/``resumed`` are audit-only
EVENTS = ("submitted", "started", "committed", "failed", "rejected",
          "resumed")


def job_key(filename: str, config) -> str:
    """Stable identity of (input, output-relevant config)."""
    cfg = {f: getattr(config, f, None) for f in KEY_FIELDS}
    blob = json.dumps({"filename": os.path.abspath(filename), **cfg},
                      sort_keys=True, default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


def file_sha256(path: str) -> Optional[str]:
    try:
        h = hashlib.sha256()
        with open(path, "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                h.update(chunk)
        return "sha256:" + h.hexdigest()
    except OSError:
        return None


@dataclass
class ReplayState:
    """What a restarted server knows about its queue."""

    #: key -> the committed event dict (outputs fingerprints inside)
    committed: Dict[str, dict] = field(default_factory=dict)
    #: key -> last failure reason (terminal in its process; re-run-able)
    failed: Dict[str, str] = field(default_factory=dict)
    #: keys started but never committed/failed — in flight at the crash
    inflight: Dict[str, dict] = field(default_factory=dict)
    #: per-key count of committed events across the whole journal — the
    #: duplication audit (anything > 1 means a job ran twice)
    commit_counts: Dict[str, int] = field(default_factory=dict)
    #: every key ever journaled as submitted (restart re-submits are
    #: deduped against this)
    submitted: set = field(default_factory=set)
    last_seq: int = 0
    events: int = 0
    corrupt_segments: int = 0


class JobJournal:
    """Append-only journal over atomic single-event segments.

    ``fault_cb`` (the serve runner's queue-lifetime injector hook) is
    called with site ``journal_write`` before every append.
    """

    def __init__(self, root: str,
                 fault_cb: Optional[Callable[[str], None]] = None):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.fault_cb = fault_cb
        self._seq = self._max_seq() + 1
        #: in-memory mirror of ReplayState, maintained incrementally by
        #: append() so position() (called at every health publish) does
        #: not re-read the whole segment directory per job
        self._mirror: Optional[ReplayState] = None

    # -- segment mechanics -------------------------------------------------
    def _seg_path(self, seq: int) -> str:
        return os.path.join(self.root, f"ev-{seq:08d}.json")

    def _segments(self) -> List[str]:
        try:
            names = sorted(n for n in os.listdir(self.root)
                           if n.startswith("ev-") and n.endswith(".json"))
        except OSError:
            return []
        return [os.path.join(self.root, n) for n in names]

    def _max_seq(self) -> int:
        top = 0
        for p in self._segments():
            try:
                top = max(top, int(os.path.basename(p)[3:-5]))
            except ValueError:
                continue
        return top

    def append(self, ev: str, **fields) -> int:
        """Durably record one event; returns its sequence number.

        tmp + fsync + ``os.replace``: after this returns, the event
        survives ``kill -9``; if the process dies inside, the journal
        simply does not contain the event — never half of it."""
        assert ev in EVENTS, ev
        if self.fault_cb is not None:
            self.fault_cb("journal_write")
        seq = self._seq
        rec = {"schema": SCHEMA, "seq": seq, "ev": ev,
               "t": round(time.time(), 3), **fields}
        path = self._seg_path(seq)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(rec, fh, sort_keys=True)
            fh.write("\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        self._seq = seq + 1
        if self._mirror is not None:    # keep the cheap mirror current
            self._apply(self._mirror, rec)
        return seq

    def events(self) -> List[dict]:
        """Every readable event in sequence order; corrupt/truncated
        segments (possible only from external damage — appends are
        atomic) are skipped with a warning, not raised."""
        out: List[dict] = []
        for p in self._segments():
            try:
                with open(p, encoding="utf-8") as fh:
                    out.append(json.load(fh))
            except Exception as exc:
                logger.warning("journal segment %s unreadable (%s: %s): "
                               "skipped", p, type(exc).__name__, exc)
                out.append({"ev": "_corrupt", "path": p})
        return out

    # -- replay ------------------------------------------------------------
    @staticmethod
    def _apply(st: ReplayState, rec: dict) -> None:
        """One event's state transition — shared by the full-disk replay
        and the incremental in-memory mirror, so they cannot drift."""
        ev = rec.get("ev")
        if ev == "_corrupt":
            st.corrupt_segments += 1
            return
        st.events += 1
        st.last_seq = max(st.last_seq, int(rec.get("seq", 0)))
        key = rec.get("key")
        if not key:
            return
        if ev == "submitted":
            st.submitted.add(key)
        elif ev == "started":
            st.inflight[key] = rec
            st.failed.pop(key, None)
        elif ev == "committed":
            st.committed[key] = rec
            st.inflight.pop(key, None)
            st.failed.pop(key, None)
            st.commit_counts[key] = st.commit_counts.get(key, 0) + 1
        elif ev == "failed":
            st.failed[key] = str(rec.get("error", ""))
            st.inflight.pop(key, None)

    def replay(self) -> ReplayState:
        import copy

        st = ReplayState()
        for rec in self.events():
            self._apply(st, rec)
        # the mirror must be a SEPARATE copy: later appends update it
        # incrementally, and mutating the state just handed to the
        # caller would corrupt its view (the runner reads replay()
        # AFTER journaling the new queue as submitted)
        self._mirror = copy.deepcopy(st)
        return st

    def verify_outputs(self, committed_rec: dict) -> bool:
        """True iff every output file the commit recorded still exists
        with its recorded fingerprint — the skip-on-restart gate.  A
        missing or drifted file re-runs the job (the journal is an
        audit trail, not a trust store)."""
        outputs = committed_rec.get("outputs") or {}
        if not outputs:
            return False
        # a null recorded fingerprint (commit-time hash failure) must
        # NOT match a null re-hash of a missing file — unknown never
        # verifies, the job re-runs
        return all(want is not None and file_sha256(p) == want
                   for p, want in outputs.items())

    # -- per-job checkpoint homes ------------------------------------------
    def ckpt_dir(self, key: str) -> str:
        """The PR-2 checkpoint home the runner assigns a journaled job
        (created lazily by the checkpoint writer)."""
        return os.path.join(self.root, "ckpt", key)

    def drop_ckpt(self, key: str) -> None:
        """A committed job's checkpoint is dead weight: remove it."""
        d = self.ckpt_dir(key)
        if os.path.isdir(d):
            shutil.rmtree(d, ignore_errors=True)

    # -- health / audit ----------------------------------------------------
    def position(self) -> dict:
        """The journal's place in the world, for health snapshots.
        Served from the in-memory mirror (one full replay at first use,
        incremental per append after) — health publishes happen at
        every job boundary, and re-reading the whole segment directory
        each time would grow per-job cost linearly with history."""
        st = self._mirror if self._mirror is not None else self.replay()
        return {"root": self.root, "last_seq": st.last_seq,
                "events": st.events, "committed": len(st.committed),
                "inflight": len(st.inflight), "failed": len(st.failed),
                "corrupt_segments": st.corrupt_segments}

    def audit(self) -> dict:
        """Duplication/loss audit over the whole journal: per-key commit
        counts plus the set of keys ever submitted — the chaos-soak
        harness asserts ``max(commit_counts.values()) <= 1`` per cycle
        and ``submitted ⊆ committed`` at cycle end."""
        st = self.replay()
        return {"submitted": sorted(st.submitted),
                "commit_counts": dict(st.commit_counts),
                "duplicated": sorted(k for k, n in st.commit_counts.items()
                                     if n > 1),
                "lost": sorted(st.submitted - set(st.committed))}
