"""Cohort-scale serving: manifest-streamed waves over a shared panel.

The serve stack's batch scheduler (serve/scheduler.py) packs whatever
small jobs happen to be queued; a COHORT is the case the paper's
target-capture workloads actually ship — hundreds to tens of thousands
of samples, every one aligned against the SAME reference panel.  That
sameness collapses the remaining per-job planning costs:

* **layout dedup** — every member's offset table is ``k * panel_len``
  (equal :func:`~.packing.reference_fingerprint` implies equal
  layout), so ONE :class:`~.packing.PanelGeometry` is planned before
  wave 1 and every wave reuses it verbatim.  The scheduler's
  ``batch/panel_plans`` / ``batch/panel_reuses`` counters are the
  zero-re-plans evidence;
* **one compile footprint** — the canonical scatter shapes of the
  combined panel axis (:func:`~..ops.pileup.canonical_panel_shapes`)
  are prewarmed once, so every wave — the first included — dispatches
  shapes the jit cache already holds;
* **manifest streaming** — the cohort arrives as ONE manifest
  (directory, file list, or object-store-style JSONL listing), not N
  CLI submissions.  The driver slices it into packed waves, probes
  wave k+1's headers on a side thread while wave k dispatches
  (filling the scheduler's ``probe_cache``), and journals a
  ``cohort_wave`` marker per finished wave so a restarted cohort
  resumes at the last committed wave (member jobs keep their own
  per-job journal lifecycles — the wave marker is progress evidence,
  not a commit fence);
* **occupancy-aware wave sizing** — each wave's size comes from the
  hard caps (combined-length cap, ``--max-queue``, ``--mem-budget``
  via the memory plane's predicted peak) and a learned packed-rate
  target (the ``cohort_jobs_per_sec`` rate card ×
  ``S2C_COHORT_WAVE_SEC``), priced as a ``cohort_wave`` ledger
  decision per wave: predicted vs measured jobs/s joined at wave end,
  residual inside the drift band once the rate is learned.

Failure semantics are the scheduler's, unchanged: a fault inside a
wave's packed phases demotes that wave's members WHOLE to the serial
path (count-bank rule, ``batch/demotions``); the cohort keeps
streaming subsequent waves, and a crash resumes from the journal.

Outputs: per-sample FASTAs byte-identical to serial runs (the packed
path's structural guarantee), plus a cohort-level per-position
call-concordance summary accumulated from each member's private count
partition (tapped off the combined tensor at zero extra device work;
members that ran serially are back-filled through the CPU oracle
accumulation in :func:`oracle_member_counts`).
"""

from __future__ import annotations

import dataclasses
import glob
import hashlib
import json
import logging
import math
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import observability as obs
from ..constants import NUM_SYMBOLS
from ..observability.ledger import finalize as ledger_finalize
from ..observability import ratecard as rcard
from . import packing

logger = logging.getLogger("sam2consensus_tpu.serve.cohort")

#: manifest directory scan picks up exactly the container formats the
#: ingest layer sniffs (formats/)
MANIFEST_EXTS = (".sam", ".sam.gz", ".bam")

#: wave-duration target the rate-based sizing aims at: big enough to
#: amortize per-wave fixed costs, small enough that progress gauges
#: and the journal's wave markers stay live
DEFAULT_WAVE_SEC = 2.0


def _wave_sec() -> float:
    try:
        return max(0.1, float(os.environ.get("S2C_COHORT_WAVE_SEC",
                                             DEFAULT_WAVE_SEC)))
    except ValueError:
        return DEFAULT_WAVE_SEC


# -- manifest ---------------------------------------------------------------
def load_manifest(path: str) -> List[str]:
    """Resolve a cohort manifest to an ordered list of input paths.

    Three shapes, dispatched on what ``path`` is:

    * a **directory** — every ``*.sam`` / ``*.sam.gz`` / ``*.bam``
      directly inside it, sorted by name;
    * a **``.jsonl`` file** — one JSON object per line, each with a
      ``"path"`` key (the object-store-listing shape); relative paths
      resolve against the manifest's own directory;
    * any other **text file** — one path or glob per line, ``#``
      comments and blank lines skipped, globs expanded (sorted)
      relative to the manifest's directory.

    Raises ``ValueError`` on an empty resolution — a cohort of zero
    samples is a manifest bug, not a successful no-op."""
    out: List[str] = []
    if os.path.isdir(path):
        for name in sorted(os.listdir(path)):
            if name.endswith(MANIFEST_EXTS):
                out.append(os.path.join(path, name))
    elif path.endswith(".jsonl"):
        base = os.path.dirname(os.path.abspath(path))
        with open(path, "r", encoding="utf-8") as fh:
            for ln, line in enumerate(fh, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError as exc:
                    raise ValueError(
                        f"{path}:{ln}: not JSON ({exc})") from None
                p = row.get("path") if isinstance(row, dict) else None
                if not p:
                    raise ValueError(
                        f"{path}:{ln}: listing row has no 'path' key")
                out.append(p if os.path.isabs(p)
                           else os.path.join(base, p))
    else:
        base = os.path.dirname(os.path.abspath(path))
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                p = line if os.path.isabs(line) \
                    else os.path.join(base, line)
                if any(ch in line for ch in "*?["):
                    out.extend(sorted(glob.glob(p)))
                else:
                    out.append(p)
    if not out:
        raise ValueError(
            f"cohort manifest {path!r} resolved to zero inputs")
    return out


# -- concordance ------------------------------------------------------------
class ConcordanceAccumulator:
    """Per-position call concordance across a shared-panel cohort.

    Each member contributes one modal CALL per panel position (argmax
    over its private ``[panel_len, 6]`` count partition; zero depth =
    the explicit no-call lane), accumulated into a ``[panel_len, 7]``
    tally.  Concordance at a position is modal-call fraction among
    members that made a call there (positions nobody called read 1.0
    — absence of evidence is not discordance).  The summary's
    ``digest`` hashes the raw tally, so "pinned vs CPU oracle" is one
    dict equality: same members through the device path and the oracle
    path must produce the same calls, hence the same digest."""

    NO_CALL = NUM_SYMBOLS          # lane 6: zero-depth positions

    def __init__(self, panel_len: int):
        self.panel_len = int(panel_len)
        self.members = 0
        self._table = np.zeros((self.panel_len, NUM_SYMBOLS + 1),
                               dtype=np.int64)

    def add_member(self, counts: np.ndarray) -> None:
        counts = np.asarray(counts)
        if counts.shape[0] != self.panel_len:
            raise ValueError(
                f"member counts cover {counts.shape[0]} positions; "
                f"the cohort panel has {self.panel_len}")
        calls = np.argmax(counts, axis=1)
        depth = counts.sum(axis=1)
        calls = np.where(depth > 0, calls, self.NO_CALL)
        self._table[np.arange(self.panel_len), calls] += 1
        self.members += 1

    def summary(self) -> dict:
        called = self._table[:, :NUM_SYMBOLS]
        ncalled = called.sum(axis=1)
        modal = called.max(axis=1)
        conc = np.where(ncalled > 0,
                        modal / np.maximum(ncalled, 1), 1.0)
        return {
            "schema": "s2c-cohort-concordance/1",
            "panel_len": self.panel_len,
            "members": int(self.members),
            "mean_concordance": round(float(conc.mean()), 6)
            if self.panel_len else 1.0,
            "min_concordance": round(float(conc.min()), 6)
            if self.panel_len else 1.0,
            "discordant_positions": int((conc < 1.0).sum()),
            "digest": hashlib.sha1(
                self._table.tobytes()).hexdigest()[:16],
        }


def oracle_member_counts(filename: str, cfg, backend=None) -> np.ndarray:
    """One member's ``[panel_len, 6]`` count tensor via the CPU oracle
    path: serial decode + host accumulation, no packing, no device.
    This is both the concordance pin's independent evidence source and
    the back-fill for members the packed path demoted to serial (their
    partitions never crossed the combined tensor, so the count tap
    never saw them)."""
    from ..config import resolve_decode_threads
    from ..encoder.events import GenomeLayout
    from ..formats import open_alignment_input
    from ..ops.pileup import HostPileupAccumulator

    if backend is None:
        from ..backends.jax_backend import JaxBackend

        backend = JaxBackend()
    robs = obs.prepare_run(config=None)
    ai = open_alignment_input(
        filename, getattr(cfg, "input_format", "auto"), binary=True,
        threads=resolve_decode_threads(cfg))
    try:
        with obs.bind_run_to_thread(robs):
            layout = GenomeLayout(ai.contigs)
            acc = HostPileupAccumulator(layout.total_len)
            _encoder, gen = backend._make_encoder(layout, ai.stream,
                                                  cfg, None)
            for batch in gen:
                acc.add(batch)
            return np.asarray(acc.counts_host())
    finally:
        ai.close()


# -- wave sizing ------------------------------------------------------------
def wave_cap(samples_left: int, panel_len: int, cfg, scheduler,
             admission) -> Tuple[int, dict]:
    """The HARD member cap any wave of this cohort must respect: the
    scheduler's combined-length cap, the admission window
    (``--max-queue``), and the largest wave whose predicted peak
    (:func:`~..observability.memplane.predict_job_peak_bytes` over
    ``W * panel_len``) fits ``--mem-budget`` (binary search; raises
    when even a 2-member wave cannot fit — a cohort that would trip
    admission mid-stream must fail at sizing time, not wave 40).

    Computed once up front to size the ONE canonical
    :class:`~.packing.PanelGeometry` (every wave is a prefix slice of
    it, so no wave can ever force a re-plan), then again per wave by
    :func:`size_wave` against the shrinking remainder."""
    panel_len = max(1, int(panel_len))
    len_cap = scheduler.max_combined_len // panel_len
    if len_cap < 2:
        raise ValueError(
            f"panel of {panel_len} positions: even 2 members exceed "
            f"the combined-length cap ({scheduler.max_combined_len}; "
            f"raise S2C_BATCH_MAX_LEN) — this cohort cannot pack")
    cap = min(len_cap, max(1, int(samples_left)))
    inputs: dict = {"samples_left": int(samples_left),
                    "panel_len": panel_len, "len_cap": len_cap}
    if admission.max_queue:
        cap = min(cap, admission.max_queue)
        inputs["queue_cap"] = admission.max_queue
    if admission.mem_budget:
        from ..observability import memplane

        lo, hi, best = 1, cap, 0
        while lo <= hi:
            mid = (lo + hi) // 2
            if memplane.predict_job_peak_bytes(
                    mid * panel_len, cfg) <= admission.mem_budget:
                best, lo = mid, mid + 1
            else:
                hi = mid - 1
        if best < 2 <= samples_left:
            raise ValueError(
                f"--mem-budget {admission.mem_budget}: predicted peak "
                f"of a 2-member wave over a {panel_len}-position panel "
                f"already exceeds the budget — raise the budget or "
                f"shrink the panel")
        cap = min(cap, max(1, best))
        inputs["mem_cap"] = best
    return cap, inputs


def size_wave(samples_left: int, panel_len: int, cfg, scheduler,
              admission, requested: int = 0, jps: float = 1.0,
              wave_sec: Optional[float] = None,
              rows_per_member: float = 0.0) -> Tuple[int, dict]:
    """Pick the next wave's member count; returns ``(W, inputs)`` with
    the sizing evidence for the ``cohort_wave`` ledger decision.

    Hard caps first (:func:`wave_cap`).  Within them, an explicit
    ``--cohort-wave N`` wins; otherwise the wave targets ``jps *
    wave_sec`` members (the learned packed rate × the wave duration
    target), floored at 2 — a wave of one cannot pack.  When the
    driver has learned ``rows_per_member`` from a finished wave, the
    rate target is then SNAPPED (±25%, still capped) to the candidate
    whose estimated slab row count sits closest under its pow2 pad
    boundary (:func:`~.packing._pad_rows`) — trading a slightly
    off-target wave for dispatch rows that are mostly real instead of
    pad, which is where a cohort's throughput actually goes."""
    wave_sec = _wave_sec() if wave_sec is None else float(wave_sec)
    cap, inputs = wave_cap(samples_left, panel_len, cfg, scheduler,
                           admission)
    if requested:
        w = min(int(requested), cap)
        inputs["requested"] = int(requested)
    else:
        target = max(2, int(round(max(0.1, jps) * wave_sec)))
        w = min(target, cap)
        inputs["rate_target"] = target
        inputs["wave_sec_target"] = wave_sec
        # pow2 snap only when MORE waves follow anyway: shrinking the
        # final wave below the remainder would mint extra waves, and a
        # wave's fixed costs always beat its pad rows' (the accumulator
        # trims the pad tail before dispatch — ops/pileup.py add)
        if rows_per_member > 0 and w >= 2 \
                and samples_left > int(math.ceil(w * 1.25)):
            lo_w = max(2, int(math.ceil(w * 0.75)))
            hi_w = max(lo_w, min(cap, int(math.ceil(w * 1.25))))
            best_w, best_occ = w, -1.0
            for cand in range(lo_w, hi_w + 1):
                rows = max(1, int(round(cand * rows_per_member)))
                occ = rows / packing._pad_rows(rows)
                if occ > best_occ + 1e-9 or (
                        abs(occ - best_occ) <= 1e-9
                        and abs(cand - w) < abs(best_w - w)):
                    best_w, best_occ = cand, occ
            w = best_w
            inputs["rows_per_member"] = round(rows_per_member, 2)
            inputs["occupancy_target_pct"] = round(100.0 * best_occ, 1)
    w = max(1, min(w, samples_left))
    if samples_left >= 2:
        w = max(2, w)
    inputs["wave_jobs"] = w
    return w, inputs


# -- the driver -------------------------------------------------------------
class CohortRunner:
    """Stream one manifest's samples through a ServeRunner in packed
    waves.  One instance per cohort submission; attach via
    ``CohortRunner(runner, ...).run()`` — the instance registers
    itself as ``runner.cohort`` so the health snapshot and
    ``tools/s2c_top.py`` see live progress."""

    def __init__(self, runner, paths: List[str], base_config,
                 wave: int = 0, tenant: str = "",
                 concordance: str = "on",
                 summary_out: Optional[str] = None,
                 echo: Optional[Callable] = None):
        sched = getattr(runner, "scheduler", None)
        if sched is None or not sched.enabled:
            raise ValueError(
                "cohort serving rides the batch scheduler: start the "
                "server with --batch auto (or --batch N)")
        if concordance not in ("on", "off"):
            raise ValueError(
                f"concordance={concordance!r}: use 'on' or 'off'")
        self.runner = runner
        self.sched = sched
        self.paths = list(paths)
        self.base_config = base_config
        self.requested_wave = max(0, int(wave or 0))
        self.tenant = tenant or ""
        self.summary_out = summary_out
        self.echo = echo or (lambda *a, **k: None)
        # -- progress state (health_summary reads these live) ----------
        self.samples_total = len(self.paths)
        self.samples_done = 0
        self.resumed = 0
        self.failed = 0
        self.waves_done = 0
        self.waves_total_est = 0
        self.panel_len = 0
        self.ref_fp = ""
        self.admission_trips = 0
        self.last_wave: dict = {}
        self.decisions: List[dict] = []
        self.results: List[object] = []
        self.concordance: Optional[ConcordanceAccumulator] = None
        #: bench/test seam: called as ``wave_hook(k)`` after wave ``k``
        #: fully finalizes (counters folded, journal marker written) —
        #: how the cohort bench snapshots plan/compile counters at wave
        #: boundaries without reaching into the wave loop
        self.wave_hook: Optional[Callable[[int], None]] = None
        self._want_concordance = concordance == "on"
        self._jps_ema: Optional[float] = None
        #: learned decoded rows per member (EMA over finished waves) —
        #: feeds size_wave's pow2 occupancy snapping
        self._rows_per_member: float = 0.0
        self._tapped: set = set()
        self._lock = threading.Lock()
        runner.cohort = self

    # -- pieces ------------------------------------------------------------
    def _spec(self, idx: int, path: str):
        from ..config import default_prefix
        from .runner import JobSpec

        cfg = self.base_config
        if not cfg.prefix:
            # per-sample default prefix (input basename), the same rule
            # the CLI applies per -i input — a shared-panel cohort's
            # outputs would otherwise all collapse onto one filename
            cfg = dataclasses.replace(cfg,
                                      prefix=default_prefix(path))
        return JobSpec(filename=path, config=cfg,
                       job_id=f"c{idx}:{os.path.basename(path)}",
                       tenant=self.tenant)

    def _prefilter_resumed(self) -> List[Tuple[int, str]]:
        """Journal-backed resume: drop samples whose jobs a previous
        process already committed (outputs still fingerprint-match), so
        a restarted cohort's waves contain only pending work — the
        resume position IS the last committed wave."""
        from . import journal as sjournal

        runner = self.runner
        if runner.journal is None:
            return list(enumerate(self.paths))
        replay = runner.journal.replay()
        left: List[Tuple[int, str]] = []
        for idx, path in enumerate(self.paths):
            key = sjournal.job_key(path, self._spec(idx, path).config)
            rec = replay.committed.get(key)
            if rec is not None and runner.journal.verify_outputs(
                    rec, mode=runner.verify_mode):
                self.resumed += 1
            else:
                left.append((idx, path))
        if self.resumed:
            runner.registry.add("cohort/resumed_skipped", self.resumed)
        return left

    def _probe_panel(self, path: str) -> None:
        """Header-probe the first pending sample for the cohort's panel
        geometry; the OPEN handle parks in the scheduler's probe cache
        so wave 1's compose reuses it (one header parse per member,
        cohort-wide)."""
        from ..config import resolve_decode_threads
        from ..encoder.events import GenomeLayout
        from ..formats import open_alignment_input

        ai = open_alignment_input(
            path, getattr(self.base_config, "input_format", "auto"),
            binary=True,
            threads=resolve_decode_threads(self.base_config))
        try:
            layout = GenomeLayout(ai.contigs)
            self.panel_len = layout.total_len
            self.ref_fp = packing.reference_fingerprint(ai.contigs)
        except BaseException:
            ai.close()
            raise
        entry = {"batch_total_len": self.panel_len,
                 "batch_handle": ai, "batch_ref_fp": self.ref_fp}
        try:
            entry["batch_bytes"] = os.path.getsize(path)
        except OSError:
            pass
        self.sched.probe_cache[path] = entry
        if self.panel_len <= 0:
            raise ValueError(f"{path!r}: empty reference panel")
        if self.panel_len > self.sched.max_member_len:
            raise ValueError(
                f"panel of {self.panel_len} positions exceeds the "
                f"packable member cap ({self.sched.max_member_len}; "
                f"S2C_BATCH_MAX_MEMBER_LEN) — this cohort cannot pack")

    def _prefetch(self, batch_paths: List[str]) -> None:
        """Probe the NEXT wave's headers off-thread while the current
        wave decodes/dispatches, parking results (open handles
        included) in the scheduler's probe cache.  Failures are
        absorbed: the critical-path probe will re-open and surface the
        real error in the right job."""
        from ..config import resolve_decode_threads
        from ..encoder.events import GenomeLayout
        from ..formats import open_alignment_input

        for path in batch_paths:
            if path in self.sched.probe_cache:
                continue
            try:
                ai = open_alignment_input(
                    path,
                    getattr(self.base_config, "input_format", "auto"),
                    binary=True,
                    threads=resolve_decode_threads(self.base_config))
            except Exception:
                self.runner.registry.add("cohort/prefetch_failed", 1)
                continue
            try:
                entry = {
                    "batch_total_len": GenomeLayout(
                        ai.contigs).total_len,
                    "batch_handle": ai,
                    "batch_ref_fp": packing.reference_fingerprint(
                        ai.contigs),
                }
                try:
                    entry["batch_bytes"] = os.path.getsize(path)
                except OSError:
                    pass
                self.sched.probe_cache[path] = entry
            except Exception:
                ai.close()
                self.runner.registry.add("cohort/prefetch_failed", 1)

    def _drain_probe_cache(self) -> None:
        for path in list(self.sched.probe_cache):
            entry = self.sched.probe_cache.pop(path, None)
            ai = (entry or {}).get("batch_handle")
            if ai is not None:
                try:
                    ai.close()
                except Exception:
                    pass

    def _tap(self, job_id: str, counts: np.ndarray) -> None:
        """Scheduler count tap: one member's private partition, sliced
        from the combined tensor the wave just fetched."""
        with self._lock:
            if self.concordance is not None:
                self.concordance.add_member(counts)
                self._tapped.add(job_id)

    def _prewarm(self, wave_jobs: int) -> int:
        """Compile the combined panel axis's canonical scatter shapes
        ONCE, before wave 1 — the dedup story's compile half (the host
        accumulation rung compiles nothing, so it skips)."""
        if self.runner.prewarm_mode == "off" \
                or self.sched._accum_host_rung():
            return 0
        from ..encoder.events import resolve_segment_width
        from ..ops.pileup import canonical_panel_shapes

        shapes = canonical_panel_shapes(
            self.panel_len, wave_jobs,
            chunk_reads=self.base_config.chunk_reads,
            segment_width=resolve_segment_width(
                getattr(self.base_config, "segment_width", 0)))
        return self.runner.prewarm(self.panel_len * wave_jobs, shapes)

    def _consult_jps(self) -> Tuple[float, dict]:
        """The jobs/s estimate wave sizing prices against: the learned
        ``cohort_jobs_per_sec`` card when confident, else this run's
        own EMA, else (before wave 1) the packed-batch rate or the
        scheduler's shared-wall model."""
        if self._jps_ema is not None:
            default = self._jps_ema
        else:
            packed, _ = rcard.consult("packed_jobs_per_sec", 0.0)
            default = packed or self._heuristic_jps()
        val, prov = rcard.consult("cohort_jobs_per_sec", default)
        return max(0.1, float(val)), prov

    def _heuristic_jps(self) -> float:
        n = max(2, self.sched.max_jobs)
        first = self.sched.probe_cache.get(
            next(iter(self.sched.probe_cache), ""), {})
        bytes_total = n * int(first.get("batch_bytes") or 1 << 20)
        pred = self.sched._predict_wall(n, bytes_total,
                                        self.sched._accum_host_rung())
        return n / max(1e-6, pred)

    # -- the run -----------------------------------------------------------
    def run(self) -> dict:
        runner = self.runner
        reg = runner.registry
        t_run0 = time.perf_counter()
        left = self._prefilter_resumed()
        if self.resumed:
            self.echo(f"cohort: {self.resumed} sample(s) already "
                      "committed — resuming from the journal's last "
                      "committed wave")
        if not left:
            return self._summarize(t_run0)
        self._probe_panel(left[0][1])
        if self._want_concordance:
            self.concordance = ConcordanceAccumulator(self.panel_len)
            runner.count_tap = self._tap
        self.echo(f"cohort: {len(left)} pending sample(s) over a "
                  f"{self.panel_len}-position panel "
                  f"(fingerprint {self.ref_fp})")
        # ONE canonical slab geometry for the whole cohort, planned at
        # the hard wave cap: rate-sized waves vary in member count, and
        # a geometry sized to wave 0 would force the scheduler to
        # re-plan the first time a wave outgrew it.  Planned here, every
        # wave — whatever its size — is a prefix slice of this table
        # (``batch/panel_reuses`` per wave, ``batch/panel_plans`` == 1).
        cap, _ = wave_cap(len(left), self.panel_len, self.base_config,
                          self.sched, runner.admission)
        key = (self.ref_fp, self.panel_len)
        if self.sched._panel_geoms.get(key) is None \
                or self.sched._panel_geoms[key].max_jobs < cap:
            self.sched._panel_geoms[key] = packing.PanelGeometry(
                fingerprint=self.ref_fp, panel_len=self.panel_len,
                max_jobs=max(2, cap))
            reg.add("batch/panel_plans", 1)
        pos, k = 0, 0
        prefetcher: Optional[threading.Thread] = None
        prev_max_jobs, prev_mode = self.sched.max_jobs, self.sched.mode
        try:
            while pos < len(left):
                samples_left = len(left) - pos
                jps, prov = self._consult_jps()
                w, inputs = size_wave(
                    samples_left, self.panel_len, self.base_config,
                    self.sched, runner.admission,
                    requested=self.requested_wave, jps=jps,
                    rows_per_member=self._rows_per_member)
                predicted_bytes = 0
                if runner.admission.mem_budget:
                    from ..observability import memplane

                    predicted_bytes = memplane.predict_job_peak_bytes(
                        w * self.panel_len, self.base_config)
                dec = runner.admission.price_cohort_wave(
                    w, predicted_bytes)
                if not dec.admitted:
                    # sizing already honored every cap, so a reject
                    # here is model disagreement — halve and count it
                    # (the bench gates this counter at zero)
                    self.admission_trips += 1
                    reg.add("cohort/admission_trips", 1)
                    if w <= 2:
                        raise ValueError(
                            f"cohort wave of {w} rejected "
                            f"({dec.reason}) — nothing left to shrink")
                    w = max(2, w // 2)
                    inputs["halved_on"] = dec.reason
                if k == 0:
                    self._prewarm(w)
                wave_items = left[pos:pos + w]
                # overlap: probe wave k+1's headers while this wave
                # decodes/dispatches (join before ITS submit consumes
                # the cache, so entries are never half-written)
                if prefetcher is not None:
                    prefetcher.join()
                nxt = [p for _, p in left[pos + w:pos + 2 * w]]
                if nxt:
                    prefetcher = threading.Thread(
                        target=self._prefetch, args=(nxt,),
                        name="cohort-prefetch", daemon=True)
                    prefetcher.start()
                self.sched.max_jobs = max(2, w)
                self._run_wave(k, w, wave_items, inputs, jps, prov,
                               pos, left)
                pos += w
                k += 1
        finally:
            if prefetcher is not None:
                prefetcher.join()
            runner.count_tap = None
            self.sched.max_jobs, self.sched.mode = (prev_max_jobs,
                                                    prev_mode)
            self._drain_probe_cache()
        return self._summarize(t_run0)

    def _run_wave(self, k: int, w: int,
                  wave_items: List[Tuple[int, str]], inputs: dict,
                  jps: float, prov: dict, pos: int,
                  left: List[Tuple[int, str]]) -> None:
        from ..io.fasta import write_outputs

        runner = self.runner
        reg = runner.registry
        specs = [self._spec(i, p) for i, p in wave_items]
        wobs = obs.prepare_run(config=None)
        # informational (band=0) until the rate is learned: the first
        # wave carries cold start, and a default-priced prediction has
        # no calibration to hold a band against (the serve_batch
        # first-batch precedent)
        rec = wobs.ledger.record(
            "cohort_wave", str(w),
            inputs={**inputs, "wave": k,
                    "jobs_per_sec_est": round(jps, 3)},
            predicted={"sec": w / jps, "jobs_per_sec": jps},
            measured={"sec": {"counters": ["cohort/wave_wall_sec"]},
                      "jobs_per_sec": {
                          "num": ["cohort/wave_jobs"],
                          "den": ["cohort/wave_wall_sec"]}},
            provenance=prov,
            band=0 if (k == 0 or prov.get("source") != "learned")
            else None)
        t0 = time.perf_counter()
        results = runner.submit_jobs(specs)
        wall = max(1e-9, time.perf_counter() - t0)
        n_ok = sum(1 for r in results if r.ok)
        self.samples_done += n_ok
        self.failed += len(results) - n_ok
        self.results.extend(results)
        # concordance back-fill: members the packed path demoted ran
        # serially, so the count tap never saw their partitions — the
        # CPU oracle accumulation supplies them (same counts by the
        # byte-identity contract)
        if self.concordance is not None:
            for spec, r in zip(specs, results):
                if r.ok and not r.resumed \
                        and r.job_id not in self._tapped:
                    try:
                        self._tap(r.job_id, oracle_member_counts(
                            spec.filename, spec.config,
                            backend=runner.backend))
                        reg.add("cohort/concordance_oracle_members", 1)
                    except Exception:
                        reg.add("cohort/concordance_skipped", 1)
        # outputs: journal mode already wrote them at commit; otherwise
        # write per-sample FASTAs here (same writer the CLI uses)
        for spec, r in zip(specs, results):
            if r.ok and not r.resumed and not r.output_paths \
                    and r.fastas is not None:
                write_outputs(r.fastas, spec.config.outfolder,
                              spec.config.prefix, spec.config.nchar,
                              spec.config.thresholds,
                              echo=lambda *a, **kw: None)
        # join the wave's decision against its measured counters, fold
        # the wave-scope instruments into the server aggregate
        wobs.registry.add("cohort/wave_wall_sec", wall)
        wobs.registry.add("cohort/wave_jobs", n_ok)
        ledger_finalize(wobs.ledger, wobs.registry, wobs.tracer)
        self.decisions.append(rec.to_dict())
        try:
            reg.fold(wobs.registry, job_id=f"cohort-w{k}")
        except Exception:
            reg.add("telemetry/fold_failed", 1)
        measured_jps = n_ok / wall
        if n_ok:
            self._jps_ema = measured_jps if self._jps_ema is None \
                else 0.6 * self._jps_ema + 0.4 * measured_jps
            card = rcard.installed()
            if card is not None:
                card.observe("cohort_jobs_per_sec", measured_jps)
        runner._journal_append(
            "cohort_wave", wave=k, jobs=len(results), ok=n_ok,
            wall_sec=round(wall, 4),
            jobs_per_sec=round(measured_jps, 3),
            fingerprint=self.ref_fp)
        # -- live progress (health snapshot + s2c_top) -----------------
        self.waves_done += 1
        remaining = len(left) - pos - w
        self.waves_total_est = self.waves_done \
            + int(math.ceil(remaining / max(1, w)))
        snap_g = reg.snapshot()["gauges"]
        occ = snap_g.get("batch/occupancy_pct", {}).get("value", 0.0)
        rows = snap_g.get("batch/real_rows", {}).get("value", 0.0)
        if rows and results:
            rpm = rows / len(results)
            self._rows_per_member = rpm if not self._rows_per_member \
                else 0.6 * self._rows_per_member + 0.4 * rpm
        self.last_wave = {"wave": k, "jobs": len(results), "ok": n_ok,
                          "wall_sec": round(wall, 3),
                          "jobs_per_sec": round(measured_jps, 3),
                          "occupancy_pct": occ}
        reg.gauge("cohort/waves_done").set(float(self.waves_done))
        reg.gauge("cohort/waves_total").set(float(self.waves_total_est))
        reg.gauge("cohort/samples_done").set(
            float(self.samples_done + self.resumed))
        reg.gauge("cohort/samples_total").set(float(self.samples_total))
        reg.gauge("cohort/jobs_per_sec").set(round(measured_jps, 3))
        reg.gauge("cohort/occupancy_pct").set(occ)
        reg.gauge("cohort/progress").set_info(dict(self.last_wave))
        self.echo(f"cohort wave {k}: {n_ok}/{len(results)} ok in "
                  f"{wall:.2f}s ({measured_jps:.1f} jobs/s, "
                  f"occupancy {occ:.0f}%)")
        if self.wave_hook is not None:
            try:
                self.wave_hook(k)
            except Exception:
                pass

    # -- reporting ---------------------------------------------------------
    def health_summary(self) -> dict:
        """The health snapshot's ``cohort`` section (serve/health.py);
        cheap and lock-free — read by telemetry threads mid-wave."""
        return {
            "samples_total": self.samples_total,
            "samples_done": self.samples_done + self.resumed,
            "resumed": self.resumed,
            "failed": self.failed,
            "waves_done": self.waves_done,
            "waves_total_est": self.waves_total_est,
            "panel_len": self.panel_len,
            "reference_fingerprint": self.ref_fp,
            "admission_trips": self.admission_trips,
            "last_wave": dict(self.last_wave),
        }

    def _summarize(self, t_run0: float) -> dict:
        reg = self.runner.registry
        elapsed = max(1e-9, time.perf_counter() - t_run0)
        summary = {
            "schema": "s2c-cohort/1",
            "samples_total": self.samples_total,
            "samples_ok": self.samples_done,
            "resumed": self.resumed,
            "failed": self.failed,
            "waves": self.waves_done,
            "panel_len": self.panel_len,
            "reference_fingerprint": self.ref_fp,
            "panel_plans": int(reg.value("batch/panel_plans")),
            "panel_reuses": int(reg.value("batch/panel_reuses")),
            "jit_cache_hits": int(reg.value("compile/jit_cache_hit")),
            "jit_cache_misses": int(
                reg.value("compile/jit_cache_miss")),
            "batch_demotions": int(reg.value("batch/demotions")),
            "admission_trips": self.admission_trips,
            "elapsed_sec": round(elapsed, 3),
            "jobs_per_sec": round(self.samples_done / elapsed, 3),
            "decisions": list(self.decisions),
            "concordance": self.concordance.summary()
            if self.concordance is not None else None,
        }
        if self.summary_out:
            from ..observability.telemetry import atomic_write_text

            try:
                atomic_write_text(self.summary_out,
                                  json.dumps(summary, indent=1,
                                             sort_keys=False) + "\n")
            except Exception as exc:
                reg.add("telemetry/write_failed", 1)
                logger.warning("cohort summary write failed: %s", exc)
        return summary
