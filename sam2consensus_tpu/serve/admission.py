"""Admission control: a bounded queue that sheds load instead of dying.

ROADMAP item 2(b): at fleet scale the failure mode of an unbounded
queue is not slowness, it is an OOM'd server taking every queued job
with it — and the failure mode of shared tenancy is one tenant's
degraded jobs dragging the warm device path through retry/demotion
cycles for everyone.  This module makes both decisions explicit and
auditable:

* **bounded queue** — at most ``max_queue`` jobs are admitted per
  submission window (0 = unbounded); overflow is rejected with reason
  ``queue_full`` rather than silently buffered.  Rejection IS the
  backpressure signal: the submitter sees it immediately and can
  re-offer the job later, instead of discovering an hour later that
  the queue never drained;
* **per-tenant quotas** — at most ``tenant_quota`` admitted jobs per
  tenant per window (0 = unbounded), reason ``tenant_quota``: one
  tenant cannot occupy the whole queue;
* **degraded-tenant pinning** — a tenant whose previous job ended on a
  demoted ladder rung (``resilience.ladder.job_rungs``) gets its NEXT
  jobs admitted but PINNED to the host rung
  (``ladder.job_host_rung_config``): the jobs still run — byte
  identity is rung-independent — but they never touch the fleet's
  device path, so a tenant with a poisoned input or a cursed shape
  cannot demote the fleet.  A pinned job that completes cleanly clears
  the tenant back to the fast path (one good job is the probation).

Every decision is a counter: ``serve/admission_admitted``,
``serve/admission_rejected`` (+ ``/<reason>``), ``serve/admission_pinned``
— surfaced through ``publish_stats_extra`` and the manifest ``serve``
section like every other serve counter.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

REASON_QUEUE_FULL = "queue_full"
REASON_TENANT_QUOTA = "tenant_quota"
#: capacity shed (``serve/admission_capacity``): the job's predicted
#: peak host+device bytes (observability/memplane.py capacity model,
#: priced from its header-probed genome length + config) exceeds the
#: server's ``--mem-budget`` — the job is queued-not-OOMed: rejection
#: is the backpressure signal, and the submitter re-offers it to a
#: host that fits (or after raising the budget) instead of discovering
#: the OOM post-mortem
REASON_CAPACITY = "capacity"
#: streaming-session backpressure (serve/stream_server.py): the
#: session's journaled-but-unabsorbed wave backlog is at its bound —
#: the wave is rejected with HTTP 429 + Retry-After instead of being
#: buffered without limit (reject-with-reason, never wedge)
REASON_BACKPRESSURE = "backpressure"


@dataclass
class Decision:
    """One spec's admission verdict.  Pinning is deliberately NOT part
    of this record: it is decided at JOB-START time via
    :meth:`AdmissionController.pin_rung`, so a tenant degraded by an
    earlier job of the same batch still pins the later ones."""

    admitted: bool
    reason: Optional[str] = None        # set iff rejected
    #: capacity-planned mesh scale-up verdict: the job's predicted
    #: peak exceeds one host's ``mem_budget`` but the memory plane's
    #: ``mesh_shards`` plan (observability/memplane.plan_mesh_shards)
    #: fits it on this many hosts — "this job needs K hosts", decided
    #: at admission time instead of discovered as an OOM.  None on
    #: single-host admits and on rejects.
    mesh_shards: Optional[int] = None


@dataclass
class AdmissionController:
    """Window-scoped bounds + queue-lifetime tenant state.

    ``admit`` is called per spec in submission order; ``open_window``
    resets the per-window counts (the serve runner opens one window per
    ``submit_jobs`` batch).  Tenant degradation state intentionally
    SURVIVES windows — that is the isolation story."""

    max_queue: int = 0
    tenant_quota: int = 0
    #: predicted-peak byte budget per job (0 = no capacity gate); see
    #: REASON_CAPACITY.  Parsed with the count-cache size grammar
    #: (``--mem-budget 4G`` / S2C_MEM_BUDGET).
    mem_budget: int = 0
    #: hosts the fleet can dedicate to ONE mesh-sharded job
    #: (S2C_MESH_HOSTS; 0 = no mesh scale-out — over-budget jobs shed
    #: as before).  When > 1, an over-budget job is priced by
    #: ``memplane.plan_mesh_shards`` and admitted with a "needs K
    #: hosts" verdict if its per-host peak fits the budget on
    #: K <= mesh_hosts hosts.
    mesh_hosts: int = 0
    _window_admitted: int = 0
    _window_by_tenant: Dict[str, int] = field(default_factory=dict)
    #: tenant -> rung its last degraded job landed on ("host"/"device_scatter")
    tenant_rungs: Dict[str, str] = field(default_factory=dict)
    #: tenant -> poison submissions (DATA-class failures: blown
    #: bad-record budgets).  Queue-lifetime, like tenant_rungs — but
    #: unlike a degradation rung it never pins anybody (see note_poison)
    poison_by_tenant: Dict[str, int] = field(default_factory=dict)
    #: tenant -> SLO objective breaches (observability/telemetry.py
    #: burn counters, fed by the serve runner per finished job).
    #: Queue-lifetime evidence for admission decisions: surfaced in
    #: the health snapshot and each job's manifest serve.slo verdict,
    #: the base for future burn-rate throttling — like poison, burning
    #: an objective never demotes a tenant's rung by itself (slow is
    #: not broken, and the breach may be the FLEET's queue, not the
    #: tenant's data)
    slo_burn_by_tenant: Dict[str, int] = field(default_factory=dict)
    #: windowed burn view (observability/burn.py BurnMonitor),
    #: attached by the serve runner.  ``slo_burn()`` reads through it
    #: so live consumers (batch priority, health) see breaches DECAY
    #: out of the window instead of the lifetime dict's
    #: breached-once-throttled-forever reads
    burn_monitor: Optional[object] = None

    def slo_burn(self, now: Optional[float] = None) -> Dict[str, int]:
        """Tenant -> recent (slow-window) SLO breach count.  The
        monitor is the truth for every tenant it has observed (so an
        aged-out breach reads as unburnt); lifetime-dict entries for
        tenants the monitor has never seen pass through (bare
        controllers in tests and tools, externally-seeded burn)."""
        mon = self.burn_monitor
        if mon is None:
            return dict(self.slo_burn_by_tenant)
        try:
            out = mon.burn_counts("slow", now=now)
            seen = set(mon.states())
        except Exception:
            return dict(self.slo_burn_by_tenant)
        for t, n in self.slo_burn_by_tenant.items():
            if t not in seen and n > 0:
                out[t] = n
        return out

    def open_window(self) -> None:
        self._window_admitted = 0
        self._window_by_tenant = {}

    def seed_window(self, counts: Dict[str, int]) -> None:
        """Pre-charge the freshly-opened window with jobs the rest of
        the FLEET already has live (journal-visible submitted-not-
        terminal keys of other workers, serve/fleet.py): per-tenant
        quotas then hold against the fleet's queue, not just this
        worker's submission."""
        for tenant, n in counts.items():
            if n <= 0:
                continue
            self._window_admitted += n
            self._window_by_tenant[tenant] = \
                self._window_by_tenant.get(tenant, 0) + n

    def admit(self, tenant: str = "",
              predicted_bytes: Optional[int] = None,
              shard_plan: Optional[dict] = None) -> Decision:
        """One spec's verdict.  ``predicted_bytes`` is the memory
        plane's capacity prediction for the job (None = unpriceable —
        header unreadable; admitted, the serial path surfaces the real
        error): a prediction over ``mem_budget`` sheds the job instead
        of letting it OOM the warm server — UNLESS ``shard_plan`` (the
        memory plane's ``mesh_shards`` verdict,
        ``observability.memplane.plan_mesh_shards``) says the job fits
        sharded across K > 1 hosts, in which case it is admitted with
        ``Decision.mesh_shards = K``: capacity planning replaces
        capacity shedding whenever the fleet has the hosts."""
        if self.max_queue and self._window_admitted >= self.max_queue:
            return Decision(False, reason=REASON_QUEUE_FULL)
        if (self.tenant_quota and tenant
                and self._window_by_tenant.get(tenant, 0)
                >= self.tenant_quota):
            return Decision(False, reason=REASON_TENANT_QUOTA)
        mesh_shards = None
        if (self.mem_budget and predicted_bytes is not None
                and predicted_bytes > self.mem_budget):
            if not (shard_plan and shard_plan.get("fits")
                    and int(shard_plan.get("hosts", 1)) > 1):
                return Decision(False, reason=REASON_CAPACITY)
            mesh_shards = int(shard_plan["hosts"])
        self._window_admitted += 1
        if tenant:
            self._window_by_tenant[tenant] = \
                self._window_by_tenant.get(tenant, 0) + 1
        return Decision(True, mesh_shards=mesh_shards)

    def price_wave(self, tenant: str = "", body_bytes: int = 0,
                   pending_waves: int = 0,
                   max_pending: int = 0) -> Decision:
        """One streaming wave's admission verdict (serve/session.py).

        Waves are NOT window-scoped jobs — a session absorbs thousands
        over its lifetime — so the queue/tenant window counters are
        left alone; the gates that matter here are the session's
        unabsorbed-wave backlog (``max_pending`` -> REASON_BACKPRESSURE,
        the 429 + Retry-After signal) and the same capacity plane the
        job path prices against: a wave whose body alone exceeds the
        server's ``--mem-budget`` could never be absorbed whole."""
        if max_pending and pending_waves >= max_pending:
            return Decision(False, reason=REASON_BACKPRESSURE)
        if self.mem_budget and body_bytes \
                and body_bytes > self.mem_budget:
            return Decision(False, reason=REASON_CAPACITY)
        return Decision(True)

    def price_cohort_wave(self, wave_jobs: int,
                          predicted_bytes: int = 0) -> Decision:
        """One cohort wave's capacity verdict (serve/cohort.py).

        Like :meth:`price_wave`, cohort waves are not window-scoped
        jobs — the queue/tenant window counters are untouched.  The
        single gate is the capacity plane: a wave whose predicted
        combined peak (``memplane.predict_job_peak_bytes`` over the
        wave's combined panel axis) exceeds ``--mem-budget`` would OOM
        the warm server mid-cohort.  The cohort driver SIZES waves so
        this verdict admits (``serve/cohort.size_wave`` binary-searches
        the largest fitting wave), then prices the chosen size here —
        so "no admission trips mid-cohort" is checked, not assumed."""
        if wave_jobs < 1:
            return Decision(False, reason=REASON_CAPACITY)
        if self.mem_budget and predicted_bytes \
                and predicted_bytes > self.mem_budget:
            return Decision(False, reason=REASON_CAPACITY)
        return Decision(True)

    def pin_rung(self, tenant: str) -> Optional[str]:
        """The rung a tenant's next job must run on (None = fast path).
        Consulted at JOB-START time, not admission time — a tenant
        degraded by job k must see job k+1 pinned even when both were
        admitted in the same batch."""
        return self.tenant_rungs.get(tenant) if tenant else None

    def note_poison(self, tenant: str) -> None:
        """Count one poison submission (a job failed DATA-class: blown
        bad-record budget / rotten upload) for the tenant.  Counting is
        ALL this does — a tenant whose data is garbage gets precise
        failure summaries, not a device-rung demotion: the fast path
        would fail the same input no slower, and pinning them to the
        host rung would punish their next (clean) job for their last
        (dirty) one.  The tally is the evidence base for future
        poison-rate throttling at admission time."""
        self.poison_by_tenant[tenant or ""] = \
            self.poison_by_tenant.get(tenant or "", 0) + 1

    def note_slo(self, tenant: str, n_violations: int = 1) -> None:
        """Count SLO objective breaches for the tenant (see
        ``slo_burn_by_tenant``)."""
        if n_violations > 0:
            self.slo_burn_by_tenant[tenant or ""] = \
                self.slo_burn_by_tenant.get(tenant or "", 0) \
                + int(n_violations)

    def note_result(self, tenant: str, rungs: dict, ok: bool,
                    was_pinned: bool) -> None:
        """Feed a finished job's outcome back into tenant state.

        A job that ended demoted marks its tenant degraded (its next
        jobs run pinned).  A PINNED job that completed cleanly is the
        probation pass: the tenant returns to the fast path.  Failed
        pinned jobs stay pinned — the bottom rung failing is not
        evidence the device path would fare better."""
        if not tenant:
            return
        if rungs and not was_pinned:
            # deepest rung wins the record: host < device_scatter
            rung = rungs.get("pileup") or rungs.get("tail") or "host"
            self.tenant_rungs[tenant] = rung
        elif was_pinned and ok:
            self.tenant_rungs.pop(tenant, None)
