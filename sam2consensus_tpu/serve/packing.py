"""Cross-job slab packing: N small jobs' rows in ONE shared dispatch.

The pileup's entire job state is a flat ``[L, 6]`` count tensor and
addition commutes (SURVEY.md §5), so packing is exact by construction:
give each job a disjoint offset window inside one combined position
axis, remap every segment row's flat start by its job's offset, and the
combined tensor's slice ``[off_j, off_j + L_j)`` is bit-for-bit the
count tensor job *j*'s own accumulation would have produced — whatever
order, batching, or device kernel accumulated it.  That one invariant
is what lets the serve scheduler (serve/scheduler.py) ride N queued
small jobs through a single device dispatch sequence and still hand
each job a byte-identical consensus: the per-job tail/render runs the
SAME code path a cold run takes, just over the extracted partition.

This module is the pure layer: offset planning, slab merging, count
extraction, occupancy accounting.  No device work, no scheduling
policy — both live with their owners (ops/pileup.py, serve/scheduler).

Merged slabs stay on the CANONICAL shape grid: encoder bucket widths ×
pow2 row counts.  This module pads rows pow2 with a floor of 8
(:func:`_pad_rows` is the one authoritative statement of that
contract); the accumulator's pad-tail trim then re-rounds each
dispatch to pow2 of the REAL rows (``ops/pileup.py`` ``add``), landing
on the same canonical family ``ops.pileup.canonical_slab_shapes``
enumerates and the serve prewarm compiles — so a packed batch
dispatches shapes the warm server has already compiled: packing
changes how FULL the slabs are, never which programs run.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np

from ..constants import PAD_CODE
from ..encoder.events import SegmentBatch


@dataclass
class PackedMember:
    """One job's slot in a pack plan.  Planned from the HEADER's genome
    length alone (the scheduler probes headers at compose time), so the
    offset table exists before any member decodes — decode and dispatch
    can overlap in waves."""

    job_id: str
    total_len: int
    offset: int = 0            # flat-position base inside the combined axis
    n_events: int = 0          # countable cells this member contributed


@dataclass
class PackPlan:
    """Disjoint offset windows over one combined position axis.

    ``total_len`` is the combined genome length the shared accumulator
    allocates; member *j* owns positions ``[offset_j, offset_j + L_j)``.
    """

    members: List[PackedMember] = field(default_factory=list)
    total_len: int = 0
    # -- merge accounting (filled by merge_batches) -----------------------
    real_rows: int = 0
    padded_rows: int = 0
    merged_slabs: int = 0

    @property
    def occupancy(self) -> float:
        """Real rows / padded rows of the merged slabs (1.0 = no pad)."""
        return (self.real_rows / self.padded_rows) if self.padded_rows \
            else 0.0


def plan_pack(members: Sequence[Tuple[str, int]]) -> PackPlan:
    """Assign each ``(job_id, total_len)`` a disjoint offset window."""
    plan = PackPlan()
    off = 0
    for job_id, total_len in members:
        plan.members.append(PackedMember(job_id=job_id,
                                         total_len=int(total_len),
                                         offset=off))
        off += int(total_len)
    plan.total_len = off
    return plan


def _real_rows(starts: np.ndarray, codes: np.ndarray
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Drop all-PAD rows (the pow2 pad tail, plus any genuinely empty
    encoded row — both contribute zero counts).  Vectorized: one
    first-cell prefilter catches the contiguous pad tail cheaply, the
    full-row scan runs only over the candidates."""
    first = codes[:, 0] == PAD_CODE
    if not first.any():
        return starts, codes
    keep = ~(codes == PAD_CODE).all(axis=1)
    return starts[keep], codes[keep]


def _pad_rows(n: int) -> int:
    """Merged-slab row padding: pow2, floor 8 — the authoritative
    statement of the packing layer's row-padding contract (the module
    docstring defers here).  The accumulator's pad-tail trim re-rounds
    to pow2 of the REAL rows before dispatching anyway (ops/pileup.py
    ``add``), so the dispatch shapes stay on the same canonical grid
    the prewarm compiles — this pad only squares the host array."""
    return 1 << max(3, (max(1, n) - 1).bit_length())


def merge_batches(plan: PackPlan,
                  pairs: Sequence[Tuple[PackedMember,
                                        List[SegmentBatch]]],
                  max_cells: int = 1 << 24) -> List[SegmentBatch]:
    """Remap + merge members' decoded batches into shared slabs.

    ``pairs`` is any subset of the plan's members with their decoded
    batches — the scheduler merges in WAVES (whichever members have
    finished decoding) so dispatch overlaps the remaining decodes.  Per
    bucket width, each member's rows are compacted to real rows, their
    flat starts shifted by the member's offset, concatenated across the
    wave, and re-padded pow2; buckets whose merged row count would
    exceed ``max_cells / width`` split into several slabs (the same
    cell-budget discipline as ``ops.pileup.iter_row_slices``, applied
    at build time so a merged batch cannot pin unbounded host memory).

    Pileup addition commutes, so the merge is byte-exact: the combined
    tensor's member slices equal each member's own accumulation.
    Occupancy (real/padded rows) accumulates into ``plan``.
    """
    by_w: Dict[int, Tuple[List[np.ndarray], List[np.ndarray]]] = {}
    for member, batches in pairs:
        member_events = 0
        for batch in batches:
            if batch.accumulated or not batch.buckets:
                continue
            for w, (starts, codes) in batch.buckets.items():
                starts, codes = _real_rows(np.asarray(starts),
                                           np.asarray(codes))
                if not len(starts):
                    continue
                slist, clist = by_w.setdefault(w, ([], []))
                slist.append(starts.astype(np.int32)
                             + np.int32(member.offset))
                clist.append(codes)
            member_events += batch.n_events
        member.n_events = member_events

    merged: List[SegmentBatch] = []
    for w in sorted(by_w):
        slist, clist = by_w[w]
        starts = np.concatenate(slist) if len(slist) > 1 else slist[0]
        codes = np.concatenate(clist) if len(clist) > 1 else clist[0]
        # rows per slab under the cell budget: align down to 1024-row
        # stripes when the budget allows one, else take the exact row
        # budget (floor 1 row) — a wide bucket must never mint a slab
        # over ``max_cells`` just to reach the alignment stripe
        budget_rows = max(1, max_cells // int(w))
        step = budget_rows // 1024 * 1024 if budget_rows >= 1024 \
            else budget_rows
        for lo in range(0, len(starts), step):
            s = starts[lo:lo + step]
            c = codes[lo:lo + step]
            n = len(s)
            n_pad = _pad_rows(n)
            st = np.zeros(n_pad, dtype=np.int32)
            st[:n] = s
            mat = np.full((n_pad, int(w)), PAD_CODE, dtype=np.uint8)
            mat[:n] = c
            nev = int(n * w - int((c == PAD_CODE).sum()))
            merged.append(SegmentBatch(buckets={int(w): (st, mat)},
                                       n_events=nev))
            plan.real_rows += n
            plan.padded_rows += n_pad
            plan.merged_slabs += 1
    return merged


def extract_counts(plan: PackPlan, combined_counts: np.ndarray
                   ) -> List[np.ndarray]:
    """Slice each member's private count partition out of the combined
    tensor (ONE host fetch upstream, N views here).  Copies: a member's
    tail may narrow/re-upload its partition independently, and the
    combined buffer must stay immutable until every member extracted —
    the count-bank discipline (partitions merged/handed out only after
    the whole dispatch succeeded)."""
    return [extract_member(combined_counts, m) for m in plan.members]


def extract_member(combined_counts: np.ndarray, member: PackedMember
                   ) -> np.ndarray:
    """One member's private partition (a copy — the combined buffer
    stays immutable until every member extracted).  The ONE slicing
    definition, shared by :func:`extract_counts` and the scheduler's
    lazy per-member fallback path."""
    lo = member.offset
    return np.ascontiguousarray(
        combined_counts[lo:lo + member.total_len])


# -- shared-reference cohorts (layout dedup) --------------------------------
def reference_fingerprint(contigs: Iterable) -> str:
    """Order-sensitive fingerprint of a reference set: sha1 over the
    header's (name, length) pairs.  Two inputs with equal fingerprints
    declare byte-identical reference LAYOUTS — same contigs, same
    lengths, same order — which is exactly the condition under which a
    pack plan's offset table can be shared verbatim across jobs
    (offsets are cumulative lengths, nothing else).  Accepts Contig
    objects or plain ``(name, length)`` pairs."""
    import hashlib

    h = hashlib.sha1()
    for c in contigs:
        name = getattr(c, "name", None)
        if name is None:
            name, length = c
        else:
            length = c.length
        h.update(str(name).encode("utf-8", "replace"))
        h.update(b"\x00")
        h.update(str(int(length)).encode("ascii"))
        h.update(b"\x00")
    return h.hexdigest()[:16]


@dataclass
class PanelGeometry:
    """ONE canonical slab geometry for a shared-reference cohort.

    When every member of a batch targets the same reference panel
    (equal :func:`reference_fingerprint`, hence equal ``total_len``),
    the offset table degenerates to ``k * panel_len`` — so the
    geometry is planned ONCE and every subsequent wave reuses the
    cached table by prefix (a wave of ``n <= max_jobs`` members takes
    ``offsets[:n]``).  ``plans_built`` / ``reuses`` are the re-plan
    evidence the cohort bench gates on: after wave 1, ``plans_built``
    stays at 1 and every wave increments ``reuses``."""

    fingerprint: str
    panel_len: int
    max_jobs: int
    offsets: Tuple[int, ...] = ()
    plans_built: int = 0
    reuses: int = 0

    def __post_init__(self) -> None:
        if not self.offsets:
            self.offsets = tuple(k * int(self.panel_len)
                                 for k in range(int(self.max_jobs)))

    def plan_wave(self, job_ids: Sequence[str]) -> PackPlan:
        """A wave's :class:`PackPlan` from the cached offset table.

        Fresh :class:`PackedMember` objects each call (the scheduler
        mutates ``n_events`` per wave), but zero re-planning: offsets
        come straight from the table built at construction."""
        if len(job_ids) > self.max_jobs:
            raise ValueError(
                f"wave of {len(job_ids)} members exceeds the panel "
                f"geometry's {self.max_jobs}-job table")
        if self.plans_built:
            self.reuses += 1
        else:
            self.plans_built = 1
        plan = PackPlan(total_len=len(job_ids) * self.panel_len)
        for k, job_id in enumerate(job_ids):
            plan.members.append(PackedMember(job_id=job_id,
                                             total_len=self.panel_len,
                                             offset=self.offsets[k]))
        return plan
