"""Network front door for streaming sessions: a fault-tolerant HTTP/1.1
ingest endpoint.

Rides the TelemetryServer pattern (observability/telemetry.py): stdlib
``ThreadingHTTPServer`` bound to 127.0.0.1 only (``--ingest-port``; 0 =
ephemeral, ``.port`` holds the real one), daemon handler threads, a
handler body that catches everything — one broken request can never
kill the server.  What it adds over the scrape endpoint is everything a
front door facing real (slow, buggy, malicious) clients needs:

* **POST bodies**, both ``Content-Length`` and ``Transfer-Encoding:
  chunked`` (decoded manually — live basecallers stream waves without
  knowing their size up front);
* **bounded requests** — a declared or actual body over
  ``max_body`` answers 413 before buffering the excess;
* **slow-client timeouts** — a per-request socket deadline
  (``timeout``): a client that stops mid-body answers 408 and frees
  the handler thread instead of wedging it forever;
* **typed failures** — every rejection is a JSON body with a
  machine-readable ``reason`` and the right status: 400 malformed
  framing, 404 unknown session, 405 wrong method, 408 slow client,
  409 closed session / lost lease, 413 oversized, 422 DATA-class
  poison wave (quarantined, never retried), 429 + ``Retry-After``
  admission backpressure, 503 transient absorb failure.  Rejecting
  with a reason IS the backpressure signal — the server never wedges;
* **keep-alive framing safety** — an error answered before the
  request body was fully consumed closes the connection instead of
  letting the unread bytes desync the next request on the socket;
* the ``ingest_conn`` fault site fires per request (the chaos
  harness's handle on torn connections).

Routes::

    POST /session/open          body = SAM header  -> {sid}
    POST /session/<sid>/wave    body = read lines  -> wave ACK
    POST /session/<sid>/revote                     -> {digest, stable}
    POST /session/<sid>/close                      -> final outputs
    GET  /session/<sid>                            -> status JSON
    GET  /sessions                                 -> health summary

Headers: ``X-Tenant`` labels the session at open; ``X-Wave-Sha256``
lets a client declare the wave body's hash — a mismatch is rejected
422 (the torn-upload gate) instead of being absorbed.
"""

from __future__ import annotations

import json
import logging
import socket
import threading
from typing import Optional

from .session import SessionError, SessionManager

logger = logging.getLogger("sam2consensus_tpu.serve.stream_server")

#: request body bound (bytes); --ingest-max-body overrides
DEFAULT_MAX_BODY = 64 * 1024 * 1024
#: per-request socket deadline (seconds); --ingest-timeout overrides
DEFAULT_TIMEOUT_S = 10.0
#: per-chunk-size-line bound: a chunked framing line longer than this
#: is not a hex size, it is garbage (or an attack)
_MAX_CHUNK_LINE = 64


class RequestError(Exception):
    """Typed framing/transport failure, mapped straight to a status."""

    def __init__(self, status: int, reason: str, detail: str = ""):
        super().__init__(detail or reason)
        self.status = int(status)
        self.reason = reason


def _read_exact(rfile, n: int) -> bytes:
    """Read exactly ``n`` bytes or raise the truncated-body error —
    a short read is a client that died mid-wave, not a wave."""
    out = b""
    while len(out) < n:
        chunk = rfile.read(n - len(out))
        if not chunk:
            raise RequestError(400, "truncated_body",
                               f"body ended after {len(out)} of {n} "
                               f"bytes")
        out += chunk
    return out


def read_chunked(rfile, max_body: int) -> bytes:
    """Manual ``Transfer-Encoding: chunked`` decode, size-bounded.

    Malformed framing (non-hex size line, missing CRLF, truncation) is
    a 400; exceeding ``max_body`` is a 413 raised BEFORE buffering the
    offending chunk."""
    body = b""
    while True:
        line = rfile.readline(_MAX_CHUNK_LINE + 2)
        if not line.endswith(b"\n"):
            raise RequestError(400, "bad_chunk_size",
                               "chunk-size line unterminated or over "
                               f"{_MAX_CHUNK_LINE} bytes")
        token = line.strip().split(b";")[0]     # ignore extensions
        try:
            size = int(token, 16)
        except ValueError:
            raise RequestError(
                400, "bad_chunk_size",
                f"chunk-size line {token[:32]!r} is not hex") from None
        if size < 0:
            raise RequestError(400, "bad_chunk_size", "negative size")
        if size == 0:
            # trailer section: consume until the blank line
            while True:
                t = rfile.readline(_MAX_CHUNK_LINE + 2)
                if t in (b"\r\n", b"\n", b""):
                    break
            return body
        if len(body) + size > max_body:
            raise RequestError(413, "body_too_large",
                               f"chunked body exceeds {max_body} bytes")
        body += _read_exact(rfile, size)
        crlf = _read_exact(rfile, 2)
        if crlf not in (b"\r\n",):
            raise RequestError(400, "bad_chunk_framing",
                               "chunk data not CRLF-terminated")


class IngestServer:
    """The streaming-session front door (see the module docstring)."""

    def __init__(self, manager: SessionManager, port: int = 0,
                 max_body: int = DEFAULT_MAX_BODY,
                 timeout: float = DEFAULT_TIMEOUT_S):
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        outer = self
        self.manager = manager
        self.registry = manager.registry
        self.max_body = int(max_body)
        self.timeout = float(timeout)

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            # -- plumbing ---------------------------------------------
            def _reply(self, status: int, payload: dict,
                       retry_after: Optional[float] = None) -> None:
                body = (json.dumps(payload, default=str) + "\n") \
                    .encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type",
                                 "application/json; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                if retry_after is not None:
                    self.send_header("Retry-After",
                                     str(max(1, int(retry_after))))
                self.end_headers()
                self.wfile.write(body)

            def _error(self, status: int, reason: str, detail: str = "",
                       retry_after: Optional[float] = None) -> None:
                # an error answered BEFORE the request body was fully
                # consumed leaves its unread bytes on the socket; on a
                # keep-alive connection the next "request" would be
                # parsed out of those leftovers (a 400 cascade), so
                # the connection must close instead of desyncing
                if not getattr(self, "_body_done", True):
                    self.close_connection = True
                outer.registry.add("ingest/rejected", 1)
                outer.registry.add(f"ingest/rejected/{reason}", 1)
                self._reply(status, {"error": reason,
                                     "detail": detail or reason},
                            retry_after=retry_after)

            def _read_body(self) -> bytes:
                te = (self.headers.get("Transfer-Encoding") or "") \
                    .lower()
                if "chunked" in te:
                    body = read_chunked(self.rfile, outer.max_body)
                    self._body_done = True
                    return body
                cl = self.headers.get("Content-Length")
                if cl is None:
                    raise RequestError(
                        400, "length_required",
                        "POST needs Content-Length or chunked "
                        "transfer-encoding")
                try:
                    n = int(cl)
                except ValueError:
                    raise RequestError(
                        400, "bad_content_length",
                        f"Content-Length {cl!r} is not an "
                        f"integer") from None
                if n < 0:
                    raise RequestError(400, "bad_content_length",
                                       "negative Content-Length")
                if n > outer.max_body:
                    raise RequestError(
                        413, "body_too_large",
                        f"declared {n} bytes exceeds the "
                        f"{outer.max_body}-byte wave bound")
                body = _read_exact(self.rfile, n)
                self._body_done = True
                return body

            def _drain_body(self) -> None:
                """Consume a (possibly present) body on verbs that
                take none, so a keep-alive connection stays framed."""
                if "Content-Length" in self.headers \
                        or "Transfer-Encoding" in self.headers:
                    self._read_body()
                else:
                    self._body_done = True

            # -- routes -----------------------------------------------
            def do_POST(self):          # noqa: N802 (stdlib name)
                self._body_done = False     # set by a complete read
                try:
                    self.connection.settimeout(outer.timeout)
                    outer.registry.add("ingest/requests", 1)
                    outer.manager.runner._fault_check("ingest_conn")
                    parts = [p for p in
                             self.path.split("?")[0].split("/") if p]
                    if not parts or parts[0] != "session":
                        self._error(404, "not_found",
                                    f"no such route {self.path!r}")
                        return
                    if parts[1:] == ["open"]:
                        body = self._read_body()
                        outer.registry.add("ingest/bytes", len(body))
                        res = outer.manager.open_session(
                            body.decode("utf-8", errors="strict"),
                            tenant=self.headers.get("X-Tenant", ""))
                        self._reply(200, res)
                        return
                    if len(parts) != 3:
                        self._error(404, "not_found",
                                    f"no such route {self.path!r}")
                        return
                    sid, verb = parts[1], parts[2]
                    if verb == "wave":
                        body = self._read_body()
                        outer.registry.add("ingest/bytes", len(body))
                        res = outer.manager.receive_wave(
                            sid, body,
                            declared_sha=self.headers.get(
                                "X-Wave-Sha256"))
                        self._reply(
                            202 if res.get("status") == "pending"
                            else 200, res)
                    elif verb == "revote":
                        self._drain_body()
                        self._reply(200, outer.manager.revote(sid))
                    elif verb == "close":
                        self._drain_body()
                        self._reply(200,
                                    outer.manager.close_session(sid))
                    else:
                        self._error(404, "not_found",
                                    f"no session verb {verb!r}")
                except SessionError as exc:
                    self._safe_error(exc.status, exc.reason, str(exc),
                                     retry_after=exc.retry_after)
                except RequestError as exc:
                    self._safe_error(exc.status, exc.reason, str(exc))
                except (socket.timeout, TimeoutError):
                    outer.registry.add("ingest/slow_clients", 1)
                    self._safe_error(408, "slow_client",
                                     f"no bytes within "
                                     f"{outer.timeout:g}s")
                except UnicodeDecodeError as exc:
                    self._safe_error(422, "bad_encoding", str(exc))
                except Exception as exc:   # never kill the server
                    logger.warning("ingest request failed (%s: %s)",
                                   type(exc).__name__, exc)
                    self._safe_error(500, "internal",
                                     f"{type(exc).__name__}: {exc}")

            def do_GET(self):           # noqa: N802 (stdlib name)
                self._body_done = False
                try:
                    self.connection.settimeout(outer.timeout)
                    self._drain_body()  # a GET with a body stays framed
                    parts = [p for p in
                             self.path.split("?")[0].split("/") if p]
                    if parts == ["sessions"]:
                        self._reply(
                            200, outer.manager.health_summary())
                    elif len(parts) == 2 and parts[0] == "session":
                        self._reply(200,
                                    outer.manager.status(parts[1]))
                    else:
                        self._error(404, "not_found",
                                    f"no such route {self.path!r}")
                except SessionError as exc:
                    self._safe_error(exc.status, exc.reason, str(exc))
                except RequestError as exc:
                    self._safe_error(exc.status, exc.reason, str(exc))
                except Exception as exc:
                    self._safe_error(500, "internal",
                                     f"{type(exc).__name__}: {exc}")

            def _safe_error(self, status, reason, detail,
                            retry_after=None):
                """Answer an error on a socket that may already be
                dead — the client tearing its connection mid-reply
                must not take the handler (or server) down."""
                try:
                    self._error(status, reason, detail,
                                retry_after=retry_after)
                except Exception:
                    self.close_connection = True

            def do_PUT(self):           # noqa: N802
                self._body_done = False
                try:
                    self.connection.settimeout(outer.timeout)
                    self._drain_body()  # keep the connection framed
                except Exception:
                    pass                # _error closes it instead
                self._safe_error(405, "method_not_allowed",
                                 "use POST/GET")

            do_DELETE = do_PATCH = do_HEAD = do_PUT

            def log_message(self, *a):  # waves are not stderr news
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_port
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="s2c-ingest-http")
        self._thread.start()
        logger.info("streaming ingest endpoint on 127.0.0.1:%d",
                    self.port)

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
