"""Warm-path serving: one persistent backend across many consensus jobs.

The reference is a one-shot CLI — one process, one SAM file — and every
prior round inherited that shape, so each job re-paid the whole warmup
bill: jit trace/compile per slab shape, the link probe, the native
extension's staleness check, interpreter + jax import.  On the small
BENCH configs that fixed cost exceeds the actual work (the rows are
"oracle-noise-bound"); at the ROADMAP's serving scale it is pure waste
multiplied by every request.  This package makes the WARM path the
common path:

* :class:`.runner.ServeRunner` / :func:`submit_jobs` — a persistent
  multi-job runner (``s2c serve`` CLI entry, ``sam2consensus_tpu.cli``)
  that keeps one :class:`~..backends.jax_backend.JaxBackend` alive
  across jobs.  Job N+1's host decode/encode runs on a decode-ahead
  thread while job N's device work is in flight, with the measured
  intersection published as ``serve/overlap_sec`` — cross-job overlap
  is a number in each job's registry/manifest, not an assumption;
* shape-bucket-aware jit reuse — the canonical slab shapes
  (``ops.pileup.canonical_slab_shapes``) are prewarmed once per server
  lifetime (optionally behind the first job's decode), and every pileup
  dispatch is classified ``compile/jit_cache_{hit,miss}``
  (``observability/jitcache.py``), so amortization is proven per job;
* per-job scoping — each job gets its OWN metrics registry, tracer,
  decision ledger and manifest (``observability.prepare_run`` +
  thread-local binding for the decode-ahead thread), and its own
  resilience ladder/fault-injection scope: a fault in one job demotes
  only that job's rungs and the next job starts back on the fast path,
  warm.

Failure semantics: a job that raises is returned as a failed
:class:`JobResult` (``error`` set, ``fastas`` None) and the server
stays warm for the remaining queue; nothing a failing job demoted or
configured (ladder rung, fault spec, registry) outlives its run.

Survivability layer (the serve-level analogue of PR 2's device-path
resilience; all opt-in):

* :mod:`.journal` — a crash-safe job journal (append-only JSONL over
  atomic tmp+rename segments): ``s2c serve --journal DIR`` survives
  ``kill -9`` mid-queue, skipping committed jobs by output fingerprint
  and resuming the in-flight job from its per-job checkpoint — zero
  lost, zero duplicated jobs;
* per-job deadlines + a hung-dispatch watchdog (``--job-timeout`` /
  S2C_JOB_TIMEOUT, ``--stall-timeout`` / S2C_STALL_TIMEOUT): a wedged
  XLA dispatch or stuck decode-ahead thread fails ONLY its job
  (classified via resilience/policy.py; under ``--on-device-error
  fallback`` the job retries once on the ladder's host rung) while the
  server keeps draining;
* :mod:`.admission` — bounded-queue admission control with
  reject-with-reason (``serve/admission_*`` counters), per-tenant
  quotas, and degraded-tenant pinning (``JobSpec.tenant``) so one
  tenant's cursed inputs never demote the fleet;
* :mod:`.health` — an atomic health/readiness snapshot
  (``--health-out``; also embedded in each job's manifest ``serve``
  section): queue depth, in-flight job, heartbeat age, per-tenant
  rungs, journal position — rewritten on the watchdog heartbeat
  cadence as well as at job boundaries, so it stays fresh while a job
  hangs.

Fleet telemetry plane (``observability/telemetry.py``, wired through
the runner): a server-lifetime AggregateRegistry per-job registries
fold into, per-tenant per-phase SLO histograms + burn counters
(``--slo``), an OpenMetrics exposition (``--telemetry-out`` /
``--telemetry-port`` ``/metrics``+``/healthz``), on-demand profiler
capture (SIGUSR2 / ``capture_profile`` touch-file), and correlated
JSON logs (``--log-format json``).  All best-effort: telemetry never
fails a job.

Fleet mode (:mod:`.fleet`, ``--worker-id W --lease-ttl S`` on a
``--journal`` server): N worker processes share ONE journal as a
work-stealing queue — jobs are claimed through atomic first-writer-
wins journal events, leases carry a TTL renewed on the watchdog tick,
and each worker reaps peers' expired leases so a SIGKILL'd or frozen
worker's in-flight job is re-claimed from its checkpoint with zero
lost / zero duplicated jobs (a worker re-confirms its lease before
committing, so a woken zombie abandons rather than double-commits).

Continuous batching (:mod:`.scheduler` + :mod:`.packing`,
``--batch {off,auto,N}`` / ``--batch-window``): the admission queue's
eligible small jobs are packed into shared canonical slabs so N jobs
ride one device dispatch sequence, with per-job count partitions
extracted for byte-identical per-job consensus, per-job
observability/journal/SLO scoping intact, and any fault inside a
packed phase demoting only that batch back to the serial path.

Streaming sessions (:mod:`.session` + :mod:`.stream_server`,
``--ingest-port P`` on a ``--journal`` server): long-lived per-tenant
consensus sessions fed by live read *waves* over a fault-tolerant
HTTP ingest endpoint.  Every wave is journaled as durable intent
BEFORE it is ACKed, absorbed exactly once through a checkpoint-shaped
seed/capture handoff (any mid-wave fault invalidates and replays the
wave whole — the count-bank rule), re-voted on a debounced cadence,
and watched for early stability: a consensus digest unchanged N
consecutive waves emits the read-until verdict so the basecaller can
stop sequencing.  Sessions are journal entities under the same
claim/lease semantics as fleet jobs — a SIGKILL'd worker's open
sessions are stolen lease-and-all by a peer that replays every
journaled-but-unabsorbed wave: zero lost, zero double-counted reads.
"""

from .admission import AdmissionController
from .countcache import CountCache, parse_budget, reference_key
from .fleet import FleetCoordinator
from .health import snapshot as health_snapshot
from .journal import JobJournal, job_key
from .packing import (PackPlan, extract_counts, extract_member,
                      merge_batches, plan_pack)
from .runner import JobResult, JobSpec, ServeRunner, submit_jobs
from .scheduler import BatchScheduler, parse_batch_mode
from .session import SessionError, SessionManager, consensus_digest
from .stream_server import IngestServer

__all__ = ["JobSpec", "JobResult", "ServeRunner", "submit_jobs",
           "JobJournal", "job_key", "AdmissionController",
           "health_snapshot", "BatchScheduler", "parse_batch_mode",
           "PackPlan", "plan_pack", "merge_batches", "extract_counts",
           "extract_member", "CountCache", "parse_budget",
           "reference_key", "FleetCoordinator", "SessionManager",
           "SessionError", "IngestServer", "consensus_digest"]
