"""Health/readiness snapshot of a serve runner.

One JSON-shaped answer to "is this server alive and where is it?" —
the thing an external prober, a fleet scheduler, or a human with a
wedged queue actually needs, assembled from state the runner already
keeps:

* queue depth and the in-flight job (id + how long it has been
  running);
* last-heartbeat age — the newest of job-start / dispatch-interval /
  job-end timestamps; a growing age with an in-flight job is the
  wedged-dispatch signature the watchdog acts on;
* per-tenant ladder rungs (admission control's isolation state);
* journal position (last seq, committed/inflight counts) when a
  journal is attached;
* lifetime job counts and the admission counters.

Exposure: ``s2c serve --health-out PATH`` rewrites the snapshot
atomically (tmp + ``os.replace``, so a reader never sees a torn file)
at queue start, after every job, and at queue end; the same snapshot
is embedded in each job's manifest ``serve`` section via the
``serve/health`` gauge.  Schema ``s2c-health/1``; consumers must
tolerate added keys.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Optional

SCHEMA = "s2c-health/1"


@dataclass
class HealthState:
    """The runner-side mutable state snapshots are cut from.

    ``beat()`` timestamps use ``time.monotonic`` (ages must survive
    wall-clock jumps); ``started_unix`` is wall-clock for humans."""

    started_unix: float = field(default_factory=time.time)
    _started_mono: float = field(default_factory=time.monotonic)
    queue_depth: int = 0
    in_flight: Optional[str] = None
    in_flight_since: Optional[float] = None     # monotonic
    last_beat: float = field(default_factory=time.monotonic)

    def beat(self) -> None:
        self.last_beat = time.monotonic()

    def job_started(self, job_id: str) -> None:
        self.in_flight = job_id
        self.in_flight_since = time.monotonic()
        self.beat()

    def job_finished(self) -> None:
        self.in_flight = None
        self.in_flight_since = None
        self.beat()


def snapshot(runner) -> dict:
    """Cut a health snapshot from a :class:`~.runner.ServeRunner`."""
    h = runner.health
    now = time.monotonic()
    reg = runner.registry
    # single read before the None test: telemetry HTTP threads cut
    # snapshots concurrently with the main thread's job_finished()
    # clearing the field — a check-then-read pair would 500 a scrape
    # that races a job boundary
    since = h.in_flight_since
    snap = {
        "schema": SCHEMA,
        "created_unix": round(time.time(), 3),
        "uptime_sec": round(now - h._started_mono, 3),
        "queue_depth": h.queue_depth,
        "in_flight": h.in_flight,
        "in_flight_sec": round(now - since, 3)
        if since is not None else None,
        "last_heartbeat_age_sec": round(now - h.last_beat, 3),
        "jobs": {
            "run": int(reg.value("serve/jobs")),
            "failed": int(reg.value("serve/jobs_failed")),
            "resumed_skipped": int(reg.value("serve/resume_skipped")),
            "watchdog_timeouts": int(reg.value("serve/watchdog_timeouts")),
            "retries": int(reg.value("serve/job_retries")),
        },
        "admission": {
            "admitted": int(reg.value("serve/admission_admitted")),
            "rejected": int(reg.value("serve/admission_rejected")),
            "pinned": int(reg.value("serve/admission_pinned")),
            # poison submissions (DATA class: blown bad-record budgets);
            # counted per tenant WITHOUT device-rung demotion
            "poison": int(reg.value("serve/admission_poison")),
            # capacity sheds: predicted peak > --mem-budget
            # (observability/memplane.py) — queued-not-OOMed
            "capacity": int(reg.value("serve/admission_capacity")),
        },
        # tolerant decode across the queue + the last job's verdict
        # (per-job history rides each JobResult / job manifest)
        "bad_records": int(reg.value("serve/bad_records")),
        "last_job": getattr(runner, "last_job_badrec", None),
        "poison_by_tenant": dict(runner.admission.poison_by_tenant),
        "tenant_rungs": dict(runner.admission.tenant_rungs),
        "journal": runner.journal.position()
        if runner.journal is not None else None,
    }
    # fleet mode (serve/fleet.py): which worker this snapshot belongs
    # to, plus its lease book — held leases with renewal ages, the
    # reap/steal tallies.  tools/s2c_top.py --fleet merges N of these
    # into one view; a lease whose last_renew_age_sec approaches the
    # TTL is the about-to-be-reaped signature.
    if getattr(runner, "worker_id", ""):
        snap["worker_id"] = runner.worker_id
        fl = getattr(runner, "fleet", None)
        if fl is not None:
            snap["lease"] = fl.lease_summary()
    # fleet telemetry (observability/telemetry.py): the SLO burn and
    # the telemetry plane's own health, so a prober without a
    # Prometheus stack still sees objective breaches
    # continuous batching (serve/scheduler.py): current policy + the
    # last batch's shape, so an operator (or tools/s2c_top.py) sees the
    # packing state without a Prometheus stack
    sched = getattr(runner, "scheduler", None)
    if sched is not None and sched.enabled:
        g = reg.snapshot()["gauges"]
        snap["batch"] = {
            "mode": sched.mode,
            "max_jobs": sched.max_jobs,
            "window_ms": sched.window_ms,
            "batches": int(reg.value("batch/batches")),
            "packed_jobs": int(reg.value("batch/packed_jobs")),
            "demotions": int(reg.value("batch/demotions")),
            "last_size": int(g.get("batch/size", {}).get("value", 0)),
            "last_occupancy_pct": g.get("batch/occupancy_pct",
                                        {}).get("value", 0.0),
            "last_jobs_per_sec": g.get("batch/jobs_per_sec",
                                       {}).get("value", 0.0),
        }
    # flight recorder (observability/flight.py): journal-measured
    # scheduler telemetry — queue-wait / claim / steal summaries per
    # tenant ride the s2c_sched_* exposition; here the prober-visible
    # synopsis (occupancy, churn, last lifecycle) plus the telemetry
    # interval s2c_top --fleet uses to age-flag stale workers
    reg_snap = reg.snapshot()
    sched_hists = {name: entry for name, entry
                   in reg_snap["histograms"].items()
                   if name.startswith("sched/")}
    churn = reg.value("sched/lease_churn")
    occ = reg_snap["gauges"].get("sched/occupancy_ratio",
                                 {}).get("value", 0.0)
    snap["sched"] = {
        "telemetry_interval_sec": getattr(
            runner, "telemetry_interval", None),
        "occupancy_ratio": occ,
        "lease_churn": int(churn),
        "queue_wait": {
            name.split("/", 2)[1] or "default": {
                "count": entry["count"],
                "p50_sec": round(entry["p50"], 4),
                "p95_sec": round(entry["p95"], 4)}
            for name, entry in sorted(sched_hists.items())
            if name.endswith("/queue_wait")},
        "steals_measured": {
            name.split("/", 2)[1] or "default": {
                "count": entry["count"],
                "max_sec": round(entry["max"], 3)}
            for name, entry in sorted(sched_hists.items())
            if name.endswith("/steal_latency")},
    }
    # incremental consensus (serve/countcache.py): the per-reference
    # count cache's residency + hit/evict story, mirrored from the
    # s2c_cache_* exposition family for probers without a scraper
    cc = getattr(runner, "count_cache", None)
    if cc is not None:
        snap["count_cache"] = cc.stats()
    # streaming sessions (serve/session.py): open sessions, wave
    # absorb/reject tallies, stability verdicts and last-wave ages —
    # the prober's view of the live-ingest plane.  A session whose
    # last_wave_age_sec keeps growing while open is a stalled
    # basecaller, not a stalled server (the ingest endpoint answers
    # per request; nothing here blocks)
    smgr = getattr(runner, "sessions", None)
    if smgr is not None:
        snap["sessions"] = smgr.health_summary()
    # cohort serving (serve/cohort.py): manifest progress — waves
    # done/estimated, samples done/total, last wave's rate + occupancy
    # — the prober's (and s2c_top's) view of a streaming cohort.
    # Guarded like every optional section: a cohort mid-teardown must
    # never 500 a health scrape
    cohort = getattr(runner, "cohort", None)
    if cohort is not None:
        try:
            snap["cohort"] = cohort.health_summary()
        except Exception:
            pass
    slo_obj = getattr(runner, "slo", None)
    if slo_obj or reg.value("slo/violations"):
        # windowed burn read when the runner attached a monitor: a
        # breach that aged out of the slow window stops reading as
        # "burning" here (the lifetime dict never decayed)
        slo_burn = getattr(runner.admission, "slo_burn", None)
        snap["slo"] = {
            "objectives": dict(slo_obj or {}),
            "violations": int(reg.value("slo/violations")),
            "burn_by_tenant": dict(slo_burn()) if callable(slo_burn)
            else dict(getattr(
                runner.admission, "slo_burn_by_tenant", {})),
        }
    # burn-alert plane (observability/burn.py): per-tenant ok/warn/
    # page with the fast/slow window ratios behind the verdict — only
    # present once any job was scored against an objective
    burn = getattr(runner, "burn", None)
    if burn is not None:
        bsnap = burn.snapshot()
        if bsnap.get("tenants"):
            snap["burn"] = bsnap
    # rate-card plane (observability/ratecard.py): this worker's
    # learned throughput constants + confidence verdicts, and the
    # latest evidence-only fleet scale hint when one was computed
    card = getattr(runner, "ratecard", None)
    if card is not None:
        csnap = card.snapshot()
        if csnap.get("rates") or csnap.get("restarts"):
            snap["ratecard"] = csnap
    hint = getattr(runner, "last_scale_hint", None)
    if hint is not None:
        snap["scale_hint"] = dict(hint)
    # memory plane (observability/memplane.py): per-family live/peak +
    # process/device watermarks, so a prober (or tools/s2c_top.py)
    # sees residency without a Prometheus stack; the OOM-forensics
    # tally rides along when any dump was written
    from ..observability import memplane

    snap["memory"] = memplane.summary()
    # mesh plane (parallel/partition.py): topology of the active
    # sharded mesh + the admission-time capacity plan — only present
    # once a sharded accumulator ran or a mesh_shards verdict fired,
    # so single-host servers keep their old snapshot shape
    g = reg_snap["gauges"]
    if ("mesh/shards" in g or "mesh/planned_hosts" in g
            or runner.admission.mesh_hosts):
        shard_bytes = {
            name.rsplit("/", 1)[1]: int(value)
            for name, value in reg_snap["counters"].items()
            if name.startswith("mesh/shard_bytes/")}
        snap["mesh"] = {
            "hosts": int(g.get("mesh/hosts", {}).get("value", 1)),
            "shards": int(g.get("mesh/shards", {}).get("value", 0)),
            "mesh_hosts_capacity": int(runner.admission.mesh_hosts),
            "planned_hosts": int(g.get("mesh/planned_hosts",
                                       {}).get("value", 0)) or None,
            "admitted_mesh": int(reg.value("serve/admission_mesh")),
            "shard_bytes_by_host": shard_bytes,
            "gather_bytes": int(reg.value("mesh/gather_bytes")),
        }
    if runner.admission.mem_budget:
        snap["memory"]["mem_budget_mb"] = round(
            runner.admission.mem_budget / 1e6, 1)
    if reg.value("serve/oom_dumps"):
        snap["memory"]["oom_dumps"] = int(reg.value("serve/oom_dumps"))
        snap["memory"]["last_oom_dump"] = reg.info("serve/last_oom_dump")
    prof = getattr(runner, "profiler", None)
    if prof is not None and (prof.captures
                             or reg.value("telemetry/write_failed")):
        snap["telemetry"] = {
            "profile_captures": prof.captures,
            "last_profile": prof.last_path,
            "write_failed": int(reg.value("telemetry/write_failed")),
        }
    return snap


def write_health(path: str, snap: dict) -> None:
    """Atomic rewrite: a prober polling the file never reads half a
    snapshot.  Delegates to the ONE shared writer
    (:func:`~..observability.telemetry.atomic_write_text`) the
    exposition file and journal segments also use."""
    from ..observability.telemetry import atomic_write_text

    atomic_write_text(path, json.dumps(snap, indent=1,
                                       sort_keys=False) + "\n")
