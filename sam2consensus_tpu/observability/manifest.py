"""Self-describing per-run manifest: every number traceable to its inputs.

VERDICT r5's recurring finding was headline claims with no committed
artifact tying them to the constants that produced them — a bench row
says 19.4x, but WHICH link constants priced its placement decisions,
which env overrides were live, which git state ran?  The manifest
answers that in one JSON blob written alongside ``--metrics-out``
(``<metrics_out>.manifest.json``) and embedded (summarized) in bench
rows:

* the run config (the full RunConfig dataclass, JSON-shaped);
* every live ``S2C_*`` / ``JAX_PLATFORMS`` / ``XLA_FLAGS`` env
  override — the invisible inputs that flip gate decisions;
* the link constants the placement models priced with, their source
  (probed / env / stale-cache / default) and measured-at age;
* every ledger decision with its prediction, measured outcome,
  residual and drift verdict (observability/ledger.py);
* the phase/wire counter summary and any drift events;
* ``git describe`` of the running tree and sha256 hashes of the trace
  / metrics artifacts the same run wrote.

Schema id ``s2c-manifest/1``; consumers must tolerate added keys.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import time
from typing import List, Optional

SCHEMA = "s2c-manifest/1"

#: env prefixes that are model/gate inputs — recorded verbatim so a
#: committed artifact shows every constant override that was live
_ENV_PREFIXES = ("S2C_",)
_ENV_EXACT = ("JAX_PLATFORMS", "XLA_FLAGS")

_git_cache: List[Optional[str]] = []


def git_describe() -> Optional[str]:
    """``git describe --always --dirty`` of the repo this package runs
    from (cached per process; None outside a work tree)."""
    if _git_cache:
        return _git_cache[0]
    out: Optional[str] = None
    try:
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        r = subprocess.run(
            ["git", "describe", "--always", "--dirty", "--tags"],
            capture_output=True, text=True, timeout=5, cwd=root)
        if r.returncode == 0:
            out = r.stdout.strip() or None
    except Exception:
        out = None
    _git_cache.append(out)
    return out


def env_overrides() -> dict:
    return {k: os.environ[k] for k in sorted(os.environ)
            if k.startswith(_ENV_PREFIXES) or k in _ENV_EXACT}


def file_digest(path: str) -> Optional[str]:
    try:
        h = hashlib.sha256()
        with open(path, "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                h.update(chunk)
        return "sha256:" + h.hexdigest()
    except OSError:
        return None


def _link_section(snap: dict) -> dict:
    """Link-constant provenance: probe state (utils/linkprobe) plus the
    run's recorded link gauges."""
    from ..utils import linkprobe

    link = dict(linkprobe.link_info())
    for g in ("link/rt_sec", "link/bps", "link/stale", "link/stale_age",
              "link/probe_failed"):
        entry = snap["gauges"].get(g)
        if entry is not None:
            link[g.split("/", 1)[1]] = entry["value"]
    return link


def build_manifest(registry, ledger_records, meta: Optional[dict] = None,
                   config: Optional[dict] = None,
                   artifacts: Optional[dict] = None) -> dict:
    snap = registry.snapshot()
    counters = snap["counters"]
    phases = {k: round(v, 6) for k, v in counters.items()
              if k.startswith("phase/")}
    wire = {k: v for k, v in counters.items()
            if k.startswith(("wire/", "pipeline/"))}
    # serve-mode amortization story: cross-job overlap seconds plus the
    # jit/persistent compile-cache hit counters that prove the warm
    # path actually skipped work (empty dict for cold one-shot runs).
    # Structured serve gauges ride along — serve/health (the runner's
    # readiness snapshot at job start), serve/recovery (journal-resume
    # provenance: what a restarted queue skipped and resumed),
    # serve/watchdog (the deadline/stall verdict that abandoned a job)
    # slo/* (per-tenant objective burn counters) and telemetry/*
    # (exposition-writer health, profiler captures) ride the serve
    # section: the fleet-telemetry verdicts live next to the serve
    # counters they explain (observability/telemetry.py)
    serve = {k: v for k, v in counters.items()
             if k.startswith(("serve/", "compile/", "slo/",
                              "telemetry/"))}
    for name, g in snap["gauges"].items():
        if name.startswith(("serve/", "slo/", "telemetry/")) \
                and g.get("info"):
            serve[name] = g["info"]
    # tolerant-decode evidence: bad-record counts per taxonomy reason
    # plus the quarantine summary (mode, sidecar path, truncation) —
    # empty dict on clean strict runs
    ingest = {k: int(v) for k, v in counters.items()
              if k.startswith(("ingest/bad_records", "quarantine/"))}
    qg = snap["gauges"].get("quarantine/summary")
    if qg is not None and qg.get("info"):
        ingest["quarantine/summary"] = qg["info"]
    # streaming sessions (serve/session.py + serve/stream_server.py):
    # wave absorb/reject/steal tallies plus the front door's request
    # counters — the manifest's record of the live-ingest plane
    # (empty dict outside session mode).  ``ingest/bad_records*``
    # stays in the ingest section above: that family is the per-job
    # tolerant-decode taxonomy, not the network front door
    sessions = {k: v for k, v in counters.items()
                if k.startswith("session/")
                or (k.startswith("ingest/")
                    and not k.startswith("ingest/bad_records"))}
    for name, g in snap["gauges"].items():
        if name.startswith("session/"):
            sessions[name] = g["value"]
    # memory plane (observability/memplane.py): per-family live/peak
    # gauges, the peak-tracked ratchet, process/device watermarks and
    # any OOM-dump tally — the manifest answers "what did this run
    # actually pin" next to "how long did it take"
    memory: dict = {k: int(v) for k, v in counters.items()
                    if k.startswith("mem/")}
    for name, g in snap["gauges"].items():
        if name.startswith("mem/"):
            memory[name] = g["value"]
    decisions = []
    for rec in ledger_records:
        d = rec.to_dict() if hasattr(rec, "to_dict") else dict(rec)
        decisions.append(d)
    # flight-recorder lifecycle seed (observability/flight.py): the
    # sched/trace info gauge carries the job's trace-context
    # (trace_id = journal key) so a cold-written manifest already
    # joins the fleet trace; the serve runner's finalize then
    # overlays the full journal-measured ``lifecycle`` section
    # (queue wait, claim/steal latency, worker) on top of this.
    lifecycle: dict = {}
    tg = snap["gauges"].get("sched/trace")
    if tg is not None and tg.get("info"):
        lifecycle = dict(tg["info"])
    return {
        "schema": SCHEMA,
        "created_unix": round(time.time(), 3),
        "git": git_describe(),
        "meta": dict(meta or {}),
        "config": config,
        "env_overrides": env_overrides(),
        "link": _link_section(snap),
        "decisions": decisions,
        "phases": phases,
        "wire": wire,
        "serve": serve,
        "ingest": ingest,
        "sessions": sessions,
        "memory": memory,
        "lifecycle": lifecycle,
        "drift_events": int(counters.get("drift/events", 0)),
        "artifacts": dict(artifacts or {}),
    }


def manifest_path_for(metrics_out: str) -> str:
    """The manifest path derived from a ``--metrics-out`` destination."""
    return metrics_out + ".manifest.json"


def write_manifest(path: str, manifest: dict) -> None:
    from .export import _json_default

    with open(path, "w", encoding="utf-8") as fh:
        json.dump(manifest, fh, indent=1, sort_keys=False,
                  default=_json_default)
        fh.write("\n")


def summarize(manifest: dict) -> dict:
    """The compact form bench rows embed: decisions + provenance, no
    full config/phase dump (those live in the row already)."""
    return {
        "schema": manifest["schema"],
        "git": manifest.get("git"),
        "env_overrides": manifest.get("env_overrides", {}),
        "link": manifest.get("link", {}),
        "decisions": [
            {k: d[k] for k in ("decision", "chosen", "predicted",
                               "measured", "residual", "drift")
             if k in d}
            for d in manifest.get("decisions", [])],
        "drift_events": manifest.get("drift_events", 0),
    }
