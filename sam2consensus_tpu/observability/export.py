"""Exports: Chrome/Perfetto trace-event JSON and a JSONL metrics sink.

* ``write_chrome_trace``: the trace-event "JSON object format"
  (https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU)
  — ``ph: "X"`` complete events with ``ts``/``dur`` in microseconds,
  ``ph: "i"`` instants for span events and gate decisions, plus
  ``thread_name`` metadata so the decode prefetch / parallel-decode
  worker threads are labeled.  Load via https://ui.perfetto.dev or
  chrome://tracing.
* ``write_metrics_jsonl``: one JSON object per line, one line per
  instrument (``{"kind": "counter"|"gauge"|"histogram", "name": ...,
  ...}``), preceded by one ``{"kind": "meta", ...}`` header line.
  tools/bench_report.py renders the per-phase table from this sink.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from .metrics import MetricsRegistry
from .trace import Tracer


def chrome_trace_events(tracer: Tracer, pid: Optional[int] = None) -> list:
    """Tracer spans -> a list of Chrome trace-event dicts."""
    pid = os.getpid() if pid is None else pid
    events = []
    for tid, name in tracer.thread_names().items():
        events.append({"ph": "M", "pid": pid, "tid": tid,
                       "name": "thread_name", "args": {"name": name}})
    for s in tracer.drain():
        if s.dur_us < 0:
            ev = {"ph": "i", "name": s.name, "pid": pid, "tid": s.tid,
                  "ts": s.ts_us, "s": "t"}
            if s.args:
                ev["args"] = s.args
            events.append(ev)
            continue
        ev = {"ph": "X", "name": s.name, "pid": pid, "tid": s.tid,
              "ts": s.ts_us, "dur": s.dur_us}
        if s.args:
            ev["args"] = s.args
        events.append(ev)
        for ename, ets, eargs in (s.events or ()):
            iev = {"ph": "i", "name": ename, "pid": pid, "tid": s.tid,
                   "ts": ets, "s": "t"}
            if eargs:
                iev["args"] = eargs
            events.append(iev)
    events.sort(key=lambda e: e.get("ts", 0.0))
    return events


def _json_default(o):
    """Keep exports schema-valid whatever rides in span/gauge args:
    numpy scalars/arrays become their python values, anything else its
    repr-ish string — an exotic arg must never turn a whole trace
    artifact into a crash."""
    try:
        import numpy as np

        if isinstance(o, np.generic):
            return o.item()
        if isinstance(o, np.ndarray):
            return o.tolist()
    except ImportError:
        pass
    return str(o)


def write_chrome_trace(tracer: Tracer, path: str) -> None:
    blob = {"traceEvents": chrome_trace_events(tracer),
            "displayTimeUnit": "ms",
            # trace-context block for the fleet flight recorder
            # (observability/flight.py): epoch_unix re-anchors this
            # process's perf_counter microseconds onto the journal's
            # wall clock; trace_id/key/worker (stamped by the serve
            # runner into tracer.meta) join this artifact to its
            # journal per-job track.  Perfetto ignores unknown
            # top-level keys, so the file stays loadable as-is.
            "s2c": {"epoch_unix": getattr(tracer, "epoch_unix", None),
                    **getattr(tracer, "meta", {})}}
    # explicit utf-8: ensure_ascii=False emits raw unicode, and a
    # C/POSIX-locale CI host must not turn a unicode span label into a
    # lost artifact
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(blob, fh, ensure_ascii=False, default=_json_default)
        fh.write("\n")


def write_metrics_jsonl(registry: MetricsRegistry, path: str,
                        meta: Optional[dict] = None) -> None:
    snap = registry.snapshot()
    with open(path, "w", encoding="utf-8") as fh:
        header = {"kind": "meta", "pid": os.getpid()}
        if meta:
            header.update(meta)
        fh.write(json.dumps(header, default=_json_default) + "\n")
        for name, value in snap["counters"].items():
            fh.write(json.dumps({"kind": "counter", "name": name,
                                 "value": value},
                                default=_json_default) + "\n")
        for name, entry in snap["gauges"].items():
            row = {"kind": "gauge", "name": name, "value": entry["value"]}
            if "info" in entry:
                row["info"] = entry["info"]
            fh.write(json.dumps(row, default=_json_default) + "\n")
        for name, entry in snap["histograms"].items():
            fh.write(json.dumps({"kind": "histogram", "name": name,
                                 **entry}, default=_json_default) + "\n")


def read_metrics_jsonl(path: str) -> list:
    """Parse a metrics JSONL sink back into a list of row dicts."""
    rows = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return rows
