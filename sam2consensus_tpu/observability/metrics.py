"""Process-wide metrics registry: counters, gauges, histograms.

The one canonical store behind every number the repo reports: phase
seconds (the tracer folds closed phase spans in here), wire bytes,
reads decoded, pileup cells, and the placement-gate decisions — the
``stats.extra`` keys bench.py and tools/bench_report.py consume are a
thin compatibility view over a snapshot of this registry
(backends read it back via ``snapshot()`` /
``backends.jax_backend`` ``_publish_stats``).

Three instrument kinds:

* counters — monotonic float adds; seconds, bytes, reads, cells;
* gauges — last-write-wins value (``.set(v)``), with optional
  structured payload (``.set_info(dict)``) for decision records like
  the tail-placement model's inputs;
* histograms — bounded reservoir of observations; the snapshot reports
  count/sum/min/max and p50/p95/p99.

Thread-safety contract: mutate counters and histograms through the
REGISTRY methods — ``registry.add(name, n)`` / ``registry.observe(name,
v)`` — which hold the registry lock across the read-modify-write (the
decode prefetch thread and the consumer both add phase seconds).  The
``counter()`` / ``histogram()`` handle accessors are for reads and
single-writer use only: ``handle.add()`` is an unlocked ``+=``.  Gauge
``set``/``set_info`` are single-store writes and safe from any thread.

A process-wide *current* registry (``current()``) lets deep call sites
(ops/pileup dispatch, utils/linkprobe, the parallel accumulators)
record without threading a handle through every signature; the backend
swaps in a fresh registry per run (``push_run()`` / ``pop_run()``) so
per-run stats never bleed across the bench's warm/timed repetitions.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

#: histogram reservoir bound: big enough for per-slab observations over
#: any real run, small enough that a snapshot's sort is microseconds
HIST_CAP = 4096

#: windowed-view ring bound: a (stamp, value) pair per observation —
#: at one serve job per second this holds >1 h of job-boundary
#: observations, which is exactly the slow burn window's horizon
WINDOW_CAP = 4096


class Counter:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def add(self, n: float = 1.0) -> None:
        self.value += n


class Gauge:
    __slots__ = ("value", "info")

    def __init__(self):
        self.value = 0.0
        self.info: Optional[dict] = None

    def set(self, v: float) -> None:
        self.value = v

    def set_info(self, info: dict) -> None:
        """Attach a structured payload (decision inputs, chosen path)."""
        self.info = info


class Histogram:
    __slots__ = ("values", "count", "total", "vmin", "vmax")

    def __init__(self):
        self.values: List[float] = []
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")

    def observe(self, v: float) -> None:
        self.count += 1
        self.total += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v
        if len(self.values) < HIST_CAP:
            self.values.append(v)
        else:
            # deterministic decimating reservoir: overwrite round-robin
            # so late observations still register without randomness
            self.values[self.count % HIST_CAP] = v

    def merge(self, other: "Histogram") -> None:
        """Fold another histogram in: count/total/min/max merge
        EXACTLY; the bounded reservoir absorbs the other's samples
        through the same deterministic round-robin decimation
        ``observe`` uses — so fleet-level percentiles over merged
        per-job histograms stay meaningful (approximate past HIST_CAP,
        exact below it).  Used by the telemetry plane's
        server-lifetime :class:`~.telemetry.AggregateRegistry`."""
        if other.count == 0:
            return
        self.total += other.total
        if other.vmin < self.vmin:
            self.vmin = other.vmin
        if other.vmax > self.vmax:
            self.vmax = other.vmax
        for v in other.values:
            self.count += 1
            if len(self.values) < HIST_CAP:
                self.values.append(v)
            else:
                self.values[self.count % HIST_CAP] = v
        # observations the other reservoir itself decimated away still
        # count toward the merged count (sum/min/max already carry them)
        self.count += other.count - len(other.values)

    def percentile(self, q: float) -> float:
        if not self.values:
            return 0.0
        s = sorted(self.values)
        idx = min(len(s) - 1, int(q * (len(s) - 1) + 0.5))
        return s[idx]


class Windowed:
    """Timestamped ring buffer: the WINDOWED view over a histogram's
    observation stream (the multi-window SLO burn plane's substrate,
    observability/burn.py).  Histograms deliberately forget WHEN an
    observation happened — fleet percentiles don't need it — but burn
    rates are meaningless without it: "violations per evaluated
    objective over the last 5 minutes" needs stamps.  Bounded like the
    reservoir (WINDOW_CAP ring, oldest overwritten), so a runaway
    queue cannot grow it; reads tolerate the wrap by filtering on
    stamp, not position."""

    __slots__ = ("items", "count")

    def __init__(self):
        self.items: List[tuple] = []     # (stamp_unix, value) ring
        self.count = 0

    def observe(self, v: float, stamp: float) -> None:
        if len(self.items) < WINDOW_CAP:
            self.items.append((stamp, v))
        else:
            self.items[self.count % WINDOW_CAP] = (stamp, v)
        self.count += 1

    def window(self, seconds: float, now: float) -> List[float]:
        """Values observed within the trailing ``seconds`` of ``now``
        (unsorted; the ring wraps out of stamp order past the cap)."""
        lo = now - seconds
        return [v for (t, v) in self.items if lo <= t <= now]


class MetricsRegistry:
    """Thread-safe named instruments; see the module docstring."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._hists: Dict[str, Histogram] = {}
        self._windows: Dict[str, Windowed] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter()
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge()
            return g

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
            return h

    def add(self, name: str, n: float = 1.0) -> None:
        """Locked read-modify-write counter add (safe across threads)."""
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter()
            c.value += n

    def observe(self, name: str, v: float,
                stamp: Optional[float] = None) -> None:
        """Histogram observe; with ``stamp`` (a wall time) the value
        ALSO lands in the name's windowed ring so burn-style trailing-
        window reads work (:meth:`window_values`).  Stampless
        observations stay windowless — one-shot runs pay nothing."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
            h.observe(v)
            if stamp is not None:
                w = self._windows.get(name)
                if w is None:
                    w = self._windows[name] = Windowed()
                w.observe(v, stamp)

    def window_values(self, name: str, seconds: float,
                      now: Optional[float] = None) -> List[float]:
        """The name's stamped observations within the trailing window
        (empty when never stamped) — the burn plane's read side."""
        import time as _time

        with self._lock:
            w = self._windows.get(name)
            if w is None:
                return []
            return w.window(seconds,
                            now if now is not None else _time.time())

    def value(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            c = self._counters.get(name)
            if c is not None:
                return c.value
            g = self._gauges.get(name)
            if g is not None:
                return g.value
            return default

    def info(self, name: str) -> Optional[dict]:
        """A gauge's structured payload (``set_info``), or None — the
        read side of decision/health records (serve health snapshots,
        the manifest's serve section) without snapshotting the whole
        registry."""
        with self._lock:
            g = self._gauges.get(name)
            return dict(g.info) if g is not None and g.info else None

    def snapshot(self) -> dict:
        """One JSON-shaped dict of every instrument's current state."""
        with self._lock:
            out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
            for name, c in self._counters.items():
                out["counters"][name] = c.value
            for name, g in self._gauges.items():
                entry: dict = {"value": g.value}
                if g.info is not None:
                    entry["info"] = g.info
                out["gauges"][name] = entry
            for name, h in self._hists.items():
                out["histograms"][name] = {
                    "count": h.count,
                    "sum": round(h.total, 9),
                    "min": h.vmin if h.count else 0.0,
                    "max": h.vmax if h.count else 0.0,
                    "p50": h.percentile(0.50),
                    "p95": h.percentile(0.95),
                    "p99": h.percentile(0.99),
                }
            return out


# -- process-current registry ---------------------------------------------
_process_registry = MetricsRegistry()
_current: List[MetricsRegistry] = [_process_registry]
_current_lock = threading.Lock()
#: thread-local OVERRIDE of the process-current registry: serve mode
#: (sam2consensus_tpu/serve) decodes job N+1 on a side thread while job
#: N's registry is process-current, and that thread's phase seconds
#: must land in job N+1's registry, not bleed into job N's
_tls = threading.local()


def current() -> MetricsRegistry:
    """The registry deep call sites record into (never None).  A
    thread-bound registry (:func:`bind_thread`) wins over the
    process-current stack."""
    reg = getattr(_tls, "registry", None)
    return reg if reg is not None else _current[-1]


def bind_thread(registry: Optional[MetricsRegistry]) -> None:
    """Route THIS thread's :func:`current` to ``registry`` (None
    unbinds).  Per-thread, so a serve decode-ahead thread records into
    its own job's registry while the main thread keeps the
    process-current one."""
    _tls.registry = registry


def push_run(registry: Optional[MetricsRegistry] = None) -> MetricsRegistry:
    """Install a fresh per-run registry as current; returns it."""
    reg = registry if registry is not None else MetricsRegistry()
    with _current_lock:
        _current.append(reg)
    return reg


def pop_run(registry: MetricsRegistry) -> None:
    """Uninstall a per-run registry (tolerates unbalanced exits)."""
    with _current_lock:
        if len(_current) > 1 and _current[-1] is registry:
            _current.pop()
        elif registry in _current[1:]:
            _current.remove(registry)
