"""Multi-window SLO burn-rate alerting with hysteresis.

The serve plane's old burn signal was a raw counter
(``slo/violations`` and ``AdmissionController.slo_burn_by_tenant``):
monotone, never decaying, so a tenant that breached an hour ago looked
exactly as burnt as one breaching NOW — a transient blip and a
sustained outage were indistinguishable, and the number could only
grow.  This module replaces that read with the standard multi-window
construction:

* per finished job, the runner feeds (tenant, objectives evaluated,
  objectives violated) with the job's wall stamp into the metrics
  registry's windowed rings (``metrics.Windowed`` — the journal-
  measured queue wait is already inside the evaluated phases, so a
  breach caused by the FLEET's queue burns the same as one caused by
  the tenant's data);
* the **burn rate** per (tenant, window) is violated/evaluated over
  the trailing window — fast (~5 min) for detection, slow (~1 h) for
  sustained-ness;
* the **alert state machine** is ok -> warn -> page with hysteresis:
  warn needs the fast window burning AND a minimum violation count
  (one blip in an empty window is a ratio of 1.0 and must NOT alarm);
  page needs BOTH windows burning (the classic page condition: it is
  bad NOW and it has been bad long enough to spend real budget);
  de-escalation steps DOWN one level per quiet period
  (``clear_after`` seconds below the warn ratio), so a flapping tenant
  cannot ring the pager on every oscillation.

Surfaces: ``s2c_burn_rate{tenant,window}`` + ``s2c_burn_alert_state
{tenant}`` gauges (rendered by telemetry.render_openmetrics), the
health snapshot's ``burn`` section, tools/s2c_top.py alert lines, and
— via :meth:`BurnMonitor.burn_counts` — the windowed replacement for
``AdmissionController.slo_burn_by_tenant`` reads.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

STATE_OK = "ok"
STATE_WARN = "warn"
STATE_PAGE = "page"
#: exposition encoding of the state gauge (s2c_burn_alert_state)
STATE_LEVELS = {STATE_OK: 0, STATE_WARN: 1, STATE_PAGE: 2}

DEFAULT_FAST_SEC = 300.0       # detection window (~5 min)
DEFAULT_SLOW_SEC = 3600.0      # sustained-ness window (~1 h)
DEFAULT_WARN_RATIO = 0.25      # fast-window violated/evaluated
DEFAULT_PAGE_RATIO = 0.5       # both windows at/over this -> page
DEFAULT_MIN_VIOLATIONS = 2     # blips below this never escalate
DEFAULT_CLEAR_SEC = 300.0      # quiet seconds per de-escalation step


def _envf(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return float(default)


class BurnMonitor:
    """Per-tenant multi-window burn over a registry's windowed rings.

    The monitor OWNS two windowed series per tenant —
    ``burn/<tenant>/evaluated`` and ``burn/<tenant>/violated`` (one
    observation per finished job, value = the count) — and derives
    rates, states and gauges from them on :meth:`tick`.  Stamps are
    caller-supplied wall times: the fleet path feeds journal-replay
    breaches with their COMMIT stamps, so a breach from an hour ago
    lands an hour old and decays exactly like a locally-observed one.
    """

    WINDOWS = ("fast", "slow")

    def __init__(self, registry, fast_sec: Optional[float] = None,
                 slow_sec: Optional[float] = None,
                 warn_ratio: Optional[float] = None,
                 page_ratio: Optional[float] = None,
                 min_violations: Optional[int] = None,
                 clear_sec: Optional[float] = None):
        self.registry = registry
        self.fast_sec = fast_sec if fast_sec is not None \
            else _envf("S2C_BURN_FAST_SEC", DEFAULT_FAST_SEC)
        self.slow_sec = slow_sec if slow_sec is not None \
            else _envf("S2C_BURN_SLOW_SEC", DEFAULT_SLOW_SEC)
        self.warn_ratio = warn_ratio if warn_ratio is not None \
            else _envf("S2C_BURN_WARN_RATIO", DEFAULT_WARN_RATIO)
        self.page_ratio = page_ratio if page_ratio is not None \
            else _envf("S2C_BURN_PAGE_RATIO", DEFAULT_PAGE_RATIO)
        self.min_violations = min_violations \
            if min_violations is not None \
            else int(_envf("S2C_BURN_MIN_VIOLATIONS",
                           DEFAULT_MIN_VIOLATIONS))
        self.clear_sec = clear_sec if clear_sec is not None \
            else _envf("S2C_BURN_CLEAR_SEC", DEFAULT_CLEAR_SEC)
        self._lock = threading.Lock()
        #: tenant -> {"state", "since_unix", "last_above", "last_step"}
        self._tenants: Dict[str, dict] = {}

    # -- feed ------------------------------------------------------------
    def observe_job(self, tenant: str, evaluated: int, violated: int,
                    now: Optional[float] = None) -> None:
        """One finished job's SLO verdict (evaluated objective count,
        violated count) under the tenant's exposition label."""
        t = tenant or "default"
        stamp = now if now is not None else time.time()
        if evaluated <= 0:
            return
        self.registry.observe(f"burn/{t}/evaluated", float(evaluated),
                              stamp=stamp)
        self.registry.observe(f"burn/{t}/violated",
                              float(max(0, violated)), stamp=stamp)
        with self._lock:
            self._tenants.setdefault(
                t, {"state": STATE_OK, "since_unix": stamp,
                    "last_above": 0.0, "last_step": 0.0})

    # -- rates -----------------------------------------------------------
    def _window_sec(self, window: str) -> float:
        return self.fast_sec if window == "fast" else self.slow_sec

    def counts(self, tenant: str, window: str = "fast",
               now: Optional[float] = None) -> Dict[str, float]:
        """(evaluated, violated) sums over the trailing window."""
        t = tenant or "default"
        sec = self._window_sec(window)
        now = now if now is not None else time.time()
        ev = sum(self.registry.window_values(
            f"burn/{t}/evaluated", sec, now))
        vi = sum(self.registry.window_values(
            f"burn/{t}/violated", sec, now))
        return {"evaluated": ev, "violated": vi}

    def rate(self, tenant: str, window: str = "fast",
             now: Optional[float] = None) -> float:
        """violated/evaluated over the window (0.0 when empty)."""
        c = self.counts(tenant, window, now)
        return c["violated"] / c["evaluated"] if c["evaluated"] > 0 \
            else 0.0

    def burn_counts(self, window: str = "slow",
                    now: Optional[float] = None) -> Dict[str, int]:
        """tenant -> violated-objective count within the window: the
        windowed replacement for the never-decaying
        ``slo_burn_by_tenant`` dict (zero-count tenants dropped, so a
        tenant whose last breach aged out reads as unburnt)."""
        out: Dict[str, int] = {}
        with self._lock:
            tenants = list(self._tenants)
        for t in tenants:
            n = int(self.counts(t, window, now)["violated"])
            if n > 0:
                out[t] = n
        return out

    # -- state machine ---------------------------------------------------
    def tick(self, now: Optional[float] = None) -> Dict[str, str]:
        """Advance every tenant's alert state and refresh the burn
        gauge family; returns tenant -> state.  Escalation is
        immediate (a page-worthy burn pages on the next tick);
        de-escalation steps down ONE level per ``clear_sec`` of the
        fast window staying under the warn ratio — the hysteresis that
        keeps a flapping tenant from oscillating ok<->page."""
        now = now if now is not None else time.time()
        states: Dict[str, str] = {}
        with self._lock:
            tenants = list(self._tenants.items())
        for t, st in tenants:
            fast = self.counts(t, "fast", now)
            slow = self.counts(t, "slow", now)
            fr = fast["violated"] / fast["evaluated"] \
                if fast["evaluated"] > 0 else 0.0
            sr = slow["violated"] / slow["evaluated"] \
                if slow["evaluated"] > 0 else 0.0
            with self._lock:
                cur = st["state"]
                if fr >= self.warn_ratio \
                        and fast["violated"] >= self.min_violations:
                    st["last_above"] = now
                    want = STATE_WARN
                    if fr >= self.page_ratio \
                            and sr >= self.page_ratio:
                        want = STATE_PAGE
                    if STATE_LEVELS[want] > STATE_LEVELS[cur]:
                        st["state"], st["since_unix"] = want, now
                elif cur != STATE_OK:
                    quiet_since = max(st["last_above"],
                                      st["last_step"])
                    if now - quiet_since >= self.clear_sec:
                        lvl = STATE_LEVELS[cur] - 1
                        st["state"] = [STATE_OK, STATE_WARN][lvl] \
                            if lvl >= 0 else STATE_OK
                        st["since_unix"] = now
                        st["last_step"] = now
                states[t] = st["state"]
            self.registry.gauge(f"burn/rate/{t}/fast").set(
                round(fr, 6))
            self.registry.gauge(f"burn/rate/{t}/slow").set(
                round(sr, 6))
            g = self.registry.gauge(f"burn/state/{t}")
            g.set(float(STATE_LEVELS[states[t]]))
            g.set_info({"tenant": t, "state": states[t],
                        "fast_ratio": round(fr, 4),
                        "slow_ratio": round(sr, 4),
                        "since_unix": round(st["since_unix"], 3)})
        return states

    def states(self) -> Dict[str, str]:
        with self._lock:
            return {t: st["state"]
                    for t, st in self._tenants.items()}

    # -- export ----------------------------------------------------------
    def snapshot(self, now: Optional[float] = None) -> dict:
        """Health-section view (``burn``): per-tenant windows, rates,
        state, and the knobs in force — the whole alerting surface in
        one probe-able dict."""
        now = now if now is not None else time.time()
        tenants: Dict[str, dict] = {}
        with self._lock:
            items = list(self._tenants.items())
        for t, st in items:
            fast = self.counts(t, "fast", now)
            slow = self.counts(t, "slow", now)
            tenants[t] = {
                "state": st["state"],
                "since_unix": round(st["since_unix"], 3),
                "fast": {"evaluated": int(fast["evaluated"]),
                         "violated": int(fast["violated"]),
                         "ratio": round(
                             fast["violated"] / fast["evaluated"], 4)
                         if fast["evaluated"] > 0 else 0.0},
                "slow": {"evaluated": int(slow["evaluated"]),
                         "violated": int(slow["violated"]),
                         "ratio": round(
                             slow["violated"] / slow["evaluated"], 4)
                         if slow["evaluated"] > 0 else 0.0},
            }
        return {
            "windows_sec": {"fast": self.fast_sec,
                            "slow": self.slow_sec},
            "thresholds": {"warn_ratio": self.warn_ratio,
                           "page_ratio": self.page_ratio,
                           "min_violations": self.min_violations,
                           "clear_sec": self.clear_sec},
            "tenants": tenants,
        }


def replay_burn(events: List[dict], slo: Optional[dict],
                registry=None, now: Optional[float] = None,
                **knobs) -> dict:
    """Hindsight burn verdicts over journal events — the
    tools/fleet_whatif.py scorer.  ``events`` are journal records
    (dicts with ``ev``/``t``/``tenant``/``elapsed_sec``); committed
    events are scored against the e2e objective exactly like
    ``FleetCoordinator.fleet_burn``, but WITH their wall stamps, so
    the returned monitor answers "who was burning at time T" instead
    of "who ever burned".  Returns ``{"states": ..., "monitor": ...,
    "snapshot": ...}``."""
    from .metrics import MetricsRegistry

    reg = registry if registry is not None else MetricsRegistry()
    mon = BurnMonitor(reg, **knobs)
    obj = (slo or {}).get("e2e")
    last_t = 0.0
    for rec in events:
        if rec.get("ev") != "committed" or obj is None:
            continue
        t = float(rec.get("t", 0.0))
        last_t = max(last_t, t)
        elapsed = float(rec.get("elapsed_sec", 0.0))
        mon.observe_job(rec.get("tenant") or "default",
                        evaluated=1,
                        violated=1 if elapsed > obj else 0, now=t)
    eval_now = now if now is not None else (last_t or time.time())
    states = mon.tick(eval_now)
    return {"states": states, "monitor": mon,
            "snapshot": mon.snapshot(eval_now)}
