"""Compile-cache instrumentation: prove amortization instead of assuming it.

Serve mode's (sam2consensus_tpu/serve) whole premise is that keeping
one process alive across jobs makes jit compilation a one-time cost.
This module makes that claim measurable at two layers:

* **in-process jit cache** — :func:`note_trace` is called INSIDE the
  hot-path jitted function bodies (ops/pileup scatter, the fused tail),
  so it executes exactly once per trace/compile, on whichever thread
  traced, into whichever registry is current — per-job in serve mode.
  :func:`counted_call` wraps a jitted dispatch and classifies it as
  ``compile/jit_cache_hit`` (no trace happened during the call) or
  ``compile/jit_cache_miss`` (the call compiled).  A warm serve job
  therefore shows ``hit > 0, miss == 0`` in ITS OWN registry — the
  acceptance number, not an inference from wall clock;
* **persistent (cross-process) cache** — :func:`setup_persistent_cache`
  wires JAX's compilation cache to disk (default under the native
  build-cache dir, ``S2C_JIT_CACHE`` overrides, empty disables) so even
  cold process starts skip re-compiles, and registers a
  ``jax.monitoring`` listener translating the runtime's cache events
  into ``compile/persist_hit`` / ``compile/persist_miss`` counters —
  surfaced in the run manifest like every other compile/* counter.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Optional

# NOTE: ``from . import metrics`` would resolve to the package's
# ``metrics()`` FUNCTION once __init__ has run (attribute shadowing);
# import the submodule's accessor directly
from .metrics import current as _current_registry

logger = logging.getLogger("sam2consensus_tpu.observability.jitcache")

#: default on-disk cache location: next to the native decoder's build
#: cache (the .so compiled-artifact convention this repo already uses);
#: gitignored, wiped safely at any time
DEFAULT_CACHE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native", "_jit_cache")

_listener_lock = threading.Lock()
_listener_registered = False
_cache_dir: Optional[str] = None

#: jax monitoring event names -> our counter names (jax emits one event
#: per compilation that consulted the persistent cache)
_EVENT_COUNTERS = {
    "/jax/compilation_cache/cache_hits": "compile/persist_hit",
    "/jax/compilation_cache/cache_misses": "compile/persist_miss",
}


def note_trace(label: str, rows: Optional[int] = None,
               width: Optional[int] = None) -> None:
    """Record one jit trace/compile of the function ``label``.

    Call this FROM INSIDE a jitted function body: tracing executes the
    Python body once per new cache entry, so the counter bumps exactly
    when a compile happens and never on a cache hit.  ``rows``/``width``
    (concrete at trace time — shapes are static under jit) additionally
    label a per-shape counter, which is what lets a test pin "the
    prewarmed shape was never re-traced"."""
    reg = _current_registry()
    reg.add("compile/jit_traces", 1)
    reg.add(f"compile/trace/{label}", 1)
    if rows is not None and width is not None:
        reg.add(f"compile/trace/{label}/{int(rows)}x{int(width)}", 1)


def counted_call(fn: Callable, *args, **kwargs):
    """Dispatch a jitted ``fn`` and classify the call as a jit-cache
    hit or miss by whether :func:`note_trace` fired during it (the
    trace callback runs synchronously inside a compiling call).  The
    counters are per-run — a serve job's registry carries its own
    hit/miss story."""
    reg = _current_registry()
    before = reg.value("compile/jit_traces")
    out = fn(*args, **kwargs)
    if reg.value("compile/jit_traces") > before:
        reg.add("compile/jit_cache_miss", 1)
    else:
        reg.add("compile/jit_cache_hit", 1)
    return out


def _on_monitoring_event(name: str, **kwargs) -> None:
    counter = _EVENT_COUNTERS.get(name)
    if counter is not None:
        _current_registry().add(counter, 1)


def cache_dir() -> Optional[str]:
    """The persistent cache directory in effect (None = disabled)."""
    env = os.environ.get("S2C_JIT_CACHE")
    if env is not None:
        return env or None           # "" explicitly disables
    return DEFAULT_CACHE_DIR


def setup_persistent_cache() -> Optional[str]:
    """Wire JAX's persistent compilation cache to disk; returns the
    directory in effect or None when disabled/unsupported.

    Idempotent: the monitoring listener registers once per process and
    re-calls just return the configured directory.  Every failure mode
    (old jax without the config, read-only filesystem) degrades to
    "no persistent cache" with a log line, never an error — the cache
    is an amortization, not a correctness dependency."""
    global _listener_registered, _cache_dir
    path = cache_dir()
    if path is None:
        return None
    if _cache_dir is not None:
        return _cache_dir
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # compile-time floor 0: serve-scale wins come from many small
        # scatter/tail programs a default 1 s floor would never cache
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
        except Exception:
            pass                      # older jax: size floor not tunable
        with _listener_lock:
            if not _listener_registered:
                jax.monitoring.register_event_listener(
                    _on_monitoring_event)
                _listener_registered = True
    except Exception as exc:
        logger.info("persistent compilation cache unavailable: %s: %s",
                    type(exc).__name__, exc)
        return None
    _cache_dir = path
    return path
