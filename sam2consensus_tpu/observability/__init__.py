"""Unified tracing + metrics for the whole pipeline.

One subsystem replaces the ad-hoc ``time.perf_counter()`` snippets and
``stats.extra`` plumbing that every perf claim used to rest on:

* :mod:`.trace` — thread-safe hierarchical spans (free when disabled,
  device-aware ``sync`` on exit);
* :mod:`.metrics` — process-current registry of counters / gauges /
  histograms; the ``stats.extra`` keys bench.py reads are a compat view
  derived from a snapshot of this registry;
* :mod:`.export` — Chrome/Perfetto trace JSON + JSONL metrics sink
  (CLI: ``--trace-out`` / ``--metrics-out``).

Usage, backend side::

    obs = observability.start_run(trace_out=cfg.trace_out,
                                  metrics_out=cfg.metrics_out)
    try:
        with obs.tracer.span("decode"):
            ...
    finally:
        observability.finish_run(obs, meta={"backend": "jax"})

Deep call sites (ops/pileup dispatch, utils/linkprobe, the parallel
accumulators) use :func:`tracer` / :func:`metrics` to reach the current
run's instruments without a handle threaded through their signatures.
Between runs both fall back to process-wide defaults — a disabled
tracer and a throwaway registry — so recording is always safe.
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass, field
from typing import List, Optional

from . import ledger as _ledger
from . import manifest as _manifest
from . import metrics as _metrics
from .export import (read_metrics_jsonl, write_chrome_trace,
                     write_metrics_jsonl)
from .ledger import DecisionLedger, DecisionRecord
from .metrics import MetricsRegistry
from .trace import Tracer

__all__ = [
    "Tracer", "MetricsRegistry", "RunObservability", "PHASES",
    "DecisionLedger", "DecisionRecord",
    "start_run", "finish_run", "prepare_run", "bind_run_to_thread",
    "tracer", "metrics", "ledger",
    "record_decision", "finalize_decisions", "last_manifest",
    "publish_stats_extra", "configure_logging",
    "write_chrome_trace", "write_metrics_jsonl", "read_metrics_jsonl",
]

#: span/phase names in pipeline order — the canonical phase vocabulary
#: shared by the tracer, the metrics registry (``phase/<name>_sec``
#: counters), and the legacy ``stats.extra`` compat keys bench.py reads
PHASES = ("decode", "stage", "pileup_dispatch", "accumulate",
          "insertions", "vote", "render")

#: the always-available fallback tracer; disabled, so every span call
#: outside a run is the shared no-op
_disabled_tracer = Tracer(enabled=False)
_tracer_stack: List[Tracer] = [_disabled_tracer]
_stack_lock = threading.Lock()
_tracer_tls = threading.local()


def tracer() -> Tracer:
    """The current run's tracer (a disabled one between runs).  A
    thread-bound tracer (:func:`bind_run_to_thread`) wins over the
    process-current stack."""
    t = getattr(_tracer_tls, "tracer", None)
    return t if t is not None else _tracer_stack[-1]


def metrics() -> MetricsRegistry:
    """The current run's metrics registry (see metrics.current)."""
    return _metrics.current()


def ledger() -> DecisionLedger:
    """The current run's decision ledger (see ledger.current)."""
    return _ledger.current()


def record_decision(decision: str, chosen: str, **kwargs) -> DecisionRecord:
    """Register a model-driven decision into the current run's ledger
    (see :mod:`.ledger` for the record/measured-spec shapes)."""
    return _ledger.record(decision, chosen, **kwargs)


def finalize_decisions() -> List[DecisionRecord]:
    """Join the current run's ledger against its measured counters,
    emitting ``residual/*`` gauges and ``drift`` events (idempotent).
    The backend calls this at the end of a run BEFORE deriving the
    ``stats.extra`` compat view, so residuals ride into bench rows;
    ``finish_run`` re-checks for runs that died before reaching it."""
    return _ledger.finalize(_ledger.current(), _metrics.current(),
                            tracer())


#: the most recent finish_run's manifest — bench.py embeds a summary in
#: its per-config rows without threading a handle through run_once
_last_manifest: List[Optional[dict]] = [None]


def last_manifest() -> Optional[dict]:
    """The manifest built by the most recent ``finish_run`` (None before
    any run completes)."""
    return _last_manifest[0]


@dataclass
class RunObservability:
    """Handle for one run's instruments + export destinations."""

    tracer: Tracer
    registry: MetricsRegistry
    trace_out: Optional[str] = None
    metrics_out: Optional[str] = None
    ledger: DecisionLedger = field(default_factory=DecisionLedger)
    config: Optional[dict] = None


def prepare_run(trace_out: Optional[str] = None,
                metrics_out: Optional[str] = None,
                enabled: Optional[bool] = None,
                config=None) -> RunObservability:
    """Build a run's instruments WITHOUT installing them as current.

    Serve mode (sam2consensus_tpu/serve) creates job N+1's instruments
    while job N is still process-current: the decode-ahead thread binds
    them thread-locally (:func:`bind_run_to_thread`) so its phase
    seconds land in the right job, and the backend later installs the
    same handle via ``start_run(prepared=...)`` — nothing recorded
    ahead of the run is lost.
    """
    trace_out = trace_out or os.environ.get("S2C_TRACE_OUT") or None
    metrics_out = metrics_out or os.environ.get("S2C_METRICS_OUT") or None
    if enabled is None:
        enabled = trace_out is not None
    if config is not None and not isinstance(config, dict):
        import dataclasses

        config = dataclasses.asdict(config) \
            if dataclasses.is_dataclass(config) else None
    return RunObservability(tracer=Tracer(enabled=bool(enabled)),
                            registry=MetricsRegistry(),
                            trace_out=trace_out, metrics_out=metrics_out,
                            ledger=DecisionLedger(), config=config)


def start_run(trace_out: Optional[str] = None,
              metrics_out: Optional[str] = None,
              enabled: Optional[bool] = None,
              config=None,
              prepared: Optional[RunObservability] = None
              ) -> RunObservability:
    """Install a fresh tracer + registry + decision ledger as the
    process-current set.

    The tracer is enabled iff a trace destination exists (``trace_out``
    or S2C_TRACE_OUT) or ``enabled`` forces it; the registry always
    collects — its cost is a few locked adds per *slab*, not per row,
    and the compat ``stats.extra`` view needs it on every run.
    ``config`` (a RunConfig or dict) is snapshotted into the run's
    manifest so every artifact records the flags that produced it.
    ``prepared`` installs an existing :func:`prepare_run` handle
    instead (serve mode: the handle already holds the job's
    decode-ahead phase seconds).
    """
    robs = prepared if prepared is not None else prepare_run(
        trace_out=trace_out, metrics_out=metrics_out, enabled=enabled,
        config=config)
    _metrics.push_run(robs.registry)
    _ledger.push_run(robs.ledger)
    with _stack_lock:
        _tracer_stack.append(robs.tracer)
    return robs


class bind_run_to_thread:
    """Context manager routing THIS thread's ``tracer()`` /
    ``metrics()`` / ``ledger()`` to one run's instruments, regardless
    of what is process-current.  Serve mode's decode-ahead thread binds
    job N+1's prepared handle while job N runs in the main thread."""

    def __init__(self, robs: RunObservability):
        self._robs = robs

    def __enter__(self):
        _metrics.bind_thread(self._robs.registry)
        _ledger.bind_thread(self._robs.ledger)
        _tracer_tls.tracer = self._robs.tracer
        return self._robs

    def __exit__(self, *exc):
        _metrics.bind_thread(None)
        _ledger.bind_thread(None)
        _tracer_tls.tracer = None
        return False


def finish_run(obs: RunObservability, meta: Optional[dict] = None) -> None:
    """Uninstall the run's instruments, write any requested exports, and
    build the run's manifest (written alongside ``--metrics-out``)."""
    # join decisions first (idempotent — the backend normally already
    # did, so residual gauges reached the stats.extra compat view) so
    # the exports and manifest below carry the residual/drift story
    _ledger.finalize(obs.ledger, obs.registry, obs.tracer)
    with _stack_lock:
        if len(_tracer_stack) > 1 and _tracer_stack[-1] is obs.tracer:
            _tracer_stack.pop()
        elif obs.tracer in _tracer_stack[1:]:
            _tracer_stack.remove(obs.tracer)
    _metrics.pop_run(obs.registry)
    _ledger.pop_run(obs.ledger)
    artifacts = {}
    if obs.trace_out:
        write_chrome_trace(obs.tracer, obs.trace_out)
        artifacts["trace"] = {"path": obs.trace_out,
                              "digest": _manifest.file_digest(
                                  obs.trace_out)}
    if obs.metrics_out:
        write_metrics_jsonl(obs.registry, obs.metrics_out, meta=meta)
        artifacts["metrics"] = {"path": obs.metrics_out,
                                "digest": _manifest.file_digest(
                                    obs.metrics_out)}
    man = _manifest.build_manifest(
        obs.registry, obs.ledger.records(), meta=meta,
        config=obs.config, artifacts=artifacts)
    _last_manifest[0] = man
    if obs.metrics_out:
        _manifest.write_manifest(
            _manifest.manifest_path_for(obs.metrics_out), man)


def publish_stats_extra(extra: dict) -> None:
    """Compat view: derive the legacy ``stats.extra`` keys from the
    current metrics registry — the one canonical source.  ``bench.py``
    and ``--json-metrics`` keep reading the same keys they always did;
    the registry (and its ``--metrics-out`` JSONL export) is where the
    numbers actually live now."""
    snap = metrics().snapshot()
    for name, value in snap["counters"].items():
        # every phase counter surfaces, not just the canonical PHASES —
        # the cpu oracle's reformat/consensus phases ride the same view
        if name.startswith("phase/") and name.endswith("_sec"):
            extra[name[len("phase/"):]] = round(value, 4)
        # the recovery story (retries, demotions, emergency checkpoints,
        # injected faults, corrupt-checkpoint absorptions) rides into
        # --json-metrics/bench rows too, so a degraded run is visible
        # from any artifact
        elif name.startswith(("resilience/", "fault/", "checkpoint/")):
            extra[name] = int(value)
        # the wire codec's compression story and the staging pipeline's
        # measured overlap (wire/bytes vs wire/raw_bytes is the ratio;
        # pipeline/overlap_sec is the R6 acceptance metric); drift
        # events (ledger residual outside band) ride along so a run
        # whose model mis-priced is visible from any artifact
        # serve/* (cross-job overlap, decode-ahead seconds) and
        # compile/* (jit cache hits/misses, persistent-cache hits) ride
        # the same view: serve-mode amortization claims are checkable
        # from any per-job artifact
        # format/* (BGZF corrupt-block absorptions, text fallbacks —
        # sam2consensus_tpu/formats) rides along so a run that survived
        # a damaged container says so from any artifact
        # ingest/* (shard counts, worker seconds, stream-rung fallbacks,
        # shard retries/demotions — encoder/parallel_decode.py) rides
        # along so the multi-core ingest story is checkable from any
        # artifact: worker_sec / decode_sec is the realized parallelism
        # quarantine/* (tolerant decode: stored sidecar entries,
        # truncation — ingest/badrecords.py) rides along so a job that
        # skipped records says so from any artifact
        # slo/* (per-tenant objective burn) and telemetry/* (exposition
        # writer health, profiler captures — observability/telemetry.py)
        # ride along so the fleet-telemetry story is checkable from any
        # per-job artifact
        # cache/* (incremental count cache hit/miss per job) and
        # epilogue/* (device vs host render epilogue) ride along so the
        # warm-path story is checkable from any per-job artifact
        # mem/* (the memory plane's peak-tracked ratchet and OOM-dump
        # tallies — observability/memplane.py) rides along so the
        # residency story is checkable from any artifact
        elif name.startswith(("wire/", "pipeline/", "drift/", "serve/",
                              "compile/", "format/", "ingest/",
                              "quarantine/", "slo/", "telemetry/",
                              "cache/", "epilogue/", "mem/")):
            extra[name] = int(value) if float(value).is_integer() \
                else round(value, 4)
    for gauge_name, extra_key in (("dispatch/tail", "tail_dispatch"),
                                  ("dispatch/pileup", "pileup_path"),
                                  ("wire/codec", "wire"),
                                  ("pipeline/overlap", "pipeline"),
                                  ("format/input", "input_format"),
                                  ("ingest/mode", "ingest_mode"),
                                  ("serve/recovery", "serve_recovery"),
                                  ("serve/watchdog", "serve_watchdog"),
                                  ("quarantine/summary", "quarantine")):
        g = snap["gauges"].get(gauge_name)
        if g is not None and g.get("info"):
            extra[extra_key] = g["info"]
    # per-decision residual ratios (ledger.finalize): the scalar
    # residual/<decision>/<key> gauges, so bench rows show how far each
    # model's prediction sat from the measured outcome
    for name, g in snap["gauges"].items():
        if name.startswith("residual/") and name.count("/") == 2:
            extra[name] = g["value"]
        # per-family peak bytes + process/device watermarks (the memory
        # plane's gauges), so bench rows and --json-metrics carry the
        # residency numbers without a second export path
        elif name.startswith("mem/"):
            extra[name] = int(g["value"]) \
                if float(g["value"]).is_integer() else g["value"]
    # the regression gate's top-level key (tools/regress_check.py gates
    # peak_rss_mb alongside jax_sec on the bench series)
    prss = snap["gauges"].get("mem/peak_rss_mb")
    if prss is not None:
        extra["peak_rss_mb"] = prss["value"]


def configure_logging(level: Optional[str],
                      log_format: str = "text") -> None:
    """Wire the package logger to stderr (``--log-level`` /
    ``--log-format``).  ``log_format="json"`` swaps in
    :class:`~.telemetry.JsonLogFormatter` — one JSON object per record
    carrying the job_id/tenant/rung/trace-span correlation context
    (:func:`~.telemetry.set_log_context`) — and implies level=info
    when no level was requested (asking for structured logs and
    getting silence would be absurd)."""
    if log_format not in ("text", "json"):
        raise SystemExit(f"error: unknown log format {log_format!r} "
                         "(use text|json)")
    if log_format == "json" and not level:
        level = "info"
    if not level:
        return
    lv = getattr(logging, level.upper(), None)
    if not isinstance(lv, int):
        raise SystemExit(f"error: unknown log level {level!r} "
                         "(use debug|info|warning|error)")
    logger = logging.getLogger("sam2consensus_tpu")
    if not logger.handlers:
        logger.addHandler(logging.StreamHandler())
    if log_format == "json":
        from .telemetry import JsonLogFormatter

        fmt: logging.Formatter = JsonLogFormatter()
    else:
        fmt = logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s %(message)s")
    for h in logger.handlers:
        h.setFormatter(fmt)
    logger.setLevel(lv)
