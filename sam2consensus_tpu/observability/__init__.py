"""Unified tracing + metrics for the whole pipeline.

One subsystem replaces the ad-hoc ``time.perf_counter()`` snippets and
``stats.extra`` plumbing that every perf claim used to rest on:

* :mod:`.trace` — thread-safe hierarchical spans (free when disabled,
  device-aware ``sync`` on exit);
* :mod:`.metrics` — process-current registry of counters / gauges /
  histograms; the ``stats.extra`` keys bench.py reads are a compat view
  derived from a snapshot of this registry;
* :mod:`.export` — Chrome/Perfetto trace JSON + JSONL metrics sink
  (CLI: ``--trace-out`` / ``--metrics-out``).

Usage, backend side::

    obs = observability.start_run(trace_out=cfg.trace_out,
                                  metrics_out=cfg.metrics_out)
    try:
        with obs.tracer.span("decode"):
            ...
    finally:
        observability.finish_run(obs, meta={"backend": "jax"})

Deep call sites (ops/pileup dispatch, utils/linkprobe, the parallel
accumulators) use :func:`tracer` / :func:`metrics` to reach the current
run's instruments without a handle threaded through their signatures.
Between runs both fall back to process-wide defaults — a disabled
tracer and a throwaway registry — so recording is always safe.
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass
from typing import List, Optional

from . import metrics as _metrics
from .export import (read_metrics_jsonl, write_chrome_trace,
                     write_metrics_jsonl)
from .metrics import MetricsRegistry
from .trace import Tracer

__all__ = [
    "Tracer", "MetricsRegistry", "RunObservability", "PHASES",
    "start_run", "finish_run", "tracer", "metrics",
    "publish_stats_extra", "configure_logging",
    "write_chrome_trace", "write_metrics_jsonl", "read_metrics_jsonl",
]

#: span/phase names in pipeline order — the canonical phase vocabulary
#: shared by the tracer, the metrics registry (``phase/<name>_sec``
#: counters), and the legacy ``stats.extra`` compat keys bench.py reads
PHASES = ("decode", "stage", "pileup_dispatch", "accumulate",
          "insertions", "vote", "render")

#: the always-available fallback tracer; disabled, so every span call
#: outside a run is the shared no-op
_disabled_tracer = Tracer(enabled=False)
_tracer_stack: List[Tracer] = [_disabled_tracer]
_stack_lock = threading.Lock()


def tracer() -> Tracer:
    """The current run's tracer (a disabled one between runs)."""
    return _tracer_stack[-1]


def metrics() -> MetricsRegistry:
    """The current run's metrics registry (see metrics.current)."""
    return _metrics.current()


@dataclass
class RunObservability:
    """Handle for one run's instruments + export destinations."""

    tracer: Tracer
    registry: MetricsRegistry
    trace_out: Optional[str] = None
    metrics_out: Optional[str] = None


def start_run(trace_out: Optional[str] = None,
              metrics_out: Optional[str] = None,
              enabled: Optional[bool] = None) -> RunObservability:
    """Install a fresh tracer + registry as the process-current pair.

    The tracer is enabled iff a trace destination exists (``trace_out``
    or S2C_TRACE_OUT) or ``enabled`` forces it; the registry always
    collects — its cost is a few locked adds per *slab*, not per row,
    and the compat ``stats.extra`` view needs it on every run.
    """
    trace_out = trace_out or os.environ.get("S2C_TRACE_OUT") or None
    metrics_out = metrics_out or os.environ.get("S2C_METRICS_OUT") or None
    if enabled is None:
        enabled = trace_out is not None
    t = Tracer(enabled=bool(enabled))
    reg = _metrics.push_run()
    with _stack_lock:
        _tracer_stack.append(t)
    return RunObservability(tracer=t, registry=reg, trace_out=trace_out,
                            metrics_out=metrics_out)


def finish_run(obs: RunObservability, meta: Optional[dict] = None) -> None:
    """Uninstall the run's instruments and write any requested exports."""
    with _stack_lock:
        if len(_tracer_stack) > 1 and _tracer_stack[-1] is obs.tracer:
            _tracer_stack.pop()
        elif obs.tracer in _tracer_stack[1:]:
            _tracer_stack.remove(obs.tracer)
    _metrics.pop_run(obs.registry)
    if obs.trace_out:
        write_chrome_trace(obs.tracer, obs.trace_out)
    if obs.metrics_out:
        write_metrics_jsonl(obs.registry, obs.metrics_out, meta=meta)


def publish_stats_extra(extra: dict) -> None:
    """Compat view: derive the legacy ``stats.extra`` keys from the
    current metrics registry — the one canonical source.  ``bench.py``
    and ``--json-metrics`` keep reading the same keys they always did;
    the registry (and its ``--metrics-out`` JSONL export) is where the
    numbers actually live now."""
    snap = metrics().snapshot()
    for name, value in snap["counters"].items():
        # every phase counter surfaces, not just the canonical PHASES —
        # the cpu oracle's reformat/consensus phases ride the same view
        if name.startswith("phase/") and name.endswith("_sec"):
            extra[name[len("phase/"):]] = round(value, 4)
        # the recovery story (retries, demotions, emergency checkpoints,
        # injected faults) rides into --json-metrics/bench rows too, so
        # a degraded run is visible from any artifact
        elif name.startswith(("resilience/", "fault/")):
            extra[name] = int(value)
        # the wire codec's compression story and the staging pipeline's
        # measured overlap (wire/bytes vs wire/raw_bytes is the ratio;
        # pipeline/overlap_sec is the R6 acceptance metric)
        elif name.startswith(("wire/", "pipeline/")):
            extra[name] = int(value) if float(value).is_integer() \
                else round(value, 4)
    for gauge_name, extra_key in (("dispatch/tail", "tail_dispatch"),
                                  ("dispatch/pileup", "pileup_path"),
                                  ("wire/codec", "wire"),
                                  ("pipeline/overlap", "pipeline")):
        g = snap["gauges"].get(gauge_name)
        if g is not None and g.get("info"):
            extra[extra_key] = g["info"]


def configure_logging(level: Optional[str]) -> None:
    """Wire the package logger to stderr at ``level`` (``--log-level``)."""
    if not level:
        return
    lv = getattr(logging, level.upper(), None)
    if not isinstance(lv, int):
        raise SystemExit(f"error: unknown log level {level!r} "
                         "(use debug|info|warning|error)")
    logger = logging.getLogger("sam2consensus_tpu")
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s %(message)s"))
        logger.addHandler(h)
    logger.setLevel(lv)
