"""Memory observability plane: host+device byte accounting + forensics.

Every observability layer so far measures seconds and wire bytes but
not a single byte of *residency* — yet the failure mode every
remaining scale item shares is memory-shaped: a wide-genome job OOMs
one host, the OOM-split ladder rung fires blind, and the HBM-OOM note
in ``ops/mxu_pileup.py`` documents a failure the system can neither
predict nor report.  This module is the residency counterpart of the
PR-12 d2h choke point (``wire.account_d2h``): one discipline, three
surfaces.

**Byte accounting.**  Every long-lived allocation family registers
through one choke point:

====================  ====================================================
family                what it holds
====================  ====================================================
``counts``            device count tensors (PileupAccumulator, sharded)
``counts_host``       the host pileup rung's count tensor
``wire_staging``      staged slab operands (encode + ``device_put`` slots)
``insertion_table``   the insertion-event key/table operands
``decode_ahead``      serve-mode predecoded batches pinned for job N+1
``count_cache``       warm per-reference count state (serve/countcache)
``quarantine``        the tolerant-decode sidecar window
``packed_batch``      continuous batching's merged combined tensors
====================  ====================================================

:func:`track` / :func:`release` (or :func:`track_obj`, which
auto-releases when the object is garbage-collected) maintain
process-wide live/peak bytes per family AND publish into the *current*
metrics registry — ``mem/live_bytes/<family>`` /
``mem/peak_bytes/<family>`` gauges plus the ``mem/peak_tracked_bytes``
ratchet counter — so each job's registry carries the peaks observed
during that job while the plane itself survives across jobs (resident
cache entries keep counting).  The plane is pure accounting: bytes are
identical with it on or off (``S2C_MEMPLANE=0`` disables; pinned by
tests/test_memplane.py).

**Watermarks.**  :func:`sample` reads process RSS (current via
``/proc/self/statm``, peak via ``resource.getrusage``), optional
tracemalloc (only when the caller already enabled tracing), and
``device.memory_stats()`` bytes-in-use/peak where the backend exposes
it (gracefully absent on CPU), publishing ``mem/rss_mb`` /
``mem/peak_rss_mb`` / ``mem/device_bytes_in_use`` /
``mem/device_peak_bytes`` gauges and keeping a bounded history ring —
the serve runner samples from its watchdog/telemetry tick, so a
mid-hang scrape shows memory too, and the ring is the forensic dump's
watermark tail.

**Capacity model.**  :func:`predict_run_peak_bytes` prices a run's
peak tracked bytes from the same geometry the allocations come from
(``padded_total_len`` counts, ``canonical_slab_shapes`` staging slots,
the threshold grid's tail buffers); :func:`record_capacity` registers
it as a ``capacity`` ledger decision joined against the measured
``mem/peak_tracked_bytes`` ratchet.  The residual is recorded
*informationally* (band=0, the shard-mode precedent): the model is an
admission-side UPPER bound — an under-filled final chunk makes
measured << predicted by design, and alarming on headroom would teach
operators to ignore drift.  The committed ``mem_watermark`` artifact
(tools/mem_watermark.py) runs chunk-filling configs precisely so its
residuals sit inside the default band, keeping the model honest where
it matters.  Serve admission consumes the same prediction: a job whose
predicted peak exceeds ``--mem-budget`` is shed with reason
``capacity`` (``serve/admission_capacity``) instead of being allowed
to OOM the fleet.

**OOM forensics.**  :func:`dump_on_capacity` writes ``mem_dump.json``
(schema ``s2c-mem-dump/1``) next to the journal / metrics artifact
when a failure classifies CAPACITY (resilience/policy.py — the class
that splits/demotes rather than blindly retrying): per-family
live/peak table, the watermark history tail, the capacity prediction
and its inputs, the innermost open span, and the error itself —
exactly like the telemetry plane's profiler span-dump, but for
residency.  The ``mem_alloc`` fault site (resilience/faultinject.py)
injects a deterministic MemoryError at the device count-tensor
allocation boundary so the whole path is testable without a real OOM.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import weakref
from collections import deque
from typing import Dict, Optional, Tuple

logger = logging.getLogger("sam2consensus_tpu.observability.memplane")

#: the documented allocation families (informational — track() accepts
#: any name; these are the ones the shipped call sites use)
FAMILIES = ("counts", "counts_host", "wire_staging", "insertion_table",
            "decode_ahead", "count_cache", "quarantine", "packed_batch")

MEM_DUMP_SCHEMA = "s2c-mem-dump/1"
MEM_DUMP_NAME = "mem_dump.json"

#: watermark history ring bound (one entry per sampler tick — at the
#: serve default 2 s cadence this is ~8.5 minutes of tail)
HISTORY_CAP = 256


def enabled() -> bool:
    """The plane's on/off gate (``S2C_MEMPLANE``; default on).  Checked
    live so tests can toggle it; one getenv per accounting event —
    allocation-family events are per run/slab/entry, never per row."""
    return os.environ.get("S2C_MEMPLANE", "1").lower() \
        not in ("0", "off", "false")


class _Plane:
    """Process-wide accounting state (families outlive runs: a warm
    count-cache entry is resident across jobs and must keep counting)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.live: Dict[str, int] = {}
        self.peak: Dict[str, int] = {}
        self.total_live = 0
        self.total_peak = 0
        self.history: deque = deque(maxlen=HISTORY_CAP)
        self.last_capacity: Optional[dict] = None
        self.last_sample: Optional[dict] = None


_plane = _Plane()


def _publish(family: str, live: int, total: int) -> None:
    """Mirror one adjustment into the CURRENT registry: live gauges are
    absolute (process-wide), peak gauges/counters ratchet per registry —
    a fresh per-job registry therefore records the peak observed during
    *that* job (including state resident when it started)."""
    from .metrics import current as _current_registry

    reg = _current_registry()
    reg.gauge(f"mem/live_bytes/{family}").set(float(live))
    g = reg.gauge(f"mem/peak_bytes/{family}")
    if live > g.value:
        g.set(float(live))
    reg.gauge("mem/live_tracked_bytes").set(float(total))
    have = reg.value("mem/peak_tracked_bytes")
    if total > have:
        reg.add("mem/peak_tracked_bytes", total - have)


def adjust(family: str, delta: int) -> None:
    """THE residency choke point: add ``delta`` bytes (negative =
    release) to ``family``'s live total and publish live/peak."""
    if delta == 0 or not enabled():
        return
    with _plane.lock:
        live = max(0, _plane.live.get(family, 0) + int(delta))
        _plane.live[family] = live
        if live > _plane.peak.get(family, 0):
            _plane.peak[family] = live
        _plane.total_live = max(0, _plane.total_live + int(delta))
        if _plane.total_live > _plane.total_peak:
            _plane.total_peak = _plane.total_live
        # publish under the plane lock so the per-registry peak ratchet
        # (read-then-add) cannot interleave across threads; lock order
        # is plane -> registry, used nowhere in the other direction
        _publish(family, live, _plane.total_live)


def track(family: str, nbytes: int) -> None:
    """Register ``nbytes`` of live residency under ``family``."""
    if nbytes > 0:
        adjust(family, int(nbytes))


def release(family: str, nbytes: int) -> None:
    """The matching release (callers with explicit lifecycles)."""
    if nbytes > 0:
        adjust(family, -int(nbytes))


def track_obj(family: str, obj, nbytes: int) -> None:
    """Track ``nbytes`` against ``obj``'s lifetime: released
    automatically when the object is garbage-collected (CPython
    refcounting makes this prompt for the accumulator/batch objects the
    call sites hand in).  Objects that cannot carry a weakref are
    counted toward the family peak and released immediately — peak is
    the surface admission and forensics consume; a non-weakrefable
    object must not leak live bytes forever."""
    if nbytes <= 0 or not enabled():
        return
    n = int(nbytes)
    track(family, n)
    try:
        weakref.finalize(obj, adjust, family, -n)
    except TypeError:
        adjust(family, -n)


def batch_nbytes(batch) -> int:
    """Resident bytes of one decoded SegmentBatch (bucket operands +
    any staged slab payloads) — the decode-ahead / packed-batch
    families' sizing helper."""
    n = 0
    for starts, codes in getattr(batch, "buckets", {}).values():
        n += int(getattr(starts, "nbytes", 0))
        n += int(getattr(codes, "nbytes", 0))
    for slab in getattr(batch, "staged", {}).values():
        n += int(getattr(slab, "nbytes", 0))
    return n


# =========================================================================
# Watermarks
# =========================================================================
def rss_bytes() -> Tuple[int, int]:
    """(current, peak) process RSS in bytes.  Peak via
    ``resource.getrusage`` (kilobytes on Linux); current via
    ``/proc/self/statm`` where it exists, else 0 (the peak still
    reports)."""
    peak = 0
    try:
        import resource
        import sys

        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        peak = int(ru) if sys.platform == "darwin" else int(ru) * 1024
    except Exception:
        pass
    cur = 0
    try:
        with open("/proc/self/statm") as fh:
            cur = int(fh.read().split()[1]) * (os.sysconf("SC_PAGE_SIZE")
                                               if hasattr(os, "sysconf")
                                               else 4096)
    except Exception:
        pass
    return cur, peak


def device_memory_stats() -> Optional[dict]:
    """``{bytes_in_use, peak_bytes_in_use}`` from the default device
    where the backend exposes ``memory_stats()`` (real accelerators);
    None on CPU / when jax was never imported — the plane must not be
    the thing that pays jax's import or dials a remote backend."""
    import sys

    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return None
    try:
        dev = jax_mod.devices()[0]
        stats = dev.memory_stats()
    except Exception:
        return None
    if not isinstance(stats, dict):
        return None
    out = {}
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
        v = stats.get(key)
        if isinstance(v, (int, float)):
            out[key] = int(v)
    return out or None


def sample(registry=None) -> dict:
    """One watermark sample: RSS + optional tracemalloc + device bytes
    + the plane's tracked totals; appended to the bounded history ring
    and published as ``mem/*`` gauges into ``registry`` (default: the
    current registry).  Rides the serve watchdog/telemetry tick and the
    backend's end-of-run publish."""
    cur, peak = rss_bytes()
    with _plane.lock:
        tracked_live = _plane.total_live
        tracked_peak = _plane.total_peak
    s = {
        "unix": round(time.time(), 3),
        "rss_mb": round(cur / 1e6, 2),
        "peak_rss_mb": round(peak / 1e6, 2),
        "tracked_live_bytes": tracked_live,
        "tracked_peak_bytes": tracked_peak,
    }
    try:
        import tracemalloc

        if tracemalloc.is_tracing():
            traced, tpeak = tracemalloc.get_traced_memory()
            s["tracemalloc_mb"] = round(traced / 1e6, 2)
            s["tracemalloc_peak_mb"] = round(tpeak / 1e6, 2)
    except Exception:
        pass
    dev = device_memory_stats()
    if dev is not None:
        s["device_bytes_in_use"] = dev.get("bytes_in_use", 0)
        if "peak_bytes_in_use" in dev:
            s["device_peak_bytes"] = dev["peak_bytes_in_use"]
    with _plane.lock:
        _plane.history.append(s)
        _plane.last_sample = s
    if enabled():
        if registry is None:
            from .metrics import current as _current_registry

            registry = _current_registry()
        registry.gauge("mem/rss_mb").set(s["rss_mb"])
        registry.gauge("mem/peak_rss_mb").set(s["peak_rss_mb"])
        if "device_bytes_in_use" in s:
            registry.gauge("mem/device_bytes_in_use").set(
                float(s["device_bytes_in_use"]))
        if "device_peak_bytes" in s:
            registry.gauge("mem/device_peak_bytes").set(
                float(s["device_peak_bytes"]))
        # per-family live/peak into THIS registry too: the serve
        # runner samples into its server-lifetime AggregateRegistry,
        # which deliberately does NOT fold per-job mem/* (summed
        # per-job peaks would be jobs_folded x reality) — the sampled
        # publication here is how the fleet exposition carries the
        # family gauges instead.  The whole publish runs under the
        # plane lock, like adjust()'s _publish: the peak ratchets are
        # read-then-write, and an adjust() racing on the same registry
        # could otherwise inflate the monotone counter past the true
        # peak (lock order plane -> registry, same as everywhere)
        with _plane.lock:
            for f in set(_plane.live) | set(_plane.peak):
                live = _plane.live.get(f, 0)
                registry.gauge(f"mem/live_bytes/{f}").set(float(live))
                g = registry.gauge(f"mem/peak_bytes/{f}")
                if live > g.value:
                    g.set(float(live))
            total_live = _plane.total_live
            registry.gauge("mem/live_tracked_bytes").set(
                float(total_live))
            have = registry.value("mem/peak_tracked_bytes")
            if total_live > have:
                registry.add("mem/peak_tracked_bytes",
                             total_live - have)
    return s


def history_tail(n: int = 64) -> list:
    """The newest ``n`` watermark samples (forensic dump tail)."""
    with _plane.lock:
        return list(_plane.history)[-n:]


def summary() -> dict:
    """The health-snapshot / s2c_top shape: per-family live/peak plus
    the latest watermarks (sampled fresh when none exist yet)."""
    with _plane.lock:
        fams = {f: {"live_bytes": _plane.live.get(f, 0),
                    "peak_bytes": _plane.peak.get(f, 0)}
                for f in sorted(set(_plane.live) | set(_plane.peak))}
        totals = {"live_bytes": _plane.total_live,
                  "peak_bytes": _plane.total_peak}
        last = _plane.last_sample
    return {
        "families": fams,
        "tracked": totals,
        "watermarks": dict(last) if last is not None else sample(),
        "enabled": enabled(),
    }


# =========================================================================
# Capacity model
# =========================================================================
def predict_run_peak_bytes(total_len: int, n_thresholds: int = 1,
                           chunk_reads: int = 262144,
                           read_len: int = 150, shards: int = 1,
                           segment_width: int = 0,
                           n_reads: Optional[int] = None,
                           batch_members: int = 1
                           ) -> Tuple[int, Dict[str, int]]:
    """Predicted peak tracked bytes for one run, from the same geometry
    the allocations come from.

    Components: the padded count tensor (per shard — the formula
    ``padded_total_len * NUM_SYMBOLS * 4`` every accumulator
    implicitly encodes), the double-buffered staging slots at the
    widest canonical slab shape (host buffer + device operand), and
    the tail's per-threshold symbol/stat buffers.  Insertion tables
    and quarantine windows are data-dependent and deliberately
    unpriced — the model is a geometry bound, and its residual is
    recorded informationally (module docstring).
    """
    try:
        from ..constants import NUM_SYMBOLS
        from ..ops.pileup import canonical_slab_shapes, padded_total_len

        padded = padded_total_len(total_len)
        shapes = canonical_slab_shapes(
            total_len, read_len=read_len, chunk_reads=chunk_reads,
            n_reads=n_reads, segment_width=segment_width)
        nsym = NUM_SYMBOLS
    except Exception:
        # geometry helpers unavailable (jax-free consumer): arithmetic
        # approximations keep admission working
        padded = -(-(total_len + 1) // 1024) * 1024
        w = max(64, 1 << max(0, (max(1, read_len) - 1).bit_length()))
        rows = min(max(8, 1 << (max(1, min(n_reads or chunk_reads,
                                           chunk_reads)) - 1)
                       .bit_length()), max(1, (1 << 22) // w))
        shapes = [(rows, w)]
        nsym = 6
    shards = max(1, int(shards))
    counts = padded * nsym * 4 * shards
    # widest canonical slab in its WIRE layout (packed nibble lanes +
    # int32 starts — what the staged device operands actually hold)
    slab = max((int(r) * (int(w) // 2 + 4) for r, w in shapes),
               default=0)
    # two pinned staging slots (wire.pipeline.DEFAULT_SLOTS)
    staging = 2 * slab
    tail = max(1, int(n_thresholds)) * padded * 6
    components = {
        "counts_bytes": int(counts),
        "staging_bytes": int(staging),
        "tail_bytes": int(tail),
    }
    total = sum(components.values()) * max(1, int(batch_members)) \
        if batch_members > 1 else sum(components.values())
    return int(total), components


def predict_job_peak_bytes(total_len: int, cfg) -> int:
    """Admission-side wrapper: the prediction for one job from its
    header-probed genome length + RunConfig (serve/runner.py)."""
    total, _comp = predict_run_peak_bytes(
        total_len,
        n_thresholds=len(getattr(cfg, "thresholds", None) or [0.25]),
        chunk_reads=getattr(cfg, "chunk_reads", 262144),
        shards=getattr(cfg, "shards", 1) or 1,
        segment_width=max(0, getattr(cfg, "segment_width", 0)))
    return total


def record_capacity(total_len: int, n_thresholds: int,
                    chunk_reads: int = 262144, shards: int = 1,
                    segment_width: int = 0,
                    n_reads: Optional[int] = None,
                    budget_bytes: int = 0) -> dict:
    """Register the run's ``capacity`` ledger decision (predicted peak
    bytes joined against the measured ``mem/peak_tracked_bytes``
    ratchet at finalize, like every other gate).  Returns the
    prediction record (also kept as the forensic dump's ``capacity``
    section)."""
    from .. import observability as obs

    total, components = predict_run_peak_bytes(
        total_len, n_thresholds=n_thresholds, chunk_reads=chunk_reads,
        shards=shards, segment_width=segment_width, n_reads=n_reads)
    chosen = "unbudgeted"
    if budget_bytes:
        chosen = "over_budget" if total > budget_bytes \
            else "within_budget"
    inputs = {
        "total_len": int(total_len),
        "n_thresholds": int(n_thresholds),
        "chunk_reads": int(chunk_reads),
        "shards": int(max(1, shards)),
        "segment_width": int(segment_width),
        **({"budget_bytes": int(budget_bytes)} if budget_bytes else {}),
        **components,
    }
    record = {"predicted_bytes": int(total), "chosen": chosen,
              "inputs": inputs}
    with _plane.lock:
        _plane.last_capacity = record
    if enabled():
        # band=0: informational residual (see the module docstring) —
        # the model is an upper bound; headroom must not alarm.  The
        # rate-card stamp reports how tight the bound has been running
        # on this host (learned measured/predicted ratio), so the
        # manifest can distinguish honest headroom from a stale model.
        from . import ratecard as _rc

        _ratio, _cap_prov = _rc.consult("capacity_residual_ratio", 1.0)
        obs.record_decision(
            "capacity", chosen, inputs=inputs,
            predicted={"bytes": float(total)},
            measured={"bytes": {"counters": ["mem/peak_tracked_bytes"]}},
            band=0, provenance=_cap_prov)
    return record


def plan_mesh_shards(total_len: int, cfg=None, budget_bytes: int = 0,
                     max_hosts: int = 0, record: bool = True) -> dict:
    """Choose the mesh host count for a job from the capacity model.

    The memory plane as PLANNER: instead of discovering at runtime
    that one host OOMs, the same geometry the ``capacity`` gate prices
    picks the minimal host count ``K`` whose PER-HOST predicted peak
    fits ``budget_bytes``.  Per-host bytes under a K-host
    position-sharded mesh: the count tensor and the tail's symbol
    planes divide by K (each host is resident for only its position
    window — ``parallel.base._track_counts`` bills the addressable
    fraction, so the prediction and the measurement speak the same
    units); staging does NOT divide (every host stages its own slab
    slots at full width).

    Returns ``{"hosts", "per_host_bytes", "single_host_bytes",
    "fits", "alternatives"}`` — ``fits`` is False when even
    ``max_hosts`` (0 = single host only) cannot bring the per-host
    peak under budget.  ``record=True`` registers the ``mesh_shards``
    priced ledger decision (predicted per-host bytes joined against
    the measured ``mem/peak_tracked_bytes`` ratchet at finalize;
    band=0 — the model is an upper bound, headroom must not alarm).
    """
    n_thresholds = len(getattr(cfg, "thresholds", None) or [0.25]) \
        if cfg is not None else 1
    chunk_reads = getattr(cfg, "chunk_reads", 262144) \
        if cfg is not None else 262144
    segment_width = max(0, getattr(cfg, "segment_width", 0)) \
        if cfg is not None else 0
    _total, comp = predict_run_peak_bytes(
        total_len, n_thresholds=n_thresholds, chunk_reads=chunk_reads,
        shards=1, segment_width=segment_width)

    def per_host(k: int) -> int:
        return (comp["counts_bytes"] // k + comp["staging_bytes"]
                + comp["tail_bytes"] // k)

    single = per_host(1)
    hosts_cap = max(1, int(max_hosts) or 1)
    alternatives = {str(k): float(per_host(k))
                    for k in range(1, hosts_cap + 1)}
    hosts, fits = 1, True
    if budget_bytes and single > budget_bytes:
        fits = False
        for k in range(2, hosts_cap + 1):
            if per_host(k) <= budget_bytes:
                hosts, fits = k, True
                break
        if not fits:
            hosts = hosts_cap
    plan = {
        "hosts": int(hosts),
        "per_host_bytes": int(per_host(hosts)),
        "single_host_bytes": int(single),
        "budget_bytes": int(budget_bytes),
        "fits": bool(fits),
        "alternatives": alternatives,
    }
    if record and enabled():
        from .. import observability as obs

        chosen = (f"hosts_{hosts}" if fits else "over_capacity")
        from . import ratecard as _rc

        _ratio, _mesh_prov = _rc.consult("capacity_residual_ratio",
                                         1.0)
        obs.record_decision(
            "mesh_shards", chosen,
            inputs={"total_len": int(total_len),
                    "budget_bytes": int(budget_bytes),
                    "max_hosts": int(hosts_cap), **comp},
            predicted={"per_host_bytes": float(plan["per_host_bytes"])},
            measured={"per_host_bytes":
                      {"counters": ["mem/peak_tracked_bytes"]}},
            alternatives=alternatives, band=0, provenance=_mesh_prov)
    return plan


def capacity_actuals() -> dict:
    """Predicted-vs-actual snapshot for the OOM-split rung
    (resilience/ladder.py): the last capacity prediction next to the
    tracked/process peaks at split time, so the split threshold stops
    being folklore."""
    cur, peak = rss_bytes()
    with _plane.lock:
        cap = _plane.last_capacity
        out = {
            "predicted_bytes": (cap or {}).get("predicted_bytes"),
            "live_tracked_bytes": _plane.total_live,
            "peak_tracked_bytes": _plane.total_peak,
            "rss_mb": round(cur / 1e6, 2),
            "peak_rss_mb": round(peak / 1e6, 2),
        }
    dev = device_memory_stats()
    if dev is not None:
        out["device_bytes_in_use"] = dev.get("bytes_in_use", 0)
    return out


# =========================================================================
# OOM forensics
# =========================================================================
def write_mem_dump(out_dir: str, exc: Optional[BaseException] = None,
                   registry=None, context: Optional[dict] = None
                   ) -> Optional[str]:
    """Write ``mem_dump.json`` into ``out_dir``; returns the path.
    Never raises — forensics must not replace one failure with
    another."""
    try:
        from .metrics import current as _current_registry
        from .telemetry import atomic_write_text
        from .trace import current_span_name

        if registry is None:
            registry = _current_registry()
        classification = None
        if exc is not None:
            try:
                from ..resilience.policy import classify

                classification = classify(exc)
            except Exception:
                classification = None
        snap = registry.snapshot()
        mem_counters = {k: v for k, v in snap["counters"].items()
                        if k.startswith(("mem/", "cache/evicted"))}
        with _plane.lock:
            fams = {f: {"live_bytes": _plane.live.get(f, 0),
                        "peak_bytes": _plane.peak.get(f, 0)}
                    for f in sorted(set(_plane.live) | set(_plane.peak))}
            totals = {"live_bytes": _plane.total_live,
                      "peak_bytes": _plane.total_peak}
            capacity = dict(_plane.last_capacity) \
                if _plane.last_capacity else None
        blob = {
            "schema": MEM_DUMP_SCHEMA,
            "created_unix": round(time.time(), 3),
            "pid": os.getpid(),
            "error": ({
                "type": type(exc).__name__,
                "message": str(exc),
                "classification": classification,
            } if exc is not None else None),
            "families": fams,
            "tracked": totals,
            "watermarks": sample(registry=registry),
            "watermark_tail": history_tail(),
            "capacity": capacity,
            "registry_mem_counters": mem_counters,
            "open_span": current_span_name(),
            "context": dict(context or {}),
        }
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, MEM_DUMP_NAME)
        atomic_write_text(path, json.dumps(blob, indent=1, default=str)
                          + "\n")
        logger.warning("memory forensics written to %s (%s)", path,
                       blob["error"])
        return path
    except Exception as dump_exc:
        logger.warning("mem_dump write failed: %s: %s",
                       type(dump_exc).__name__, dump_exc)
        return None


def dump_on_capacity(exc: BaseException, out_dir: Optional[str],
                     registry=None,
                     context: Optional[dict] = None) -> Optional[str]:
    """The OOM hook: write the forensic dump iff ``exc`` classifies
    CAPACITY (resilience/policy.py) and a destination exists.  Counted
    ``mem/oom_dumps`` so a job that died of memory says so from any
    artifact."""
    if not enabled() or not out_dir:
        return None
    try:
        from ..resilience.policy import CAPACITY, classify

        if classify(exc) != CAPACITY:
            return None
    except Exception:
        return None
    path = write_mem_dump(out_dir, exc=exc, registry=registry,
                          context=context)
    if path is not None:
        from .metrics import current as _current_registry

        (registry or _current_registry()).add("mem/oom_dumps", 1)
    return path


def _reset_for_tests() -> None:
    """Zero the process-wide plane (tests only — families are
    deliberately process-lifetime in production)."""
    global _plane
    _plane = _Plane()
