"""Fleet telemetry plane: server-lifetime aggregation + exposition.

Everything observability built so far is *per-job scoped* — each job
gets its own registry/trace/ledger/manifest, and the serve runner's
health snapshot is rewritten only at job boundaries.  That answers
"what did job 17 do" but not the operator questions a long-running
``s2c serve`` fleet actually gets paged on: *what is tenant X's p99
end-to-end latency this hour*, *is queue wait growing*, *is the
in-flight job making progress RIGHT NOW*.  This module is the layer
that answers them:

* :class:`AggregateRegistry` — a server-lifetime registry per-job
  registries **fold** into at job end: counters summed, gauges
  last-wins (stamped with the folding job + wall time), histograms
  merged through the existing decimating reservoir
  (:meth:`~.metrics.Histogram.merge`).  Live mid-job state (heartbeat
  age, in-flight job age) is written as gauges by the serve runner's
  watchdog tick, so a hung job is visible *while* it hangs;
* **SLO objectives** (:func:`parse_slo`) — ``e2e=5s,queue=1s`` /
  ``S2C_SLO`` over the serving phases ``queue_wait`` (alias
  ``queue``), ``decode``, ``dispatch``, ``vote``, ``e2e``.  The runner
  observes every finished job's per-phase latency into per-tenant
  histograms (``slo/<tenant>/<phase>``) and bumps the burn counters
  ``slo/violations/<tenant>/<phase>`` on breach — the counters ride
  into the health snapshot, the exposition, and each job's manifest
  ``serve.slo`` verdict;
* **OpenMetrics/Prometheus text exposition**
  (:func:`render_openmetrics`) — HELP/TYPE/label discipline over the
  aggregate snapshot, validated by :func:`lint_openmetrics` (promtool-
  style rules, incl. counter monotonicity across two scrapes).
  Written atomically on a time cadence (``--telemetry-out``) and
  served by the stdlib-only localhost endpoint
  (:class:`TelemetryServer`, ``--telemetry-port``: ``/metrics`` +
  ``/healthz`` from the same snapshot);
* **on-demand profiler capture** (:class:`ProfilerCapture`) — SIGUSR2
  or a ``capture_profile`` touch-file arms a bounded
  ``jax.profiler.trace()`` window (pure-Python span/stack dump
  fallback on cpu), written next to the journal, so a misbehaving
  production job can be profiled without restarting the server;
* **structured JSON logging** (:class:`JsonLogFormatter` +
  :func:`set_log_context`) — ``--log-format json``: every record
  carries job_id/tenant/rung/trace-span correlation IDs.

Failure semantics: the telemetry plane is strictly best-effort.  A
write failure degrades to the per-job manifests (counted
``telemetry/write_failed``, warned once per failure) and NEVER fails a
job — the exposition is derived state; the job's own registry/manifest
remain the durable record.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from .metrics import MetricsRegistry

logger = logging.getLogger("sam2consensus_tpu.observability.telemetry")

#: the serving phases SLO objectives can be set over, in pipeline
#: order.  ``queue_wait`` is submission-to-start wall time; ``decode``
#: / ``dispatch`` / ``vote`` map onto the canonical phase counters
#: (dispatch = pileup_dispatch + accumulate + stage, vote = vote +
#: insertions + render); ``e2e`` is the job's full wall clock.
SLO_PHASES = ("queue_wait", "decode", "dispatch", "vote", "e2e")

#: flag-grammar aliases -> canonical phase names
_SLO_ALIASES = {"queue": "queue_wait", "queue_wait": "queue_wait",
                "decode": "decode", "dispatch": "dispatch",
                "vote": "vote", "e2e": "e2e"}

#: default exposition rewrite cadence (seconds); S2C_TELEMETRY_INTERVAL
#: overrides.  One atomic rewrite of a few KB per tick — cheap enough
#: to ride the watchdog poll, slow enough to never matter.
DEFAULT_INTERVAL_S = 2.0

#: default bounded profiler-capture window (seconds);
#: S2C_PROFILE_CAPTURE_S overrides
DEFAULT_CAPTURE_S = 3.0

#: the touch-file name that arms a profiler capture (polled by the
#: serve runner's watchdog tick, consumed on arm)
CAPTURE_TOUCH_NAME = "capture_profile"


# =========================================================================
# SLO objectives
# =========================================================================
def parse_slo(spec: Optional[str]) -> Dict[str, float]:
    """``e2e=5s,queue=1s`` -> ``{"e2e": 5.0, "queue_wait": 1.0}``.

    Grammar: comma-separated ``<phase>=<number>[ms|s]`` (bare numbers
    are seconds).  Unknown phases and unparsable values raise
    ``ValueError`` — a typo'd objective must fail the server start,
    not silently never fire.  ``None``/empty falls back to ``S2C_SLO``
    then to no objectives at all.
    """
    raw = spec if spec else os.environ.get("S2C_SLO", "")
    out: Dict[str, float] = {}
    if not raw or not raw.strip():
        return out
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"bad SLO entry {part!r}: expected <phase>=<seconds>"
                f" (phases: {', '.join(sorted(set(_SLO_ALIASES)))})")
        name, _, val = part.partition("=")
        phase = _SLO_ALIASES.get(name.strip().lower())
        if phase is None:
            raise ValueError(
                f"unknown SLO phase {name.strip()!r} "
                f"(use one of: {', '.join(sorted(set(_SLO_ALIASES)))})")
        val = val.strip().lower()
        scale = 1.0
        if val.endswith("ms"):
            val, scale = val[:-2], 1e-3
        elif val.endswith("s"):
            val = val[:-1]
        try:
            sec = float(val) * scale
        except ValueError:
            raise ValueError(
                f"bad SLO value for {phase}: {part!r} "
                f"(expected e.g. {phase}=5s or {phase}=250ms)") from None
        if not sec > 0:
            raise ValueError(f"SLO objective must be > 0: {part!r}")
        out[phase] = sec
    return out


def slo_phase_seconds(counters: dict, elapsed_sec: float,
                      queue_wait_sec: float) -> Dict[str, float]:
    """Map one finished job's registry counters onto the SLO phases."""
    return {
        "queue_wait": max(0.0, queue_wait_sec),
        "decode": counters.get("phase/decode_sec", 0.0),
        "dispatch": (counters.get("phase/pileup_dispatch_sec", 0.0)
                     + counters.get("phase/accumulate_sec", 0.0)
                     + counters.get("phase/stage_sec", 0.0)),
        "vote": (counters.get("phase/vote_sec", 0.0)
                 + counters.get("phase/insertions_sec", 0.0)
                 + counters.get("phase/render_sec", 0.0)),
        "e2e": max(0.0, elapsed_sec),
    }


# =========================================================================
# Server-lifetime aggregation
# =========================================================================
class AggregateRegistry(MetricsRegistry):
    """A server-lifetime registry per-job registries fold into.

    Subclasses :class:`MetricsRegistry` so every existing reader (the
    health snapshot, ``registry.value``, the manifest) keeps working;
    adds :meth:`fold`, the job-end merge:

    * counters sum — EXCEPT the ``serve/`` and ``slo/`` families,
      which the runner owns at server scope already (folding its own
      mirrors back in would double-count every retry/overlap second);
    * gauges last-wins, info payload stamped with the folding job id
      and wall time so "whose value is this" survives aggregation;
    * histograms merge exactly on count/sum/min/max and fold their
      decimating reservoirs (:meth:`~.metrics.Histogram.merge`), so
      fleet-level percentiles stay meaningful.
    """

    #: counter families the serve runner already records at server
    #: scope — folding a job's copies would double-count
    # cache/: the count cache bills the server registry DIRECTLY
    # (serve/countcache.py gets/puts pass it) while each incremental
    # job's registry carries its own cache/{hits,misses} copy for the
    # per-job manifest — folding that copy would double-count the
    # server-lifetime family
    # mem/: the memory plane's per-registry PEAK ratchets are maxima,
    # not flows — summing per-job peaks would report jobs_folded x the
    # real footprint.  The watchdog-tick sampler
    # (observability/memplane.sample) publishes the server-lifetime
    # mem/* family into this registry directly instead.
    # fleet/: the claim/lease counters are runner-owned coordination
    # state (serve/fleet.py records them straight into the server
    # registry); a job registry carrying a copy would double-count
    # sched/: the flight recorder's scheduler telemetry (queue-wait /
    # claim / steal distributions, lease churn, occupancy) is likewise
    # runner-owned — derived from journal wall times at finalize, not
    # from anything a job's own registry could know.  The one sched/
    # name a JOB registry carries (the sched/trace info gauge stamping
    # trace_id into the metrics artifact) must not leak into the
    # server aggregate either: the last-folded job would overwrite it.
    # rate/ + burn/ + process/: the learned rate card, the windowed
    # burn plane and the start-time gauge are likewise runner-owned —
    # folded-in job registries never carry them, and a job that DID
    # (a test fixture, a future leak) must not overwrite the server's
    # card state or alerting state
    FOLD_SKIP_PREFIXES = ("serve/", "slo/", "telemetry/", "cache/",
                          "mem/", "fleet/", "sched/", "rate/",
                          "burn/", "process/")

    def fold(self, registry: MetricsRegistry, job_id: str = "",
             tenant: str = "") -> None:
        snap = registry.snapshot()
        now = round(time.time(), 3)
        for name, value in snap["counters"].items():
            if name.startswith(self.FOLD_SKIP_PREFIXES):
                continue
            self.add(name, value)
        for name, entry in snap["gauges"].items():
            if name.startswith(self.FOLD_SKIP_PREFIXES):
                continue
            g = self.gauge(name)
            g.set(entry["value"])
            info = dict(entry.get("info") or {})
            info["folded_from"] = job_id
            if tenant:
                info["tenant"] = tenant
            info["updated_unix"] = now
            g.set_info(info)
        # merge the actual reservoirs, not the snapshot summaries —
        # count/sum/min/max merge exactly, percentiles approximately
        # (the documented decimating-reservoir contract).  The name
        # list is copied under the SOURCE registry's lock: an
        # abandoned watchdog worker may still be recording into its
        # job's registry when the runner folds it, and an unlocked
        # dict iteration would crash the fold ("dictionary changed
        # size") — losing exactly the timed-out job's numbers
        with registry._lock:
            hist_items = list(registry._hists.items())
        for name, hist in hist_items:
            if name.startswith(self.FOLD_SKIP_PREFIXES):
                continue
            with self._lock:
                mine = self._hists.get(name)
                if mine is None:
                    from .metrics import Histogram

                    mine = self._hists[name] = Histogram()
                mine.merge(hist)
        self.add("telemetry/jobs_folded", 1)


# =========================================================================
# Atomic file writer (shared with serve/health.py)
# =========================================================================
def atomic_write_text(path: str, text: str) -> None:
    """tmp + fsync + ``os.replace``: a reader polling ``path`` never
    sees a torn file.  The ONE writer discipline behind the health
    snapshot, the exposition file, and the journal segments."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(text)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


# =========================================================================
# OpenMetrics / Prometheus text exposition
# =========================================================================
_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_:]")

#: HELP strings for the families an operator will actually grep for;
#: everything else gets a generic registry-metric line
_HELP = {
    "s2c_phase_seconds_total": "Cumulative seconds per pipeline phase "
                               "across all folded jobs.",
    "s2c_slo_phase_seconds": "Per-tenant per-phase job latency "
                             "(merged decimating reservoir).",
    "s2c_slo_violations_total": "Jobs that breached the configured "
                                "latency objective, per tenant/phase.",
    "s2c_serve_jobs_total": "Jobs run by this server (lifetime).",
    "s2c_serve_jobs_failed_total": "Jobs that failed (lifetime).",
    "s2c_serve_heartbeat_age_sec": "Seconds since the last dispatch "
                                   "heartbeat (grows while a job "
                                   "hangs).",
    "s2c_serve_inflight_age_sec": "Age of the in-flight job (0 when "
                                  "idle).",
    "s2c_serve_queue_depth": "Jobs admitted and not yet finished.",
    "s2c_serve_up": "1 while the serve runner is alive.",
    "s2c_serve_uptime_sec": "Server lifetime in seconds.",
    "s2c_telemetry_profile_captures_total": "On-demand profiler "
                                            "captures taken.",
    "s2c_telemetry_jobs_folded_total": "Per-job registries folded into "
                                       "this server-lifetime "
                                       "aggregate.",
    "s2c_telemetry_write_failed_total": "Exposition/health writes that "
                                        "failed (telemetry degrades, "
                                        "jobs never fail).",
    # continuous batching (serve/scheduler.py): the s2c_batch_* family
    "s2c_batch_batches_total": "Packed batches executed (continuous "
                               "batching, --batch).",
    "s2c_batch_packed_jobs_total": "Jobs that rode a packed batch's "
                                   "shared dispatch.",
    "s2c_batch_demotions_total": "Batches demoted whole to the serial "
                                 "path (fault inside a packed phase).",
    "s2c_batch_tail_demotions_total": "Shared-tail failures demoted to "
                                      "per-member extraction tails.",
    "s2c_batch_pack_sec_total": "Cumulative non-dispatch shared-phase "
                                "seconds (merge/extract/fetch).",
    "s2c_batch_size": "Members in the most recent packed batch.",
    "s2c_batch_occupancy_pct": "Real rows / padded rows of the last "
                               "batch's merged slabs, percent.",
    "s2c_batch_jobs_per_sec": "Last batch's shared-phase throughput "
                              "(members / shared wall).",
    # cohort serving (serve/cohort.py): the s2c_cohort_* family —
    # manifest-streamed shared-panel waves
    "s2c_cohort_waves_done": "Cohort waves fully finalized (journal "
                             "cohort_wave markers written).",
    "s2c_cohort_waves_total": "Estimated total waves (done + remaining "
                              "at the last wave's size).",
    "s2c_cohort_samples_done": "Cohort members finished or resumed "
                               "from the journal.",
    "s2c_cohort_samples_total": "Members the manifest resolved to.",
    "s2c_cohort_jobs_per_sec": "Last wave's measured throughput "
                               "(ok members / wave wall).",
    "s2c_cohort_occupancy_pct": "Packed-slab occupancy of the last "
                                "wave's batch, percent.",
    "s2c_cohort_wave_wall_sec_total": "Cumulative wave wall seconds "
                                      "(cohort_wave decisions' "
                                      "measured denominator).",
    "s2c_cohort_wave_jobs_total": "Members that finished OK inside a "
                                  "packed cohort wave.",
    "s2c_cohort_resumed_skipped_total": "Members skipped at cohort "
                                        "start (journal-committed "
                                        "with verified outputs).",
    "s2c_cohort_prefetch_failed_total": "Wave-ahead header probes that "
                                        "failed (the wave re-probes "
                                        "inline).",
    "s2c_cohort_admission_trips_total": "Wave sizes rejected by "
                                        "admission and halved before "
                                        "dispatch.",
    "s2c_cohort_concordance_oracle_members_total":
        "Serially-run members back-filled into the concordance table "
        "via the CPU oracle accumulation.",
    "s2c_cohort_concordance_skipped_total":
        "Members whose counts reached neither the tap nor the oracle "
        "(absent from the concordance table).",
    # incremental consensus (serve/countcache.py): the s2c_cache_*
    # family — per-reference device-resident count cache
    "s2c_cache_entries": "References with warm count state resident "
                         "in the serve count cache.",
    "s2c_cache_resident_bytes": "Bytes of count+insertion state the "
                                "cache holds (LRU under "
                                "--count-cache).",
    "s2c_cache_hits_total": "Incremental jobs seeded from a warm "
                            "reference (paid only delta decode + "
                            "scatter + re-vote).",
    "s2c_cache_misses_total": "Incremental jobs that absorbed their "
                              "input cold (no warm entry).",
    "s2c_cache_evictions_total": "Entries evicted by the LRU byte "
                                 "budget.",
    "s2c_cache_evicted_bytes_total": "Bytes of warm count state "
                                     "evicted under the LRU budget "
                                     "(the silent-pressure signal: a "
                                     "growing rate means the budget "
                                     "is churning).",
    "s2c_cache_invalidated_total": "Entries dropped whole after a "
                                   "seeded job failed (the count-bank "
                                   "rule).",
    "s2c_cache_inserts_total": "Entries (re-)inserted at job commit.",
    # device-resident epilogue (ops/fused.py): where the render
    # epilogue ran per tail
    "s2c_epilogue_device_tails_total": "Tails whose fill substitution "
                                       "+ dash counts ran on device "
                                       "(fetched bytes are final "
                                       "FASTA).",
    "s2c_epilogue_host_tails_total": "Tails whose render epilogue ran "
                                     "host-side (sharded/native/"
                                     "unrepresentable fill).",
    # memory plane (observability/memplane.py): the s2c_mem_* family
    "s2c_mem_live_bytes": "Live tracked bytes per allocation family "
                          "(counts/staging/caches/... — see "
                          "observability/memplane.py).",
    "s2c_mem_peak_bytes": "Peak tracked bytes per allocation family "
                          "since this registry started.",
    "s2c_mem_live_tracked_bytes": "Live tracked bytes across all "
                                  "allocation families.",
    "s2c_mem_peak_tracked_bytes_total": "Peak-tracked-bytes ratchet "
                                        "(monotone; the capacity "
                                        "ledger decision's measured "
                                        "side).",
    "s2c_mem_rss_mb": "Process resident set size, MB (watermark "
                      "sampler on the watchdog/telemetry tick).",
    "s2c_mem_peak_rss_mb": "Process peak RSS, MB (ru_maxrss).",
    "s2c_mem_device_bytes_in_use": "Device bytes in use where the "
                                   "backend exposes memory_stats() "
                                   "(absent on CPU).",
    "s2c_mem_device_peak_bytes": "Device peak bytes in use where "
                                 "exposed.",
    "s2c_mem_oom_dumps_total": "CAPACITY-class failures that wrote a "
                               "mem_dump.json forensic record.",
    "s2c_serve_admission_capacity_total": "Jobs shed because their "
                                          "predicted peak exceeded "
                                          "--mem-budget (queued-not-"
                                          "OOMed).",
    "s2c_serve_admission_mesh_total": "Over-budget jobs admitted with "
                                      "a capacity-planned 'needs K "
                                      "hosts' mesh_shards verdict "
                                      "instead of being shed.",
    # mesh plane (parallel/partition.py): the s2c_mesh_* family —
    # topology + shard/gather traffic of the sharded count tensor
    "s2c_mesh_hosts": "Distinct processes owning the active mesh's "
                      "devices (1 on any single-controller mesh).",
    "s2c_mesh_shards": "Device count of the active ('dp','sp') mesh "
                       "(the count tensor's position shard count).",
    "s2c_mesh_planned_hosts": "Host count the admission-time "
                              "mesh_shards capacity plan chose for "
                              "the most recent over-budget job.",
    "s2c_mesh_shard_bytes_total": "Bytes THIS process shipped to its "
                                  "own devices' shards on a process-"
                                  "spanning mesh (host label = "
                                  "process index; counts never ride "
                                  "DCN on the way in).",
    "s2c_mesh_gather_bytes_total": "Bytes landed on this host by "
                                   "cross-process gathers "
                                   "(process_allgather tails: vote "
                                   "symbols and stats, never raw "
                                   "counts).",
    "s2c_serve_oom_dumps_total": "Serve jobs whose CAPACITY failure "
                                 "wrote a mem_dump.json next to the "
                                 "journal.",
    # fleet mode (serve/fleet.py): the s2c_fleet_* family — every
    # sample additionally carries a worker="<id>" label so
    # tools/s2c_top.py --fleet can merge N workers' expositions
    "s2c_fleet_claims_total": "Job leases this worker won (fleet "
                              "work-stealing over the shared "
                              "journal).",
    "s2c_fleet_claim_lost_total": "Claim races this worker lost to a "
                                  "peer (it moved on; the peer runs "
                                  "the job).",
    "s2c_fleet_steals_total": "Expired peer leases this worker reaped "
                              "AND re-claimed (dead/frozen worker's "
                              "job resumed from its checkpoint).",
    "s2c_fleet_lease_renewals_total": "Lease TTL renewals on the "
                                      "watchdog tick.",
    "s2c_fleet_lease_reaped_total": "Peer leases this worker marked "
                                    "expired (lease_expired events "
                                    "appended).",
    "s2c_fleet_lease_lost_total": "Jobs this worker finished but "
                                  "could NOT commit: its lease had "
                                  "been reaped mid-run (result "
                                  "abandoned, the thief commits).",
    "s2c_fleet_completed_elsewhere_total": "Queue entries resolved by "
                                           "a peer's journal commit "
                                           "(this worker never "
                                           "decoded a byte).",
    "s2c_fleet_failed_elsewhere_total": "Queue entries a peer "
                                        "journaled as failed "
                                        "(terminal, like a local "
                                        "failure).",
    "s2c_fleet_journal_write_failed_total": "Fleet journal appends "
                                            "that failed (an "
                                            "unjournaled claim is "
                                            "simply not held).",
    "s2c_fleet_leases_held": "Leases this worker currently holds.",
    # flight recorder (observability/flight.py): journal-measured
    # scheduler telemetry — the s2c_sched_* family
    "s2c_sched_seconds": "Journal-measured scheduler latency summary "
                         "per tenant: kind=queue_wait (submitted -> "
                         "started wall time, the SLO plane's "
                         "queue-wait truth source), kind="
                         "claim_latency (submitted -> this worker won "
                         "the lease), kind=steal_latency (victim's "
                         "last lease sign of life -> winning "
                         "re-claim; bounded by ~2x lease TTL).",
    "s2c_sched_lease_churn_total": "Lease-lifecycle turnover this "
                                   "worker observed: reaps it "
                                   "appended, claim races it lost, "
                                   "leases it lost mid-run. High "
                                   "churn with low steals means "
                                   "contention, not failure "
                                   "recovery.",
    "s2c_sched_occupancy_ratio": "Fraction of this worker's serve "
                                 "uptime spent running jobs "
                                 "(busy-seconds / uptime; the "
                                 "flight recorder's per-worker "
                                 "occupancy lane, live).",
    # streaming sessions (serve/session.py + serve/stream_server.py):
    # the s2c_session_* / s2c_ingest_* families — the live-ingest plane
    "s2c_session_opened_total": "Streaming sessions opened (lifetime).",
    "s2c_session_closed_total": "Streaming sessions closed cleanly "
                                "(final outputs written).",
    "s2c_session_waves_total": "Read waves journaled as received "
                               "(durable intent precedes the ACK).",
    "s2c_session_waves_absorbed_total": "Waves absorbed exactly once "
                                        "into session count state "
                                        "(wave_absorbed journaled, "
                                        "lease-fenced).",
    "s2c_session_waves_rejected_total": "Waves rejected DATA-class "
                                        "(malformed/poison/sha "
                                        "mismatch; quarantined, never "
                                        "retried).",
    "s2c_session_waves_shed_total": "Waves shed by admission "
                                    "backpressure (429 + Retry-After; "
                                    "pending backlog at its bound).",
    "s2c_session_torn_waves_total": "Spooled wave bodies whose hash no "
                                    "longer matched the journaled "
                                    "intent (re-requested, never "
                                    "absorbed).",
    "s2c_session_revotes_total": "Consensus re-votes over already-"
                                 "absorbed counts (zero re-ingest).",
    "s2c_session_stability_events_total": "Sessions whose consensus "
                                          "digest survived N "
                                          "consecutive waves unchanged "
                                          "(the read-until verdict).",
    "s2c_session_steals_total": "Orphaned sessions this worker stole "
                                "lease-and-all from a dead/frozen "
                                "peer (journaled waves replayed; "
                                "zero lost, zero double-counted).",
    "s2c_session_recovered_total": "Sessions rebuilt from journal "
                                   "replay (restart resume + fleet "
                                   "steals).",
    "s2c_session_reads_absorbed_total": "Reads absorbed across all "
                                        "sessions (lifetime).",
    "s2c_session_open": "Streaming sessions currently open on this "
                        "worker.",
    "s2c_session_pending_waves": "Journaled-but-unabsorbed waves "
                                 "across open sessions (the "
                                 "backpressure gauge).",
    "s2c_ingest_requests_total": "HTTP requests the ingest endpoint "
                                 "answered (lifetime).",
    "s2c_ingest_rejected_total": "Ingest requests rejected with a "
                                 "typed status (+ per-reason "
                                 "children).",
    "s2c_ingest_bytes_total": "Wave/header body bytes the ingest "
                              "endpoint accepted.",
    "s2c_ingest_slow_clients_total": "Requests killed by the "
                                     "per-request socket deadline "
                                     "(408; the handler thread is "
                                     "freed, never wedged).",
    # -- rate cards / burn alerts / scale hints (PR 19) -------------
    "s2c_rate": "Learned rate-card EWMA mean per rate key "
                "(observability/ratecard.py; served to decision "
                "sites only past the min-sample + staleness gates).",
    "s2c_rate_stddev": "Rate-card exponentially-weighted standard "
                       "deviation per rate key.",
    "s2c_rate_samples": "Rate-card observation count per rate key "
                        "(below the min-sample gate the key is not "
                        "served).",
    "s2c_rate_age_seconds": "Seconds since the rate key's last "
                            "observation (past S2C_LINK_CACHE_MAX_AGE "
                            "the key reads as stale and is not "
                            "served).",
    "s2c_rate_card": "Rate-card restart epoch (successful reloads of "
                     "the persisted card; the restart_epoch label's "
                     "source).",
    "s2c_rate_card_corrupt_total": "Persisted rate-card files that "
                                   "failed to parse and were read as "
                                   "absent (never fails a job).",
    "s2c_burn_rate": "Windowed SLO burn rate per tenant "
                     "(violated/evaluated objectives over the "
                     "trailing window; window=fast|slow).",
    "s2c_burn_alert_state": "Burn alert state per tenant "
                            "(0=ok 1=warn 2=page; hysteresis in "
                            "observability/burn.py).",
    "s2c_fleet_scale_hint": "Evidence-only fleet sizing hint: worker "
                            "delta (sign is the verdict — positive "
                            "scale-up, negative scale-down, 0 hold). "
                            "No actuation.",
    "s2c_process_start_time_seconds": "Unix time the serve process "
                                      "started (the OpenMetrics "
                                      "counter-reset detection "
                                      "convention).",
}


def _sanitize(name: str) -> str:
    out = "s2c_" + _SANITIZE_RE.sub("_", name)
    if not _NAME_RE.match(out):            # leading digit after prefix
        out = "s2c_" + _SANITIZE_RE.sub("_", "_" + name)
    return out


def _escape_label(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"') \
        .replace("\n", r"\n")


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels(pairs: List[Tuple[str, str]]) -> str:
    if not pairs:
        return ""
    return ("{" + ",".join(f'{k}="{_escape_label(v)}"'
                           for k, v in pairs) + "}")


class _Family:
    __slots__ = ("name", "ftype", "samples")

    def __init__(self, name: str, ftype: str):
        self.name = name
        self.ftype = ftype
        self.samples: List[Tuple[str, List[Tuple[str, str]], float]] = []

    def add(self, suffix: str, labels, value) -> None:
        self.samples.append((self.name + suffix, list(labels),
                             float(value)))


def render_openmetrics(snapshot: dict,
                       worker: Optional[str] = None,
                       restart_epoch: Optional[int] = None) -> str:
    """Registry snapshot -> Prometheus/OpenMetrics text exposition.

    Structured families get proper labels instead of path-encoded
    names: ``phase/<p>_sec`` counters -> ``s2c_phase_seconds_total
    {phase=...}``, ``slo/<tenant>/<phase>`` histograms ->
    ``s2c_slo_phase_seconds{tenant=,phase=,quantile=}`` summaries,
    ``slo/violations/<tenant>/<phase>`` ->
    ``s2c_slo_violations_total{tenant=,phase=}``.  Everything else is
    rendered flat under a sanitized ``s2c_`` name (counters suffixed
    ``_total``).  Output is sorted and deterministic; ends with
    ``# EOF``.

    ``worker`` (fleet mode, ``--worker-id``) stamps EVERY sample with
    a trailing ``worker="<id>"`` label, so N workers' expositions
    merge into one fleet view (``tools/s2c_top.py --fleet``, or any
    Prometheus scraping all of them) without sample collisions.
    ``restart_epoch`` (the rate card's reload count) rides along as a
    ``restart_epoch`` label: across a worker restart the labelset
    changes, so a scraper's monotonicity check sees a NEW series
    instead of a counter going backwards — counter resets become
    detectable instead of lint violations.
    """
    fams: Dict[str, _Family] = {}

    def fam(name: str, ftype: str) -> _Family:
        f = fams.get(name)
        if f is None:
            f = fams[name] = _Family(name, ftype)
        return f

    for name, value in snapshot.get("counters", {}).items():
        m = re.match(r"^phase/(.+)_sec$", name)
        if m:
            fam("s2c_phase_seconds_total", "counter").add(
                "", [("phase", m.group(1))], value)
            continue
        m = re.match(r"^slo/violations/([^/]*)/([^/]+)$", name)
        if m:
            fam("s2c_slo_violations_total", "counter").add(
                "", [("tenant", m.group(1) or "default"),
                     ("phase", m.group(2))], value)
            continue
        m = re.match(r"^mesh/shard_bytes/(\d+)$", name)
        if m:
            # per-host shard traffic: one labeled series per process
            # index instead of a sanitized name per host
            fam("s2c_mesh_shard_bytes_total", "counter").add(
                "", [("host", m.group(1))], value)
            continue
        n = _sanitize(name)
        if not n.endswith("_total"):
            n += "_total"
        fam(n, "counter").add("", [], value)
    for name, entry in snapshot.get("gauges", {}).items():
        # info payloads are manifest material, not exposition material;
        # only the scalar value ships
        m = re.match(r"^mem/(live|peak)_bytes/(.+)$", name)
        if m:
            # per-family residency gauges get a proper family label
            # instead of one sanitized series per allocation family
            fam(f"s2c_mem_{m.group(1)}_bytes", "gauge").add(
                "", [("family", m.group(2))], entry["value"])
            continue
        m = re.match(r"^rate/(mean|stddev|samples|age_seconds)/(.+)$",
                     name)
        if m:
            # rate-card estimators: one labeled family per statistic
            # instead of a sanitized series per rate key
            suffix = "" if m.group(1) == "mean" else f"_{m.group(1)}"
            fam(f"s2c_rate{suffix}", "gauge").add(
                "", [("key", m.group(2))], entry["value"])
            continue
        m = re.match(r"^burn/rate/([^/]*)/(fast|slow)$", name)
        if m:
            fam("s2c_burn_rate", "gauge").add(
                "", [("tenant", m.group(1) or "default"),
                     ("window", m.group(2))], entry["value"])
            continue
        m = re.match(r"^burn/state/([^/]*)$", name)
        if m:
            fam("s2c_burn_alert_state", "gauge").add(
                "", [("tenant", m.group(1) or "default")],
                entry["value"])
            continue
        fam(_sanitize(name), "gauge").add("", [], entry["value"])
    for name, entry in snapshot.get("histograms", {}).items():
        if name.startswith("burn/"):
            # the burn monitor's windowed rings are internal state —
            # the derived s2c_burn_rate/s2c_burn_alert_state gauges
            # are the exposition surface (a raw per-tenant summary
            # family here would be a series-per-tenant explosion)
            continue
        m = re.match(r"^sched/([^/]*)/([^/]+)$", name)
        if m:
            # flight-recorder scheduler distributions: kind is the
            # latency being measured (queue_wait / claim_latency /
            # steal_latency), tenant-labeled like the SLO families
            labels = [("tenant", m.group(1) or "default"),
                      ("kind", m.group(2))]
            f = fam("s2c_sched_seconds", "summary")
        elif (m := re.match(r"^slo/([^/]*)/([^/]+)$", name)):
            labels = [("tenant", m.group(1) or "default"),
                      ("phase", m.group(2))]
            f = fam("s2c_slo_phase_seconds", "summary")
        else:
            labels = []
            f = fam(_sanitize(name), "summary")
        for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
            f.add("", labels + [("quantile", q)], entry[key])
        f.add("_sum", labels, entry["sum"])
        f.add("_count", labels, entry["count"])

    wlabel = [("worker", worker)] if worker else []
    if restart_epoch is not None:
        wlabel = wlabel + [("restart_epoch", str(int(restart_epoch)))]
    lines: List[str] = []
    for name in sorted(fams):
        f = fams[name]
        help_txt = _HELP.get(name, f"sam2consensus-tpu registry metric "
                                   f"{name}.")
        lines.append(f"# HELP {name} "
                     + help_txt.replace("\\", r"\\").replace("\n", r"\n"))
        lines.append(f"# TYPE {name} {f.ftype}")
        for sname, labels, value in sorted(
                f.samples, key=lambda s: (s[0], s[1])):
            lines.append(
                f"{sname}{_labels(labels + wlabel)} {_fmt(value)}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


# -- exposition parsing + lint --------------------------------------------
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>\S+)(?:\s+\S+)?$")
_LABEL_RE = re.compile(
    r'\s*(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)\s*=\s*"(?P<v>(?:[^"\\]|\\.)*)"'
    r"\s*(?P<sep>,|$)")
_ESCAPE_RE = re.compile(r"\\(.)")


def parse_openmetrics(text: str) -> List[dict]:
    """Exposition text -> ``[{name, labels, value}, ...]`` sample rows
    (comments dropped).  The read side of :func:`render_openmetrics`
    used by tools/s2c_top.py; raises ``ValueError`` on a malformed
    sample line."""
    out = []
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip() or line.startswith("#"):
            continue
        name, labels = _parse_sample(line, lineno)
        m = _SAMPLE_RE.match(line)
        out.append({"name": name, "labels": labels,
                    "value": float(m.group("value"))})
    return out


def _parse_sample(line: str, lineno: int):
    m = _SAMPLE_RE.match(line)
    if not m:
        raise ValueError(f"line {lineno}: unparsable sample {line!r}")
    labels: Dict[str, str] = {}
    raw = m.group("labels")
    if raw is not None:
        pos = 0
        while pos < len(raw):
            lm = _LABEL_RE.match(raw, pos)
            if not lm:
                raise ValueError(
                    f"line {lineno}: bad label syntax in {line!r}")
            val = lm.group("v")
            for esc in re.finditer(r"\\(.)", val):
                if esc.group(1) not in ('\\', '"', 'n'):
                    raise ValueError(
                        f"line {lineno}: invalid escape "
                        f"\\{esc.group(1)} in label value")
            labels[lm.group("k")] = _ESCAPE_RE.sub(
                lambda e: {"\\": "\\", '"': '"', "n": "\n"}[e.group(1)],
                val)
            pos = lm.end()
            if lm.group("sep") == "" and pos < len(raw):
                raise ValueError(
                    f"line {lineno}: trailing junk in labels {raw!r}")
    try:
        float(m.group("value"))
    except ValueError:
        raise ValueError(
            f"line {lineno}: non-numeric value in {line!r}") from None
    return m.group("name"), labels


def lint_openmetrics(text: str,
                     prev: Optional[str] = None) -> List[str]:
    """Promtool-style format lint; returns violations (empty = clean).

    Rules: metric/label name charset; label-value escaping; exactly
    one TYPE per family, declared before its samples; every sample
    belongs to a declared family (summary families own their ``_sum``/
    ``_count`` children); counter samples are finite, non-negative and
    ``_total``-suffixed; quantile labels in [0, 1]; no duplicate
    (name, labelset) sample; the exposition ends with ``# EOF``.  With
    ``prev`` (an earlier scrape of the same endpoint) counters must be
    monotone non-decreasing — the rule that catches a "counter" that
    is secretly a gauge.

    Restart-epoch rules (PR 19): a ``restart_epoch`` label value must
    be a non-negative integer, and any exposition carrying one must
    also expose ``s2c_process_start_time_seconds`` — the two signals a
    scraper needs to tell a counter RESET (new epoch, new start time,
    fresh series) from a counter going backwards (same epoch: still a
    violation, and still caught by the ``prev`` check because the
    labelsets match).
    """
    errs: List[str] = []
    saw_restart_epoch = False
    saw_start_time = False
    types: Dict[str, str] = {}
    fam_sampled: set = set()
    seen: set = set()
    samples: Dict[Tuple[str, tuple], float] = {}
    lines = text.splitlines()
    for lineno, line in enumerate(lines, 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 2 and parts[1] == "TYPE":
                if len(parts) < 4:
                    errs.append(f"line {lineno}: malformed TYPE line")
                    continue
                fname, ftype = parts[2], parts[3].strip()
                if not _NAME_RE.match(fname):
                    errs.append(f"line {lineno}: bad family name "
                                f"{fname!r}")
                if ftype not in ("counter", "gauge", "summary",
                                 "histogram", "untyped", "info"):
                    errs.append(f"line {lineno}: unknown TYPE {ftype!r}")
                if fname in types:
                    errs.append(f"line {lineno}: duplicate TYPE for "
                                f"family {fname!r}")
                elif fname in fam_sampled:
                    errs.append(f"line {lineno}: TYPE for {fname!r} "
                                f"after its samples")
                else:
                    types[fname] = ftype
            continue
        try:
            name, labels = _parse_sample(line, lineno)
        except ValueError as exc:
            errs.append(str(exc))
            continue
        value = float(_SAMPLE_RE.match(line).group("value"))
        for k in labels:
            if not _LABEL_NAME_RE.match(k):
                errs.append(f"line {lineno}: bad label name {k!r}")
        if name == "s2c_process_start_time_seconds":
            saw_start_time = True
        if "restart_epoch" in labels:
            saw_restart_epoch = True
            if not labels["restart_epoch"].isdigit():
                errs.append(
                    f"line {lineno}: restart_epoch label "
                    f"{labels['restart_epoch']!r} is not a "
                    f"non-negative integer")
        family = name
        if family not in types:
            for suffix in ("_sum", "_count"):
                base = name[:-len(suffix)] if name.endswith(suffix) \
                    else None
                if base and types.get(base) in ("summary", "histogram"):
                    family = base
                    break
        if family not in types:
            errs.append(f"line {lineno}: sample {name!r} has no "
                        f"preceding TYPE declaration")
        else:
            fam_sampled.add(family)
            ftype = types[family]
            if ftype == "counter":
                if not name.endswith("_total"):
                    errs.append(f"line {lineno}: counter sample "
                                f"{name!r} not suffixed _total")
                if not (value >= 0.0) or value != value \
                        or value == float("inf"):
                    errs.append(f"line {lineno}: counter {name!r} has "
                                f"non-finite/negative value {value}")
            if "quantile" in labels:
                try:
                    q = float(labels["quantile"])
                    if not 0.0 <= q <= 1.0:
                        raise ValueError
                except ValueError:
                    errs.append(f"line {lineno}: quantile label "
                                f"{labels['quantile']!r} outside [0,1]")
        key = (name, tuple(sorted(labels.items())))
        if key in seen:
            errs.append(f"line {lineno}: duplicate sample {name}"
                        f"{dict(labels)}")
        seen.add(key)
        samples[key] = value
    tail = [ln for ln in lines if ln.strip()]
    if not tail or tail[-1].strip() != "# EOF":
        errs.append("exposition does not end with # EOF")
    if saw_restart_epoch and not saw_start_time:
        errs.append("restart_epoch labels present without an "
                    "s2c_process_start_time_seconds sample (scrapers "
                    "cannot confirm the reset)")
    if prev is not None:
        prev_errs = []
        prev_samples: Dict[Tuple[str, tuple], float] = {}
        prev_types: Dict[str, str] = {}
        for lineno, line in enumerate(prev.splitlines(), 1):
            if line.startswith("# TYPE "):
                parts = line.split(None, 3)
                if len(parts) == 4:
                    prev_types[parts[2]] = parts[3].strip()
                continue
            if not line.strip() or line.startswith("#"):
                continue
            try:
                name, labels = _parse_sample(line, lineno)
                prev_samples[(name, tuple(sorted(labels.items())))] = \
                    float(_SAMPLE_RE.match(line).group("value"))
            except ValueError:
                prev_errs.append(f"prev scrape line {lineno} unparsable")
        errs.extend(prev_errs)
        for key, old in prev_samples.items():
            name = key[0]
            base = name[:-len("_count")] if name.endswith("_count") \
                else name
            ftype = prev_types.get(name) or prev_types.get(base)
            if ftype != "counter" and not (
                    name.endswith("_count")
                    and prev_types.get(base) in ("summary", "histogram")):
                continue
            new = samples.get(key)
            if new is not None and new < old:
                errs.append(
                    f"counter {name}{dict(key[1])} went backwards "
                    f"across scrapes ({old} -> {new})")
    return errs


# =========================================================================
# Localhost HTTP endpoint (/metrics + /healthz)
# =========================================================================
class TelemetryServer:
    """Stdlib-only localhost scrape endpoint.

    ``metrics_fn`` returns the exposition TEXT, ``health_fn`` the
    health dict — both are called per request, so a scrape always sees
    heartbeat-fresh gauges even between watchdog ticks.  Bound to
    127.0.0.1 only (telemetry is an operator surface, not a public
    one); ``port=0`` picks an ephemeral port (``.port`` holds the real
    one).  Runs on a daemon thread; :meth:`close` shuts it down.
    """

    def __init__(self, metrics_fn: Callable[[], str],
                 health_fn: Callable[[], dict], port: int = 0):
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):           # noqa: N802 (stdlib name)
                try:
                    if self.path.split("?")[0] == "/metrics":
                        body = outer._metrics_fn().encode("utf-8")
                        ctype = ("text/plain; version=0.0.4; "
                                 "charset=utf-8")
                    elif self.path.split("?")[0] == "/healthz":
                        body = (json.dumps(outer._health_fn(),
                                           default=str) + "\n") \
                            .encode("utf-8")
                        ctype = "application/json; charset=utf-8"
                    else:
                        self.send_error(404)
                        return
                except Exception as exc:   # never kill the server
                    body = f"telemetry render failed: {exc}\n" \
                        .encode("utf-8")
                    self.send_response(500)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):   # scrapes are not stderr news
                pass

        self._metrics_fn = metrics_fn
        self._health_fn = health_fn
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_port
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="s2c-telemetry-http")
        self._thread.start()

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass


# =========================================================================
# On-demand profiler capture
# =========================================================================
class ProfilerCapture:
    """Arm-and-capture: SIGUSR2 or a touch-file requests ONE bounded
    profile of whatever the server is doing right now.

    The serve runner polls :meth:`pending` from its watchdog tick and
    calls :meth:`capture` when armed — which means the capture runs
    precisely while a hung job is hanging, the case it exists for.  On
    an accelerator backend it opens a bounded ``jax.profiler.trace()``
    window on a daemon thread (a wedged dispatch cannot block it); on
    cpu — or when the jax profiler refuses — it falls back to a
    pure-Python dump: every live thread's stack plus the current
    tracer spans and a registry snapshot, which is exactly what
    "where is it stuck" needs.  Artifacts land next to the journal
    (``profile_capture_<pid>_<n>/``).
    """

    def __init__(self, out_dir: str,
                 duration_s: Optional[float] = None,
                 touch_dir: Optional[str] = None):
        self.out_dir = out_dir
        try:
            self.duration_s = float(
                duration_s if duration_s is not None
                else os.environ.get("S2C_PROFILE_CAPTURE_S",
                                    DEFAULT_CAPTURE_S))
        except ValueError:
            self.duration_s = DEFAULT_CAPTURE_S
        self.touch_path = os.path.join(touch_dir or out_dir,
                                       CAPTURE_TOUCH_NAME)
        self.captures = 0
        self.last_path: Optional[str] = None
        self._armed = threading.Event()
        self._busy = threading.Lock()

    # -- triggers ---------------------------------------------------------
    def request(self) -> None:
        """Arm a capture (the SIGUSR2 handler and tests call this)."""
        self._armed.set()

    def install_signal(self) -> bool:
        """Install the SIGUSR2 handler (main thread only; best-effort —
        a non-main-thread or exotic-platform install failure leaves the
        touch-file trigger available)."""
        import signal

        if threading.current_thread() is not threading.main_thread():
            return False
        try:
            signal.signal(signal.SIGUSR2, lambda *_: self.request())
            return True
        except (AttributeError, ValueError, OSError):
            return False

    def pending(self) -> bool:
        """True when a capture is armed; consumes the touch file."""
        if os.path.exists(self.touch_path):
            try:
                os.unlink(self.touch_path)
            except OSError:
                pass
            self._armed.set()
        return self._armed.is_set()

    # -- the capture ------------------------------------------------------
    def capture(self, tracer=None, registry=None,
                context: Optional[dict] = None) -> Optional[str]:
        """Take the armed capture; returns the artifact path (None when
        not armed or another capture is still in flight)."""
        if not self._armed.is_set():
            return None
        if not self._busy.acquire(blocking=False):
            return None                 # a window is already open
        try:
            self._armed.clear()
            self.captures += 1
            dest = os.path.join(
                self.out_dir, f"profile_capture_{os.getpid()}_"
                              f"{self.captures}")
            os.makedirs(dest, exist_ok=True)
            mode = self._try_jax_window(dest)
            if mode is None:
                mode = "span_dump"
            self._span_dump(dest, tracer, registry, context, mode)
            self.last_path = dest
            logger.warning("profiler capture #%d (%s) written to %s",
                           self.captures, mode, dest)
            return dest
        except Exception as exc:        # capture must never fail a job
            logger.warning("profiler capture failed: %s: %s",
                           type(exc).__name__, exc)
            return None
        finally:
            self._busy.release()

    def _try_jax_window(self, dest: str) -> Optional[str]:
        """Open a bounded ``jax.profiler`` window on a daemon thread
        when a live non-cpu backend exists; returns the mode string or
        None (-> pure-Python fallback)."""
        import sys

        jax_mod = sys.modules.get("jax")
        if jax_mod is None:
            return None
        try:
            if jax_mod.default_backend() == "cpu":
                return None
        except Exception:
            return None

        def _window():
            try:
                jax_mod.profiler.start_trace(dest)
                time.sleep(self.duration_s)
            finally:
                try:
                    jax_mod.profiler.stop_trace()
                except Exception:
                    pass

        t = threading.Thread(target=_window, daemon=True,
                             name="s2c-profile-window")
        t.start()
        return f"jax_trace({self.duration_s:g}s)"

    def _span_dump(self, dest: str, tracer, registry,
                   context: Optional[dict], mode: str) -> None:
        """The always-available part: thread stacks + tracer spans +
        registry snapshot, one JSON file."""
        import sys
        import traceback

        stacks = {}
        names = {t.ident: t.name for t in threading.enumerate()}
        for tid, frame in sys._current_frames().items():
            stacks[f"{names.get(tid, '?')}({tid})"] = \
                traceback.format_stack(frame)
        blob = {
            "schema": "s2c-profile-capture/1",
            "mode": mode,
            "created_unix": round(time.time(), 3),
            "pid": os.getpid(),
            "context": dict(context or {}),
            "threads": stacks,
            "spans": [
                {"name": s.name, "ts_us": s.ts_us, "dur_us": s.dur_us,
                 "tid": s.tid}
                for s in (tracer.drain() if tracer is not None else [])
            ][-500:],
            "metrics": registry.snapshot()
            if registry is not None else None,
        }
        atomic_write_text(os.path.join(dest, "span_dump.json"),
                          json.dumps(blob, indent=1, default=str) + "\n")


# =========================================================================
# Structured JSON logging + correlation context
# =========================================================================
_log_ctx = threading.local()


def set_log_context(**fields) -> None:
    """Set THIS thread's log-correlation fields (``job_id``,
    ``tenant``, ``rung``, ...); call with no arguments to clear.  The
    serve runner sets it on the main loop, the watchdog worker and the
    decode-ahead thread, so every record a job emits — from any of its
    threads — carries the same correlation IDs."""
    _log_ctx.fields = {k: v for k, v in fields.items()
                       if v not in (None, "")} or None


def get_log_context() -> dict:
    return dict(getattr(_log_ctx, "fields", None) or {})


class JsonLogFormatter(logging.Formatter):
    """One JSON object per record: ts/level/logger/msg plus the
    thread's correlation context and the innermost open trace span
    (``--log-format json``)."""

    def format(self, record: logging.LogRecord) -> str:
        from . import trace as _trace

        obj = {
            "ts": round(record.created, 3),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        obj.update(get_log_context())
        span = _trace.current_span_name()
        if span:
            obj["span"] = span
        if record.exc_info:
            obj["exc"] = self.formatException(record.exc_info)
        return json.dumps(obj, ensure_ascii=False, default=str)
