"""Thread-safe hierarchical span tracer with a zero-cost disabled mode.

One tracer serves the whole pipeline: the backend opens phase spans
(decode → stage → pileup dispatch → accumulate → vote → insertions →
render), the accumulators open per-slab child spans, and gate decisions
(tail placement, pileup strategy) attach as structured instant events.
``export.write_chrome_trace`` renders the result as Chrome/Perfetto
trace-event JSON.

Design constraints, in priority order:

* **disabled is free** — every hot path calls ``tracer.span(...)``
  unconditionally; when tracing is off the call returns one shared
  reusable null context manager without allocating, so a tight loop
  pays two attribute loads and a truthiness test (< 2% on a no-op
  body, pinned by tests/test_observability.py);
* **threads just work** — every span records its thread's ``tid`` and
  closed spans append to one shared (locked) list, so the decode
  prefetch thread and the parallel fused-decode workers interleave
  safely.  There are no explicit parent links: nesting is by timestamp
  containment within a ``tid`` (exactly how Perfetto renders ``ph: X``
  events), which same-thread ``with`` blocks guarantee structurally;
* **device spans measure compute, not dispatch** — JAX dispatches are
  async; a span wrapping only the dispatch would close before the chip
  did the work.  ``span(..., sync=fn)`` runs ``fn`` (a one-element
  fetch or ``block_until_ready``) *inside* the span just before taking
  the closing timestamp, the same completion-forcing idiom the
  autotuner uses (ops/pileup.py ``run_tuned_slab``).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional


class Span:
    """One closed span: wall-clock microseconds, Chrome-trace-shaped."""

    __slots__ = ("name", "ts_us", "dur_us", "tid", "args", "events")

    def __init__(self, name: str, ts_us: float, dur_us: float, tid: int,
                 args: Optional[dict] = None,
                 events: Optional[list] = None):
        self.name = name
        self.ts_us = ts_us
        self.dur_us = dur_us
        self.tid = tid
        self.args = args
        self.events = events      # [(name, ts_us, args), ...] instants


class _NullSpan:
    """Shared no-op context manager returned while tracing is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def event(self, name: str, **args) -> None:
        pass

    def set_args(self, **args) -> None:
        pass


_NULL_SPAN = _NullSpan()

#: per-thread stack of OPEN span names — the log-correlation surface
#: (observability/telemetry.JsonLogFormatter stamps the innermost open
#: span onto every record).  Maintained only by live spans, so the
#: disabled path stays allocation-free.
_span_tls = threading.local()


def current_span_name() -> Optional[str]:
    """The innermost open span on THIS thread (None outside any span
    or while tracing is disabled)."""
    stack = getattr(_span_tls, "stack", None)
    return stack[-1] if stack else None


class _LiveSpan:
    """An open span on one thread's stack."""

    __slots__ = ("_tracer", "_name", "_args", "_sync", "_t0_us",
                 "_events")

    def __init__(self, tracer: "Tracer", name: str, args: Optional[dict],
                 sync: Optional[Callable[[], object]]):
        self._tracer = tracer
        self._name = name
        self._args = args
        self._sync = sync
        self._events: Optional[list] = None
        self._t0_us = 0.0

    def __enter__(self):
        stack = getattr(_span_tls, "stack", None)
        if stack is None:
            stack = _span_tls.stack = []
        stack.append(self._name)
        self._t0_us = (time.perf_counter() - self._tracer._epoch) * 1e6
        return self

    def __exit__(self, exc_type, exc, tb):
        stack = getattr(_span_tls, "stack", None)
        if stack:
            stack.pop()
        if self._sync is not None and exc_type is None:
            # force device completion INSIDE the span so dur measures
            # compute; skipped when unwinding an exception (the device
            # state is undefined then and a sync could hang)
            self._sync()
        t1 = (time.perf_counter() - self._tracer._epoch) * 1e6
        self._tracer._record(Span(self._name, self._t0_us,
                                  t1 - self._t0_us,
                                  threading.get_ident(),
                                  self._args, self._events))
        return False

    def event(self, name: str, **args) -> None:
        """Attach a structured instant event to this span."""
        ts = (time.perf_counter() - self._tracer._epoch) * 1e6
        if self._events is None:
            self._events = []
        self._events.append((name, ts, args or None))

    def set_args(self, **args) -> None:
        """Merge key/values into the span's args (shown in Perfetto)."""
        if self._args is None:
            self._args = {}
        self._args.update(args)


class Tracer:
    """Collects closed spans; disabled by default (see module docstring)."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self._epoch = time.perf_counter()
        #: wall-clock instant of the perf_counter epoch above — the
        #: anchor the fleet flight recorder (observability/flight.py)
        #: uses to re-base this process's span microseconds onto the
        #: journal's wall clock when assembling a cross-process trace.
        #: Captured back-to-back with the perf_counter read; the
        #: microseconds of skew between the two reads is far below the
        #: journal's 1 ms timestamp granularity.
        self.epoch_unix = time.time()
        #: trace-context carried into the exported artifact
        #: (export.write_chrome_trace emits it as the ``s2c`` block):
        #: the serve runner stamps ``trace_id`` / ``key`` / ``worker``
        #: here so per-worker trace JSONs join the journal's per-job
        #: tracks without filename guessing.
        self.meta: Dict[str, object] = {}
        self._lock = threading.Lock()
        self._spans: List[Span] = []
        self._thread_names: Dict[int, str] = {}

    # -- recording --------------------------------------------------------
    def span(self, name: str, sync: Optional[Callable] = None, **args):
        """Context manager timing ``name``; ``sync`` runs on exit inside
        the span (device completion).  Free when disabled."""
        if not self.enabled:
            return _NULL_SPAN
        return _LiveSpan(self, name, args or None, sync)

    def event(self, name: str, **args) -> None:
        """Top-level instant event (not attached to an open span)."""
        if not self.enabled:
            return
        ts = (time.perf_counter() - self._epoch) * 1e6
        self._record(Span(name, ts, -1.0, threading.get_ident(),
                          args or None, None))

    def complete(self, name: str, t0: float, t1: Optional[float] = None,
                 **args) -> None:
        """Record a span retroactively from ``time.perf_counter()``
        readings — for long straight-line sections where a ``with``
        block would force a 200-line reindent.  ``t0``/``t1`` are
        perf_counter seconds; ``t1`` defaults to now."""
        if not self.enabled:
            return
        if t1 is None:
            t1 = time.perf_counter()
        self._record(Span(name, (t0 - self._epoch) * 1e6,
                          (t1 - t0) * 1e6, threading.get_ident(),
                          args or None, None))

    def name_thread(self, name: str) -> None:
        """Label the calling thread in the exported trace metadata."""
        if not self.enabled:
            return
        with self._lock:
            self._thread_names[threading.get_ident()] = name

    def _record(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    # -- reading ----------------------------------------------------------
    def drain(self) -> List[Span]:
        """All closed spans so far (snapshot; tracer keeps collecting)."""
        with self._lock:
            return list(self._spans)

    def thread_names(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._thread_names)
