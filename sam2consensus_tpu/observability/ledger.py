"""Decision ledger: model-vs-measured attribution for every auto gate.

Every hot-path placement choice in this framework is priced from a cost
model — tail placement (backends/jax_backend ``_tail_cpu_wins``), the
``--wire auto`` codec resolution (wire/codec ``resolve_codec``),
``--shard-mode auto`` (parallel/auto ``choose_shard_mode``), and all of
them ultimately from the linkprobe constants.  Round 5 showed what
happens when nothing ever checks those predictions against the run that
actually happened: the baked link defaults drifted (65 ms/40 MB/s
modeled vs 72 ms/10-15 MB/s measured) and kept routing decisions for
months.  The ledger closes that loop:

* each decision site registers a structured :class:`DecisionRecord`
  — ``{decision, chosen, inputs, predicted, alternatives}`` plus a
  *measured spec* naming the registry counters that will contain the
  decision's real outcome once the run finishes;
* at run end (:func:`finalize`, called by
  ``observability.finalize_decisions``) each record is joined against
  the metrics registry: ``residual/<decision>/<key>`` gauges carry the
  measured/predicted ratio, and a ``drift/<decision>`` event fires when
  the residual leaves the configurable band (S2C_DRIFT_BAND, default
  4x either way) — turning "the model said 0.1 s, the run took 3 s"
  from an archaeology exercise into an alarm in the artifact.

Records are per-run (pushed/popped with the run's registry) and
last-wins per decision name, so a gate consulted twice (the tail
model's optimistic-then-exact double call) leaves exactly one decisive
record.  Everything here is plain dict/float work on a handful of
records per run — never per slab — so there is no hot-path cost.

Measured specs are one of two shapes, evaluated over the registry's
counter snapshot at finalize time:

* ``{"counters": [names]}`` — the sum of the named counters (absent
  counters contribute nothing; all absent -> no join);
* ``{"num": [names], "den": [names]}`` — a rate/ratio: sum(num) /
  sum(den).  No join when the denominator is 0 OR the numerator sums
  to 0 (either way there was no traffic, so there is nothing to
  attribute — a zero rate is the absence of a measurement, not a
  measurement of zero).  An optional ``"min_num"`` raises that floor:
  a bps join with ``min_num: 8e6`` only attributes runs that shipped
  at least 8 MB, below which the window is compute/encode-dominated
  and the achieved rate says nothing about the link constants.
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

logger = logging.getLogger("sam2consensus_tpu.observability.ledger")

#: default drift band: residual (measured/predicted) outside
#: [1/band, band] raises a drift event.  4x is deliberately generous —
#: the probe's honest conservatism alone accounts for ~2-3x on the
#: tunneled rig (bench link_util_pct can exceed 100%) — so a fired
#: drift event means the constants are genuinely wrong, not noisy.
DEFAULT_DRIFT_BAND = 4.0

#: seconds floor under which a "sec" residual never drifts: a model
#: that predicted 80 us and measured 900 us is pricing dispatch noise,
#: not a mis-route worth alarming on
DEFAULT_DRIFT_MIN_SEC = 0.02


def drift_band() -> float:
    """S2C_DRIFT_BAND (ratio, >= 1) or the default."""
    try:
        return max(1.0, float(os.environ.get("S2C_DRIFT_BAND",
                                             DEFAULT_DRIFT_BAND)))
    except ValueError:
        return DEFAULT_DRIFT_BAND


def drift_min_sec() -> float:
    try:
        return float(os.environ.get("S2C_DRIFT_MIN_SEC",
                                    DEFAULT_DRIFT_MIN_SEC))
    except ValueError:
        return DEFAULT_DRIFT_MIN_SEC


@dataclass
class DecisionRecord:
    """One model-driven decision + (after finalize) its real outcome."""

    decision: str                      # "tail_placement", "wire_codec", ...
    chosen: str
    inputs: dict = field(default_factory=dict)
    predicted: dict = field(default_factory=dict)   # {"sec"|"bps"|"ratio": v}
    alternatives: dict = field(default_factory=dict)  # {candidate: cost}
    measured_spec: Optional[dict] = None
    #: None -> the global S2C_DRIFT_BAND; 0/False -> residual is
    #: informational only, never raises drift (e.g. shard mode, whose
    #: model prices only the per-slab OVERHEAD delta between layouts,
    #: not the absolute slab time the registry measures)
    band: Optional[float] = None
    # -- filled by finalize() --
    measured: dict = field(default_factory=dict)
    residual: dict = field(default_factory=dict)
    drift: bool = False

    def to_dict(self) -> dict:
        out = {"decision": self.decision, "chosen": self.chosen,
               "inputs": dict(self.inputs),
               "predicted": dict(self.predicted),
               "alternatives": dict(self.alternatives),
               "measured": dict(self.measured),
               "residual": dict(self.residual),
               "drift": bool(self.drift)}
        return out


class DecisionLedger:
    """Per-run decision records, last-wins by decision name."""

    def __init__(self):
        self._lock = threading.Lock()
        self._records: Dict[str, DecisionRecord] = {}
        self.finalized = False

    def record(self, decision: str, chosen: str,
               inputs: Optional[dict] = None,
               predicted: Optional[dict] = None,
               alternatives: Optional[dict] = None,
               measured: Optional[dict] = None,
               band: Optional[float] = None,
               provenance: Optional[dict] = None) -> DecisionRecord:
        """``provenance`` is the rate-card consultation stamp
        (observability/ratecard.py ``consult``): which source priced
        this decision's prediction inputs — learned (with sample count
        and age) or default.  It rides ``inputs["ratecard"]`` so the
        manifest's residual record answers "was the drift the MODEL's
        fault or the CONSTANT's fault" per decision."""
        merged = dict(inputs or {})
        if provenance:
            merged["ratecard"] = dict(provenance)
        rec = DecisionRecord(
            decision=decision, chosen=str(chosen),
            inputs=merged,
            predicted={k: float(v) for k, v in (predicted or {}).items()
                       if v is not None},
            alternatives={k: float(v)
                          for k, v in (alternatives or {}).items()
                          if v is not None},
            measured_spec=measured, band=band)
        with self._lock:
            self._records[decision] = rec
        return rec

    def get(self, decision: str) -> Optional[DecisionRecord]:
        with self._lock:
            return self._records.get(decision)

    def records(self) -> List[DecisionRecord]:
        with self._lock:
            return list(self._records.values())


# -- process-current ledger (mirrors metrics.current) ----------------------
_process_ledger = DecisionLedger()
_current: List[DecisionLedger] = [_process_ledger]
_current_lock = threading.Lock()
_tls = threading.local()


def current() -> DecisionLedger:
    led = getattr(_tls, "ledger", None)
    return led if led is not None else _current[-1]


def bind_thread(ledger: Optional[DecisionLedger]) -> None:
    """Thread-local override of :func:`current` (mirrors
    ``metrics.bind_thread``; serve-mode decode-ahead threads)."""
    _tls.ledger = ledger


def push_run(ledger: Optional[DecisionLedger] = None) -> DecisionLedger:
    led = ledger if ledger is not None else DecisionLedger()
    with _current_lock:
        _current.append(led)
    return led


def pop_run(ledger: DecisionLedger) -> None:
    with _current_lock:
        if len(_current) > 1 and _current[-1] is ledger:
            _current.pop()
        elif ledger in _current[1:]:
            _current.remove(ledger)


def record(decision: str, chosen: str, **kwargs) -> DecisionRecord:
    """Register a decision into the current run's ledger (module-level
    convenience for deep call sites, like ``observability.metrics()``)."""
    return current().record(decision, chosen, **kwargs)


# -- the join --------------------------------------------------------------
def _eval_measured(spec, counters: dict) -> Optional[float]:
    """Evaluate one measured-spec entry over a counter snapshot."""
    if not isinstance(spec, dict):
        return None
    if "counters" in spec:
        names = [n for n in spec["counters"] if n in counters]
        if not names:
            return None
        return float(sum(counters[n] for n in names))
    if "num" in spec and "den" in spec:
        num = sum(counters.get(n, 0.0) for n in spec["num"])
        den = sum(counters.get(n, 0.0) for n in spec["den"])
        if den <= 0 or num <= 0 or num < spec.get("min_num", 0):
            return None
        return float(num) / float(den)
    return None


def finalize(ledger: DecisionLedger, registry, tracer=None
             ) -> List[DecisionRecord]:
    """Join every record against the registry's measured counters.

    Emits ``residual/<decision>/<key>`` gauges (measured/predicted
    ratio), a per-decision ``residual/<decision>`` info gauge carrying
    the full joined record, and — when a residual leaves the drift
    band — a ``drift/events`` counter bump, a ``drift/<decision>``
    gauge, a tracer instant event and a warning log.  Idempotent per
    ledger (the backend finalizes before publishing stats; finish_run
    re-checks for runs that never reached the backend's call)."""
    if ledger.finalized:
        return ledger.records()
    ledger.finalized = True
    snap = registry.snapshot()
    counters = snap["counters"]
    band_default = drift_band()
    min_sec = drift_min_sec()
    for rec in ledger.records():
        for key, spec in (rec.measured_spec or {}).items():
            m = _eval_measured(spec, counters)
            if m is None:
                continue
            rec.measured[key] = m
            p = rec.predicted.get(key)
            if p is None or p <= 0:
                continue
            rec.residual[key] = m / p
            registry.gauge(
                f"residual/{rec.decision}/{key}").set(round(m / p, 4))
        band = band_default if rec.band is None else rec.band
        if band:
            for key, ratio in rec.residual.items():
                if key == "sec" and max(
                        rec.measured.get("sec", 0.0),
                        rec.predicted.get("sec", 0.0)) < min_sec:
                    continue
                if ratio > band or ratio < 1.0 / band:
                    rec.drift = True
        info = rec.to_dict()
        info["band"] = band
        registry.gauge(f"residual/{rec.decision}").set_info(info)
        if rec.drift:
            registry.add("drift/events", 1)
            registry.gauge(f"drift/{rec.decision}").set_info(info)
            logger.warning(
                "drift: %s chose %r predicting %s but measured %s "
                "(residual %s outside band %.1fx) — the model's "
                "constants no longer describe this rig",
                rec.decision, rec.chosen, rec.predicted, rec.measured,
                {k: round(v, 3) for k, v in rec.residual.items()}, band)
            if tracer is not None:
                tracer.event(f"drift/{rec.decision}", **{
                    "chosen": rec.chosen,
                    **{f"predicted_{k}": v
                       for k, v in rec.predicted.items()},
                    **{f"measured_{k}": round(v, 6)
                       for k, v in rec.measured.items()}})
    return ledger.records()
