"""Noise-aware perf-regression detection over bench/campaign series.

The committed ``BENCH_r0*.json`` trajectory is the repo's performance
memory, but its numbers ride a one-core shared host and a tunnel whose
state swings real measurements 1.5-2x run to run (bench.py's own
best-of-N notes; the round-5 insertion sweep saw 0.77-2.23x on
identical configs).  A naive "slower than last round" gate would cry
wolf every round; no gate at all is how a 40 MB/s constant survived a
10-15 MB/s link for two rounds.  This module is the middle path:

* **median/MAD bands** — the history's center is the median, its noise
  scale the MAD (scaled by 1.4826 to estimate sigma under normality);
  both are robust to the single wild round that IS the trajectory's
  reality.  The allowed deviation is
  ``max(k * 1.4826 * MAD, rel_floor * |median|)`` — the relative floor
  keeps a 3-point history whose MAD happens to be ~0 from flagging
  ordinary rig noise;
* **min-repeat awareness** — fewer than ``min_repeats`` prior points is
  not a distribution, it is an anecdote: the verdict is
  ``insufficient_history`` (gate passes, loudly) instead of a
  confident band from two numbers;
* **direction awareness** — ``vs_baseline``/``bases_per_sec`` regress
  downward, ``*_sec`` regress upward; improvements are reported but
  never fail the gate.

Artifact tolerance: the committed BENCH files are driver wrappers whose
``tail`` capture is HEAD-TRUNCATED (last N bytes of stdout), so the
top-level JSON line is often unrecoverable while every per-config row
object inside it is intact.  Since round 6, ``bench.py`` writes the
COMPLETE result object to a sibling ``BENCH_<tag>.full.json``
(``BENCH_FULL_OUT``/``BENCH_TAG``) and :func:`load_bench_artifact`
prefers that sibling — no recovery needed.  For the pre-r06 files the
old path remains: :func:`extract_bench_rows` scans for balanced
``{"config": ...}`` objects with ``raw_decode`` instead of trusting
the line structure; a round with no recoverable rows (r01's rc=1
crash) simply contributes no history.

Consumers: ``tools/regress_check.py`` (the CI gate,
tests/test_regression_gate.py) and ``tools/bench_report.py --diff``
(two-artifact delta table sharing :func:`noise_floor`).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

DEFAULT_K = 4.0
#: relative noise floor: deviations under this fraction of the median
#: never flag, regardless of how quiet the history was.  0.35 covers
#: the measured rig noise (bench.py: sub-second ratios swing ~1.5x;
#: best-of-N keeps committed rows tighter, but not 10%-tight).
DEFAULT_REL_FLOOR = 0.35
DEFAULT_MIN_REPEATS = 3

#: metric direction: True -> lower is better (seconds), False ->
#: higher is better (throughput / speedup).  Unknown metrics default
#: to higher-is-better (the repo's headline metrics all are).
LOWER_IS_BETTER = {
    "jax_sec": True, "cpu_sec": True, "sec": True, "elapsed_sec": True,
    "vs_baseline": False, "bases_per_sec": False, "value": False,
    "pileup_mcells_per_s": False, "decode_mbases_per_s": False,
    # residency regresses UPWARD (tools/mem_watermark.py + the bench
    # rows' peak_rss_mb — the memory plane's gated metrics)
    "peak_rss_mb": True, "peak_tracked_mb": True,
}


def median(xs: Sequence[float]) -> float:
    s = sorted(xs)
    n = len(s)
    if n == 0:
        raise ValueError("median of empty series")
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def mad(xs: Sequence[float], center: Optional[float] = None) -> float:
    """Median absolute deviation (unscaled)."""
    c = median(xs) if center is None else center
    return median([abs(x - c) for x in xs])


def noise_floor(center: float, mad_value: float,
                k: float = DEFAULT_K,
                rel_floor: float = DEFAULT_REL_FLOOR) -> float:
    """The allowed absolute deviation from ``center``."""
    return max(k * 1.4826 * mad_value, rel_floor * abs(center))


def check_series(history: Sequence[float], candidate: float, *,
                 lower_is_better: bool = False,
                 k: float = DEFAULT_K,
                 rel_floor: float = DEFAULT_REL_FLOOR,
                 min_repeats: int = DEFAULT_MIN_REPEATS) -> dict:
    """Verdict for one candidate value against its history.

    Returns ``{"status": "pass"|"regressed"|"improved"|
    "insufficient_history", "median", "mad", "allowed", "delta",
    "n_history"}``.  ``delta`` is candidate - median (sign as stored,
    not direction-normalized).
    """
    n = len(history)
    out = {"n_history": n, "candidate": candidate}
    if n < min_repeats:
        out.update(status="insufficient_history", median=None, mad=None,
                   allowed=None, delta=None)
        return out
    c = median(history)
    m = mad(history, c)
    allowed = noise_floor(c, m, k=k, rel_floor=rel_floor)
    delta = candidate - c
    worse = delta > allowed if lower_is_better else delta < -allowed
    better = delta < -allowed if lower_is_better else delta > allowed
    out.update(status="regressed" if worse
               else "improved" if better else "pass",
               median=c, mad=m, allowed=allowed, delta=delta)
    return out


# -- artifact loading ------------------------------------------------------
def extract_bench_rows(text: str) -> List[dict]:
    """Every balanced ``{"config": ...}`` object recoverable from a
    (possibly truncated) bench capture, in order."""
    dec = json.JSONDecoder()
    rows: List[dict] = []
    i = 0
    while True:
        j = text.find('{"config":', i)
        if j < 0:
            break
        try:
            obj, end = dec.raw_decode(text[j:])
            rows.append(obj)
            i = j + end
        except ValueError:
            i = j + 1
    return rows


def full_sibling_path(path: str) -> str:
    """``BENCH_r06.json`` -> ``BENCH_r06.full.json`` (the complete
    result object bench.py writes since round 6); already-full paths
    map to themselves."""
    if path.endswith(".full.json"):
        return path
    if path.endswith(".json"):
        return path[:-len(".json")] + ".full.json"
    return path + ".full.json"


def load_bench_artifact(path: str) -> List[dict]:
    """Per-config rows from one bench artifact.  A sibling
    ``<name>.full.json`` (complete, untruncated) is authoritative when
    present; otherwise the artifact itself is read as a driver wrapper
    (``{"rc", "tail", "parsed"}``), a bare bench JSON line, or — for
    the pre-r06 truncated captures — any text containing config rows.
    A crashed/empty round returns []."""
    import os

    sibling = full_sibling_path(path)
    if sibling != path and os.path.exists(sibling):
        try:
            with open(sibling) as fh:
                obj = json.load(fh)
            if isinstance(obj, dict) and isinstance(
                    obj.get("configs"), list):
                return [r for r in obj["configs"]
                        if isinstance(r, dict)]
        except (OSError, json.JSONDecodeError):
            pass                      # fall back to the capture itself
    with open(path) as fh:
        text = fh.read()
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        obj = None
    if isinstance(obj, dict):
        if isinstance(obj.get("configs"), list):
            return [r for r in obj["configs"] if isinstance(r, dict)]
        parsed = obj.get("parsed")
        if isinstance(parsed, dict) and isinstance(
                parsed.get("configs"), list):
            return [r for r in parsed["configs"] if isinstance(r, dict)]
        text = obj.get("tail", "") or ""
    return extract_bench_rows(text)


def bench_series(paths: Sequence[str],
                 metrics: Sequence[str] = ("vs_baseline", "jax_sec"),
                 ) -> Dict[Tuple[str, str], List[Tuple[str, float]]]:
    """``{(config, metric): [(path, value), ...]}`` across a trajectory
    (paths in trajectory order).  Rows with errors contribute nothing."""
    series: Dict[Tuple[str, str], List[Tuple[str, float]]] = {}
    for path in paths:
        for row in load_bench_artifact(path):
            if "error" in row or "config" not in row:
                continue
            for metric in metrics:
                v = row.get(metric)
                if isinstance(v, (int, float)):
                    series.setdefault((row["config"], metric),
                                      []).append((path, float(v)))
    return series


def series_from_jsonl(path: str, group_by: str, value_field: str,
                      ) -> Dict[str, List[float]]:
    """``{group: [values...]}`` from a campaign JSONL (one JSON object
    per line; malformed lines skipped — campaign logs interleave)."""
    series: Dict[str, List[float]] = {}
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(row, dict):
                continue
            v = row.get(value_field)
            if not isinstance(v, (int, float)):
                continue
            key = str(row.get(group_by, "?"))
            series.setdefault(key, []).append(float(v))
    return series
