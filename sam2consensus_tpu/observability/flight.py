"""Fleet flight recorder: the journal as the spine of a distributed trace.

Every observability plane before this one is scoped to ONE process —
per-job spans/metrics, the decision ledger, worker-labeled exposition,
the memory plane all see a job only while *their* worker holds it.
Since the fleet layer (serve/fleet.py) a job's real life is
distributed: submitted by one process, queued in the shared journal,
claimed (or stolen after a SIGKILL) by another, committed under a
fenced lease.  The journal's event stream already carries everything a
distributed trace needs — totally-ordered segments, wall-clock ``t``
per event, worker ids on every lease event — so this module turns a
replayed journal into:

* **per-job lifecycle tracks** (:func:`assemble`): one
  :class:`JobLifecycle` per journal key, with the raw event list and
  derived segments — queue wait, claim latency, run attempts, steal
  gaps (victim's last lease sign of life -> reap -> re-claim) — that
  tile the job's submit->terminal wall clock with no holes and no
  negative durations;
* **scheduler telemetry** (:func:`sched_metrics`): per-tenant
  ``queue_wait_sec`` / ``claim_latency_sec`` / ``steal_latency_sec``
  distributions, ``lease_churn``, and per-worker busy/occupancy
  fractions, all derived from journal timestamps — the measured
  substrate the elastic-fleet planner (ROADMAP item 3) prices
  placement against.  The serve runner derives the same numbers live
  (``sched/*`` registry families, the ``s2c_sched_*`` exposition);
  this module is the offline replay that audits them;
* **a Chrome/Perfetto trace** (:func:`chrome_events` via
  tools/fleet_trace.py): per-job tracks, lease renewals as instants,
  flow arrows tying each run segment to a per-worker occupancy lane,
  and (when per-worker ``--trace-out`` artifacts are supplied) each
  worker's in-process phase spans re-anchored from its
  ``perf_counter`` epoch onto the journal's wall clock and joined by
  ``trace_id`` — no guessing;
* **critical-path attribution** (:func:`critical_path`): per job the
  end-to-end decomposition (queue -> claim -> decode -> dispatch ->
  tail -> commit, including cross-process waits), aggregated into the
  "where does the wall go" report of ``fleet_trace --report``.

Trace-context propagation: a job's ``trace_id`` is its journal key
(:func:`trace_id` centralizes the derivation) — stable across
processes, restarts and steals because the key hashes the input path
plus the output-relevant config (serve/journal.job_key).  The runner
stamps it into each job's trace JSON (the ``s2c`` metadata block
export.write_chrome_trace emits), metrics JSONL (the ``sched/trace``
gauge info) and manifest (the ``lifecycle`` section), so cross-process
artifacts join on an identifier, not on filename heuristics.

Clock assumptions are the journal's own: events carry
``round(time.time(), 3)`` stamped at append time, and commit fencing
relies on ``rec.t >= expires_unix`` arbitration
(serve/journal.JobJournal._apply) — tests/test_flight.py pins both the
per-key timestamp monotonicity this module leans on and that
arbitration rule.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

#: lifecycle events that sign a worker's liveness for a key — the
#: newest of these from the lease holder is the last proof of life a
#: steal gap is measured from
_LEASE_EVENTS = ("claimed", "lease_renewed", "started")

#: terminal events per key
_TERMINAL = ("committed", "failed")


def trace_id(key: str) -> str:
    """The ONE trace-context derivation: a job's trace id IS its
    journal key (serve/journal.job_key — sha256 over input path +
    output-relevant config, 16 hex chars).  Centralized so every
    stamping site (runner, manifest, exposition, assembler) derives it
    the same way; a future format change happens here only."""
    return str(key)


@dataclass
class Segment:
    """One horizontal slice of a per-job track: ``[t0, t1)`` wall
    seconds with a kind from the lifecycle vocabulary (``queue_wait``,
    ``claim_latency``, ``run``, ``steal_gap``, ``commit_wait``)."""

    kind: str
    t0: float
    t1: float
    worker: str = ""
    args: dict = field(default_factory=dict)

    @property
    def dur(self) -> float:
        return self.t1 - self.t0


@dataclass
class JobLifecycle:
    """Everything the journal knows about one key's distributed life."""

    key: str
    job_id: str = ""
    tenant: str = ""
    filename: str = ""
    #: raw journal events for this key, in segment order
    events: List[dict] = field(default_factory=list)
    #: derived, gap-free track segments (submit -> terminal)
    segments: List[Segment] = field(default_factory=list)
    #: instant markers (lease renewals, reaps, resumes): (name, t, args)
    instants: List[Tuple[str, float, dict]] = field(default_factory=list)
    submitted_t: Optional[float] = None
    started_t: Optional[float] = None       # first started
    terminal_t: Optional[float] = None
    terminal_ev: str = ""                   # committed | failed | ""
    committed_worker: str = ""
    #: journal-measured scheduler numbers (None where not applicable)
    queue_wait_sec: Optional[float] = None
    claim_latency_sec: Optional[float] = None
    steal_latency_sec: Optional[float] = None
    lease_churn: int = 0
    renewals: int = 0
    steals: int = 0

    @property
    def tid(self) -> str:
        return trace_id(self.key)


def _t(rec: dict) -> float:
    try:
        return float(rec.get("t", 0.0))
    except (TypeError, ValueError):
        return 0.0


def assemble(events: List[dict]) -> Dict[str, JobLifecycle]:
    """Replay journal events into per-key lifecycle models.

    Mirrors the journal's own claim/lease state machine
    (serve/journal.JobJournal._apply) where it matters: the FIRST
    ``claimed`` while no lease is open wins; ``lease_expired`` is
    effective only under the ``rec.t >= expires_unix`` arbitration
    rule; a ``committed`` from other than the open lease's holder is a
    voided zombie append (recorded as an instant, never a terminal).
    Corrupt segments (``ev == "_corrupt"``) are skipped — the reader
    already warned.
    """
    jobs: Dict[str, JobLifecycle] = {}
    #: key -> the open lease {worker, claim_seq, expires_unix, t}
    claims: Dict[str, dict] = {}
    claimed_ever: set = set()
    for rec in events:
        ev = rec.get("ev")
        key = rec.get("key")
        if ev == "_corrupt" or not key:
            continue
        jl = jobs.get(key)
        if jl is None:
            jl = jobs[key] = JobLifecycle(key=key)
        if rec.get("job") and not jl.job_id:
            jl.job_id = str(rec["job"])
        if rec.get("tenant") and not jl.tenant:
            jl.tenant = str(rec["tenant"])
        jl.events.append(rec)
        t = _t(rec)
        worker = str(rec.get("worker", "") or "")
        if ev == "submitted":
            if jl.submitted_t is None:
                jl.submitted_t = t
            if rec.get("filename"):
                jl.filename = str(rec["filename"])
        elif ev == "started":
            if jl.started_t is None:
                jl.started_t = t
            cur = claims.get(key)
            if cur is not None and cur["worker"] == worker:
                cur["t"] = t
        elif ev == "claimed":
            claimed_ever.add(key)
            if key not in claims:
                claims[key] = {
                    "worker": worker,
                    "claim_seq": int(rec.get("seq", 0)),
                    "expires_unix": float(rec.get("expires_unix", 0.0)),
                    "t": t}
                jl.instants.append(("claim_won", t, {
                    "worker": worker, "seq": rec.get("seq")}))
            else:
                jl.lease_churn += 1
                jl.instants.append(("claim_lost", t, {
                    "worker": worker,
                    "holder": claims[key]["worker"]}))
        elif ev == "lease_renewed":
            cur = claims.get(key)
            if cur is not None and cur["worker"] == worker:
                cur["expires_unix"] = float(
                    rec.get("expires_unix", 0.0))
                cur["t"] = t
                jl.renewals += 1
                jl.instants.append(("lease_renewed", t,
                                    {"worker": worker}))
        elif ev == "lease_expired":
            cur = claims.get(key)
            # the arbitration clock assumption commit fencing relies
            # on: a reap is effective only when its append timestamp
            # sits at/after the lease's expiry — a renewal that
            # published first voids it (tests pin this)
            if cur is not None and cur["worker"] == worker \
                    and t >= cur["expires_unix"]:
                jl.lease_churn += 1
                jl.instants.append(("lease_reaped", t, {
                    "victim": worker,
                    "reaper": rec.get("reaper", ""),
                    "victim_last_t": cur.get("t"),
                    "expired_unix": cur.get("expires_unix")}))
                # the journal's own transition: the lease closes, the
                # key is re-claimable — the NEXT winning claim is the
                # steal (segment derivation measures its gap from the
                # victim's last sign of life)
                del claims[key]
            else:
                jl.instants.append(("lease_reap_void", t, {
                    "victim": worker,
                    "reaper": rec.get("reaper", "")}))
        elif ev in _TERMINAL:
            cur = claims.get(key)
            if ev == "committed" and key in claimed_ever:
                cs = rec.get("claim_seq")
                if cur is None or cur["worker"] != worker \
                        or (cs is not None
                            and cs != cur.get("claim_seq")):
                    # zombie append voided by the lease fence
                    jl.instants.append(("stale_commit", t,
                                        {"worker": worker}))
                    continue
            if jl.terminal_t is None:
                jl.terminal_t = t
                jl.terminal_ev = ev
                if ev == "committed":
                    jl.committed_worker = worker
            claims.pop(key, None)
        elif ev == "resumed":
            jl.instants.append(("resumed", t,
                                {"mode": rec.get("mode", "")}))
        elif ev == "rejected":
            jl.instants.append(("rejected", t,
                                {"reason": rec.get("reason", "")}))
    for jl in jobs.values():
        _derive_segments(jl)
    return jobs


def _derive_segments(jl: JobLifecycle) -> None:
    """Tile a job's submit->terminal wall clock into contiguous,
    non-negative segments.  The derivation walks the per-key event
    list (segment order == time order per key — pinned by tests) and
    closes the open segment at every transition, so the track is
    gap-free by construction even across a SIGKILL: the victim's
    silence is covered by the ``steal_gap`` segment from its last
    lease sign of life to the thief's re-claim."""
    segs: List[Segment] = []
    open_kind: Optional[str] = None
    open_t: Optional[float] = None
    open_worker = ""
    open_args: dict = {}

    def close(t: float) -> None:
        nonlocal open_kind, open_t
        if open_kind is None or open_t is None:
            return
        if t > open_t:
            segs.append(Segment(open_kind, open_t, t, open_worker,
                                dict(open_args)))
        open_kind = open_t = None

    claim_worker = ""
    claim_t: Optional[float] = None
    last_lease_t: Optional[float] = None
    n_claims = 0
    for rec in jl.events:
        ev = rec.get("ev")
        t = _t(rec)
        worker = str(rec.get("worker", "") or "")
        if ev == "submitted" and open_kind is None:
            open_kind, open_t = "queue_wait", t
            open_worker, open_args = "", {}
        elif ev == "claimed":
            won = any(name == "claim_won" and abs(it - t) < 5e-4
                      and args.get("seq") == rec.get("seq")
                      for name, it, args in jl.instants)
            if not won:
                continue
            n_claims += 1
            stolen = last_lease_t is not None
            close(t)
            if stolen:
                # re-label the just-closed wait as the steal gap the
                # fleet_soak bound measures (victim last sign of life
                # -> re-claim); keep its start where the victim went
                # silent when that is known
                if segs and segs[-1].kind == "queue_wait":
                    segs[-1].kind = "steal_gap"
                    segs[-1].args["victim_last_t"] = last_lease_t
                if jl.steal_latency_sec is None:
                    jl.steal_latency_sec = max(0.0, t - last_lease_t)
                jl.steals += 1
            else:
                if jl.claim_latency_sec is None \
                        and jl.submitted_t is not None:
                    jl.claim_latency_sec = max(0.0, t - jl.submitted_t)
            claim_worker = worker
            last_lease_t = t
            open_kind, open_t = "claim_latency", t
            open_worker, open_args = worker, {"claim_seq":
                                              rec.get("seq")}
        elif ev == "started":
            close(t)
            # serial (claim-free) journals go straight submitted ->
            # started: the closed segment was the whole queue wait
            open_kind, open_t = "run", t
            open_worker = worker or claim_worker
            open_args = {"attempt": n_claims or 1}
            if worker or claim_worker:
                last_lease_t = t
        elif ev == "lease_renewed" and worker == claim_worker:
            last_lease_t = t
        elif ev == "lease_expired":
            reaped = any(name == "lease_reaped" and abs(it - t) < 5e-4
                         for name, it, args in jl.instants)
            if not reaped:
                continue
            close(t)
            # between the reap and the re-claim the job is ownerless:
            # the steal gap's visible tail (its head — victim silence
            # before the reap — is re-labeled at re-claim time above)
            open_kind, open_t = "queue_wait", t
            open_worker, open_args = "", {"after_reap": True}
        elif ev in _TERMINAL:
            if ev == "committed" and any(
                    name == "stale_commit" and abs(it - t) < 5e-4
                    and args.get("worker") == worker
                    for name, it, args in jl.instants):
                continue         # voided zombie append (lease fence)
            close(t)
    if jl.submitted_t is not None and jl.started_t is not None:
        jl.queue_wait_sec = max(0.0, jl.started_t - jl.submitted_t)
    jl.segments = segs


def sched_metrics(jobs: Dict[str, JobLifecycle]) -> dict:
    """Fleet-aggregate scheduler telemetry from assembled lifecycles.

    Returns ``{"per_tenant": {tenant: {queue_wait_sec: [..],
    claim_latency_sec: [..], steal_latency_sec: [..]}},
    "lease_churn": int, "workers": {worker: {busy_sec, jobs,
    occupancy}}, "wall_sec": float}`` — the same vocabulary the
    runner's live ``sched/*`` families use, derived offline."""
    per_tenant: Dict[str, Dict[str, list]] = {}
    workers: Dict[str, dict] = {}
    churn = 0
    t_min = t_max = None
    for jl in jobs.values():
        tl = jl.tenant or "default"
        bucket = per_tenant.setdefault(tl, {
            "queue_wait_sec": [], "claim_latency_sec": [],
            "steal_latency_sec": []})
        if jl.queue_wait_sec is not None:
            bucket["queue_wait_sec"].append(jl.queue_wait_sec)
        if jl.claim_latency_sec is not None:
            bucket["claim_latency_sec"].append(jl.claim_latency_sec)
        if jl.steal_latency_sec is not None:
            bucket["steal_latency_sec"].append(jl.steal_latency_sec)
        churn += jl.lease_churn
        for seg in jl.segments:
            if t_min is None or seg.t0 < t_min:
                t_min = seg.t0
            if t_max is None or seg.t1 > t_max:
                t_max = seg.t1
            if seg.kind == "run" and seg.worker:
                w = workers.setdefault(seg.worker,
                                       {"busy_sec": 0.0, "jobs": 0})
                w["busy_sec"] += seg.dur
                w["jobs"] += 1
    wall = (t_max - t_min) if (t_min is not None
                               and t_max is not None) else 0.0
    for w in workers.values():
        w["busy_sec"] = round(w["busy_sec"], 6)
        w["occupancy"] = round(w["busy_sec"] / wall, 4) \
            if wall > 0 else 0.0
    return {"per_tenant": per_tenant, "lease_churn": churn,
            "workers": workers, "wall_sec": round(wall, 6)}


def session_wave_tracks(events: List[dict]) -> Dict[str, dict]:
    """Streaming-session wave tracks from raw journal events
    (serve/session.py's vocabulary: ``session_open`` /
    ``wave_received`` / ``wave_absorbed`` / ``wave_rejected`` /
    ``session_stable`` / ``session_closed``).

    Per session: one track entry per wave with its received->absorbed
    latency (the durable-intent-to-counted gap — a wave replayed after
    a steal shows the steal's takeover window here), the absorbing
    worker, any DATA-class rejection, plus session-level marks
    (opened/stable/closed) and the claim handoffs (``claimed`` events
    on the session key from successive workers — each handoff past the
    first is a steal or restart takeover).  Offline twin of the live
    ``s2c_session_*`` exposition family, same journal truth source as
    :func:`assemble`."""
    sessions: Dict[str, dict] = {}

    def _view(sid: str) -> dict:
        s = sessions.get(sid)
        if s is None:
            s = sessions[sid] = {
                "tenant": "", "opened_t": None, "closed_t": None,
                "stable_t": None, "stable_wave": None,
                "waves": {}, "handoffs": []}
        return s

    def _wave(s: dict, rec: dict) -> dict:
        n = int(rec.get("wave", 0))
        w = s["waves"].get(n)
        if w is None:
            w = s["waves"][n] = {
                "received_t": None, "absorbed_t": None,
                "absorb_latency_sec": None, "worker": "",
                "rejected": None, "sha": str(rec.get("sha", ""))}
        return w

    for rec in events:
        ev = rec.get("ev")
        sid = rec.get("key")
        if ev == "_corrupt" or not sid:
            continue
        t = _t(rec)
        if ev == "session_open":
            s = _view(sid)
            s["opened_t"] = t
            s["tenant"] = str(rec.get("tenant", "") or "")
        elif ev == "wave_received":
            w = _wave(_view(sid), rec)
            if w["received_t"] is None:     # first intent wins
                w["received_t"] = t
        elif ev == "wave_absorbed":
            w = _wave(_view(sid), rec)
            if w["absorbed_t"] is None:     # exactly-once: first wins
                w["absorbed_t"] = t
                w["worker"] = str(rec.get("worker", "") or "")
                if w["received_t"] is not None:
                    w["absorb_latency_sec"] = round(
                        t - w["received_t"], 6)
        elif ev == "wave_rejected":
            w = _wave(_view(sid), rec)
            w["rejected"] = str(rec.get("reason", "") or "rejected")
        elif ev == "session_stable":
            s = _view(sid)
            if s["stable_t"] is None:
                s["stable_t"] = t
                s["stable_wave"] = rec.get("wave")
        elif ev == "session_closed":
            _view(sid)["closed_t"] = t
        elif ev == "claimed" and sid in sessions:
            sessions[sid]["handoffs"].append(
                {"worker": str(rec.get("worker", "") or ""), "t": t})
    return sessions


# =========================================================================
# Chrome/Perfetto assembly
# =========================================================================
#: synthetic pid lanes in the assembled trace
PID_JOBS = 1
PID_WORKERS = 2
#: worker in-process traces get pids starting here (one per file)
PID_WORKER_TRACE0 = 10


def _us(t: float, t0: float) -> float:
    return round((t - t0) * 1e6, 1)


def chrome_events(jobs: Dict[str, JobLifecycle],
                  worker_traces: Optional[List[dict]] = None) -> list:
    """Assembled lifecycles (+ optional per-worker in-process traces)
    -> one Chrome trace-event list.

    Layout: pid 1 hosts one tid per job (thread-named
    ``job <job_id> [<trace_id>]``) carrying the lifecycle segments as
    ``ph: X`` spans and lease activity as ``ph: i`` instants; pid 2
    hosts one tid per worker (the occupancy lane) with that worker's
    run spans; ``ph: s``/``f`` flow arrows tie each job run span to
    its worker-lane twin, so Perfetto draws the hop a steal makes
    between lanes.  ``worker_traces`` entries are parsed ``--trace-out``
    blobs (dicts with ``traceEvents`` and the ``s2c`` metadata block:
    ``epoch_unix`` re-anchors their perf_counter microseconds onto the
    journal's wall clock; ``trace_id`` joins them to the right job)."""
    t0 = None
    for jl in jobs.values():
        for cand in (jl.submitted_t, jl.started_t):
            if cand is not None and (t0 is None or cand < t0):
                t0 = cand
        for seg in jl.segments:
            if t0 is None or seg.t0 < t0:
                t0 = seg.t0
    if t0 is None:
        t0 = 0.0
    events: list = []
    worker_tids: Dict[str, int] = {}

    def worker_tid(w: str) -> int:
        tid = worker_tids.get(w)
        if tid is None:
            tid = worker_tids[w] = len(worker_tids) + 1
            events.append({"ph": "M", "pid": PID_WORKERS, "tid": tid,
                           "name": "thread_name",
                           "args": {"name": f"worker {w}"}})
        return tid

    events.append({"ph": "M", "pid": PID_JOBS, "tid": 0,
                   "name": "process_name",
                   "args": {"name": "fleet jobs"}})
    events.append({"ph": "M", "pid": PID_WORKERS, "tid": 0,
                   "name": "process_name",
                   "args": {"name": "workers"}})
    flow_id = 0
    for jid, (key, jl) in enumerate(sorted(jobs.items()), start=1):
        events.append({
            "ph": "M", "pid": PID_JOBS, "tid": jid,
            "name": "thread_name",
            "args": {"name": f"job {jl.job_id or key} [{jl.tid}]"}})
        for seg in jl.segments:
            ev = {"ph": "X", "pid": PID_JOBS, "tid": jid,
                  "name": seg.kind, "ts": _us(seg.t0, t0),
                  "dur": max(0.0, round(seg.dur * 1e6, 1)),
                  "args": {"trace_id": jl.tid,
                           **({"worker": seg.worker}
                              if seg.worker else {}),
                           **seg.args}}
            events.append(ev)
            if seg.kind == "run" and seg.worker:
                flow_id += 1
                wtid = worker_tid(seg.worker)
                events.append({
                    "ph": "X", "pid": PID_WORKERS, "tid": wtid,
                    "name": f"run {jl.job_id or key}",
                    "ts": _us(seg.t0, t0),
                    "dur": max(0.0, round(seg.dur * 1e6, 1)),
                    "args": {"trace_id": jl.tid}})
                # flow arrow: job track -> worker occupancy lane
                events.append({"ph": "s", "pid": PID_JOBS, "tid": jid,
                               "name": "placement", "cat": "sched",
                               "id": flow_id, "ts": _us(seg.t0, t0)})
                events.append({"ph": "f", "pid": PID_WORKERS,
                               "tid": wtid, "name": "placement",
                               "cat": "sched", "id": flow_id,
                               "ts": _us(seg.t0, t0), "bp": "e"})
        for name, t, args in jl.instants:
            events.append({"ph": "i", "pid": PID_JOBS, "tid": jid,
                           "name": name, "ts": _us(t, t0), "s": "t",
                           "args": {"trace_id": jl.tid, **args}})
    # per-worker in-process traces, re-anchored to wall clock
    by_trace_id = {jl.tid: jl for jl in jobs.values()}
    for n, blob in enumerate(worker_traces or []):
        meta = blob.get("s2c") or {}
        epoch = meta.get("epoch_unix")
        if epoch is None:
            continue                 # no wall anchor: cannot join
        pid = PID_WORKER_TRACE0 + n
        wname = meta.get("worker") or f"trace{n}"
        tid_joined = meta.get("trace_id", "")
        joined = by_trace_id.get(tid_joined)
        label = f"worker {wname} trace"
        if joined is not None:
            label += f" [job {joined.job_id or joined.key}]"
        events.append({"ph": "M", "pid": pid, "tid": 0,
                       "name": "process_name",
                       "args": {"name": label}})
        for e in blob.get("traceEvents", []):
            if e.get("ph") not in ("X", "i", "M"):
                continue
            ne = dict(e)
            ne["pid"] = pid
            if "ts" in ne:
                ne["ts"] = round((float(epoch) - t0) * 1e6
                                 + float(ne["ts"]), 1)
            if tid_joined:
                args = dict(ne.get("args") or {})
                args.setdefault("trace_id", tid_joined)
                ne["args"] = args
            events.append(ne)
    events.sort(key=lambda e: (e.get("ts", 0.0), e.get("pid", 0)))
    return events


def validate(events: list) -> List[str]:
    """Structural lint over an assembled trace-event list; returns
    violations (empty = valid).  The acceptance bar: Perfetto-loadable
    shape, at least one per-job track, zero negative durations, zero
    orphaned events (every sample event sits on a thread-named
    track)."""
    errs: List[str] = []
    named: set = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            named.add((e.get("pid"), e.get("tid")))
    if not any(pid == PID_JOBS for pid, _ in named):
        errs.append("no per-job track (no thread_name under pid 1)")
    for i, e in enumerate(events):
        ph = e.get("ph")
        if ph not in ("X", "i", "M", "s", "f"):
            errs.append(f"event {i}: unknown ph {ph!r}")
            continue
        if ph == "M":
            continue
        if "ts" not in e:
            errs.append(f"event {i}: missing ts")
        if ph == "X":
            dur = e.get("dur")
            if dur is None:
                errs.append(f"event {i}: complete event missing dur")
            elif float(dur) < 0:
                errs.append(f"event {i}: negative duration {dur}")
        if e.get("pid") in (PID_JOBS, PID_WORKERS) \
                and (e.get("pid"), e.get("tid")) not in named:
            # only the assembler's own synthetic lanes must be fully
            # thread-named; merged in-process traces legitimately
            # carry spans on unnamed (but still renderable) threads
            errs.append(
                f"event {i}: orphaned — pid/tid "
                f"({e.get('pid')}, {e.get('tid')}) has no thread_name")
    return errs


# =========================================================================
# critical-path attribution
# =========================================================================
#: the end-to-end decomposition buckets, in pipeline order.  queue /
#: claim / steal / commit come from the journal; decode / dispatch /
#: tail split the run segment using the job's phase counters when a
#: metrics artifact or manifest is joined, else the run stays whole.
PATH_BUCKETS = ("queue", "claim", "steal", "decode", "dispatch",
                "tail", "run_other", "commit")

#: phase/<name>_sec counters -> decomposition bucket (the SLO plane's
#: dispatch/vote grouping, telemetry.slo_phase_seconds)
_PHASE_BUCKET = {"decode": "decode", "stage": "dispatch",
                 "pileup_dispatch": "dispatch", "accumulate": "dispatch",
                 "vote": "tail", "insertions": "tail", "render": "tail"}


def critical_path(jl: JobLifecycle,
                  phase_sec: Optional[dict] = None) -> Dict[str, float]:
    """One job's end-to-end wall decomposition (seconds per bucket).

    ``phase_sec`` is the job's ``phase/<name>_sec`` counter dict (from
    its metrics JSONL or manifest ``phases`` section, joined by
    trace_id); when present the run segment is split into decode /
    dispatch / tail with the remainder as ``run_other``, capped so a
    counter overshoot can never make the decomposition exceed the
    measured run wall."""
    out = {b: 0.0 for b in PATH_BUCKETS}
    run_sec = 0.0
    last_run_end = None
    for seg in jl.segments:
        if seg.kind == "queue_wait":
            out["queue"] += seg.dur
        elif seg.kind == "claim_latency":
            out["claim"] += seg.dur
        elif seg.kind == "steal_gap":
            out["steal"] += seg.dur
        elif seg.kind == "run":
            run_sec += seg.dur
            last_run_end = seg.t1
    if jl.terminal_t is not None and last_run_end is not None \
            and jl.terminal_t > last_run_end:
        out["commit"] = jl.terminal_t - last_run_end
    if phase_sec:
        budget = run_sec
        for ph, bucket in _PHASE_BUCKET.items():
            sec = float(phase_sec.get(f"phase/{ph}_sec",
                                      phase_sec.get(ph, 0.0)) or 0.0)
            sec = min(sec, budget)
            out[bucket] += sec
            budget -= sec
        out["run_other"] = max(0.0, budget)
    else:
        out["run_other"] = run_sec
    return {k: round(v, 6) for k, v in out.items()}


def wall_report(jobs: Dict[str, JobLifecycle],
                phase_by_trace_id: Optional[dict] = None) -> dict:
    """The fleet-aggregate "where does the wall go" answer: per-bucket
    totals (and the per-job decompositions they sum), for
    ``fleet_trace --report``."""
    totals = {b: 0.0 for b in PATH_BUCKETS}
    per_job = {}
    for key, jl in sorted(jobs.items()):
        ph = (phase_by_trace_id or {}).get(jl.tid)
        d = critical_path(jl, ph)
        per_job[jl.job_id or key] = d
        for b, v in d.items():
            totals[b] += v
    total = sum(totals.values())
    return {"totals_sec": {b: round(v, 6) for b, v in totals.items()},
            "total_sec": round(total, 6),
            "pct": {b: round(100.0 * v / total, 2) if total > 0 else 0.0
                    for b, v in totals.items()},
            "per_job": per_job}
