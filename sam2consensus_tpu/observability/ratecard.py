"""Per-worker learned rate cards: the planner evidence plane.

Every priced decision in the repo (decode_threads, wire_codec,
serve_batch, capacity, mesh_shards — observability/ledger.py) predicts
from a CONSTANT: an env knob (S2C_DECODE_MBPS_PER_CORE), a baked rig
default (the tail link constants), or a single-process EMA that dies
with the process.  The ledger measures the residual per run, but
nothing LEARNS from it: the next job predicts from the same constant.
This module closes that loop with one per-worker card of online rate
estimators:

* **estimator** — EWMA mean + EW variance + sample count + last-update
  wall age per rate key (:data:`RATE_KEYS`); a rate is only *served*
  once it clears the min-sample confidence gate AND its age is under
  the staleness bound (:func:`max_age_sec` — the link cache's
  ``S2C_LINK_CACHE_MAX_AGE`` knob, ONE aging mechanism for every
  learned constant);
* **fold point** — the serve runner feeds the card at its existing
  ``_finalize_job`` choke point from each job's registry snapshot
  (:meth:`RateCard.observe_job`), so both execution paths (serial loop
  and batch scheduler) feed the same card and nothing new runs inside
  a job;
* **persistence** — atomically saved to ``<journal>/ratecard-<worker>
  .json`` (tmp + ``os.replace``, the link-cache discipline) and
  reloaded across restarts with age stamps intact; a corrupt or
  unreadable file reads as ABSENT with a counter
  (``rate/card_corrupt``), never as a failed job.  Each successful
  reload bumps ``restarts`` — the exposition's restart-epoch label,
  which is what lets a scraper (and tools/fleet_whatif.py's merger)
  tell a counter reset from a counter going backwards;
* **consultation** — decision sites call :func:`consult` against the
  process-installed card (:func:`install`); the returned provenance
  stamp (source learned/default, n, age) rides the decision's ledger
  ``inputs`` so every manifest records WHICH constant priced it;
* **scale hints** — :func:`compute_scale_hint` merges live workers'
  cards + burn states + journal queue depth into an evidence-only
  up/down/hold verdict with a worker delta and a projected drain time
  (ROADMAP item 3's input; this module never actuates anything).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

SCHEMA = "s2c-ratecard/1"

#: the load-bearing rates the planner consults.  The card accepts any
#: key (forward compatibility: an old card file may carry keys a
#: newer build renamed), but these are the ones decision sites read.
RATE_KEYS = (
    "decode_mbps_per_core",     # input MB decoded per second per core
    "dispatch_cells_per_sec",   # pileup cells through dispatch+stage
    "vote_sec_per_mcell",       # consensus vote seconds per 1e6 cells
    "wire_bps",                 # achieved h2d wire bytes/sec
    "link_bps",                 # probed raw link bytes/sec (linkprobe)
    "link_rt_sec",              # probed link round-trip seconds
    "warm_jobs_per_sec",        # serial serve jobs/sec (1/elapsed)
    "packed_jobs_per_sec",      # batch-scheduler jobs/sec
    "cohort_jobs_per_sec",      # cohort-wave samples/sec (serve/cohort
                                # observes per wave; wave sizing
                                # consults it, falling back to the
                                # packed rate before wave 1)
    "steal_sec",                # lease-steal latency (expiry -> claim)
    "recovery_sec",             # steal latency + re-run wall seconds
    "capacity_residual_ratio",  # measured/predicted peak-bytes ratio
)

#: EWMA smoothing: ~last 6 observations dominate — fast enough to
#: track a thermal throttle, slow enough that one weird job cannot
#: repoint the card
DEFAULT_ALPHA = 0.3
#: min samples before an estimate is served to a decision site
DEFAULT_MIN_SAMPLES = 3
#: wire-byte floor under which a job's achieved bps says nothing about
#: the link (same rationale as jax_backend._drift_min_wire_bytes)
MIN_WIRE_BYTES = 1e6


def max_age_sec() -> float:
    """The ONE staleness bound for learned constants — the link
    cache's ``S2C_LINK_CACHE_MAX_AGE`` (seconds, default 7 days).
    ``utils/linkprobe.py`` delegates here, so the card and the link
    cache can never disagree about what "stale" means."""
    try:
        return float(os.environ.get("S2C_LINK_CACHE_MAX_AGE",
                                    7 * 86400))
    except ValueError:
        return 7 * 86400.0


def min_samples() -> int:
    try:
        return max(1, int(os.environ.get("S2C_RATECARD_MIN_SAMPLES",
                                         DEFAULT_MIN_SAMPLES)))
    except ValueError:
        return DEFAULT_MIN_SAMPLES


class RateEstimator:
    """One rate's online state: EWMA mean, EW variance (West's
    update), sample count, last-update wall time."""

    __slots__ = ("mean", "var", "n", "updated_unix")

    def __init__(self, mean: float = 0.0, var: float = 0.0,
                 n: int = 0, updated_unix: float = 0.0):
        self.mean = float(mean)
        self.var = float(var)
        self.n = int(n)
        self.updated_unix = float(updated_unix)

    def observe(self, x: float, now: Optional[float] = None,
                alpha: float = DEFAULT_ALPHA) -> None:
        x = float(x)
        if not math.isfinite(x) or x <= 0.0:
            return                      # rates are strictly positive
        if self.n == 0:
            self.mean, self.var = x, 0.0
        else:
            delta = x - self.mean
            self.mean += alpha * delta
            # EW variance: decays like the mean, so stddev tracks the
            # CURRENT spread, not the lifetime spread
            self.var = (1.0 - alpha) * (self.var
                                        + alpha * delta * delta)
        self.n += 1
        self.updated_unix = float(now if now is not None
                                  else time.time())

    def stddev(self) -> float:
        return math.sqrt(self.var) if self.var > 0.0 else 0.0

    def age_sec(self, now: Optional[float] = None) -> float:
        if not self.updated_unix:
            return float("inf")
        return max(0.0, (now if now is not None else time.time())
                   - self.updated_unix)

    def confident(self, now: Optional[float] = None,
                  n_min: Optional[int] = None) -> bool:
        """Served only past the min-sample gate and under the age
        bound — an estimate that is either young-in-samples or
        stale-in-wall-time falls back to the caller's default."""
        return (self.n >= (n_min if n_min is not None
                           else min_samples())
                and self.age_sec(now) <= max_age_sec())

    def to_dict(self) -> dict:
        return {"mean": self.mean, "var": self.var, "n": self.n,
                "updated_unix": round(self.updated_unix, 3)}

    @classmethod
    def from_dict(cls, d: dict) -> "RateEstimator":
        return cls(mean=float(d.get("mean", 0.0)),
                   var=float(d.get("var", 0.0)),
                   n=int(d.get("n", 0)),
                   updated_unix=float(d.get("updated_unix", 0.0)))


class RateCard:
    """One worker's learned rates + restart lineage; see module doc."""

    def __init__(self, worker: str = "", path: Optional[str] = None):
        self.worker = str(worker or "")
        self.path = path
        self.created_unix = time.time()
        #: successful reloads of a persisted card — the exposition's
        #: restart-epoch label (0 = first life)
        self.restarts = 0
        self._lock = threading.RLock()
        self._est: Dict[str, RateEstimator] = {}

    # -- observation ----------------------------------------------------
    def observe(self, key: str, value: float,
                now: Optional[float] = None) -> None:
        with self._lock:
            est = self._est.get(key)
            if est is None:
                est = self._est[key] = RateEstimator()
            est.observe(value, now=now)

    def observe_job(self, snapshot: dict, elapsed_sec: float,
                    input_bytes: int = 0, decode_cores: int = 1,
                    packed: bool = False,
                    lifecycle: Optional[dict] = None,
                    now: Optional[float] = None) -> Dict[str, float]:
        """Fold one finished job's registry snapshot into the card
        (the ``_finalize_job`` choke point).  Returns the rates
        actually observed (for tests/tools).  Guards: every rate needs
        a meaningful denominator — a sub-millisecond phase or a
        sub-megabyte wire bill observes nothing rather than a noise
        spike."""
        c = snapshot.get("counters", {})
        seen: Dict[str, float] = {}
        dec = float(c.get("phase/decode_sec", 0.0))
        if input_bytes > 0 and dec > 0.005:
            seen["decode_mbps_per_core"] = \
                input_bytes / 1e6 / dec / max(1, int(decode_cores))
        cells = float(c.get("pileup/cells", 0.0))
        disp = (float(c.get("phase/pileup_dispatch_sec", 0.0))
                + float(c.get("phase/accumulate_sec", 0.0))
                + float(c.get("phase/stage_sec", 0.0)))
        if cells > 0 and disp > 0.001:
            seen["dispatch_cells_per_sec"] = cells / disp
        vote = float(c.get("phase/vote_sec", 0.0))
        if cells >= 1e5 and vote > 0.001:
            seen["vote_sec_per_mcell"] = vote / (cells / 1e6)
        wire = float(c.get("wire/bytes", 0.0))
        wden = (float(c.get("phase/stage_sec", 0.0))
                + float(c.get("phase/pileup_dispatch_sec", 0.0)))
        if wire >= MIN_WIRE_BYTES and wden > 0.001:
            seen["wire_bps"] = wire / wden
        if elapsed_sec > 0.001:
            seen["packed_jobs_per_sec" if packed
                 else "warm_jobs_per_sec"] = 1.0 / elapsed_sec
        steal = (lifecycle or {}).get("steal_latency_sec")
        if steal is not None and steal > 0:
            seen["steal_sec"] = float(steal)
            # recovery = expiry-to-claim gap + the re-run itself: the
            # wall cost of losing a worker mid-job, the scale-hint
            # model's churn term
            seen["recovery_sec"] = float(steal) \
                + max(0.0, float(elapsed_sec))
        # capacity model quality: the ledger already joined this job's
        # measured peak against the predicted peak — learn the ratio,
        # so the capacity/mesh_shards provenance stamps can report how
        # tight the upper bound runs on THIS host
        cap = (snapshot.get("gauges", {})
               .get("residual/capacity/bytes") or {})
        if float(cap.get("value", 0.0)) > 0:
            seen["capacity_residual_ratio"] = float(cap["value"])
        for key, val in seen.items():
            self.observe(key, val, now=now)
        return seen

    # -- consultation ---------------------------------------------------
    def rate(self, key: str, default: Optional[float] = None,
             now: Optional[float] = None) -> Optional[float]:
        with self._lock:
            est = self._est.get(key)
            if est is not None and est.confident(now):
                return est.mean
        return default

    def consult(self, key: str, default: float,
                now: Optional[float] = None) -> Tuple[float, dict]:
        """(value, provenance) — the provenance dict is the ledger
        ``inputs["ratecard"]`` stamp: which source priced the
        decision, with the evidence (n, age, spread) to audit it."""
        with self._lock:
            est = self._est.get(key)
            if est is not None and est.confident(now):
                return est.mean, {
                    "source": "learned", "key": key,
                    "n": est.n,
                    "age_sec": round(est.age_sec(now), 1),
                    "stddev": round(est.stddev(), 6),
                    "default": default,
                }
            prov = {"source": "default", "key": key}
            if est is not None:
                prov["n"] = est.n      # gated: young or stale
                if est.updated_unix:
                    prov["age_sec"] = round(est.age_sec(now), 1)
        return float(default), prov

    # -- persistence ----------------------------------------------------
    def to_blob(self, now: Optional[float] = None) -> dict:
        with self._lock:
            return {
                "schema": SCHEMA,
                "worker": self.worker,
                "created_unix": round(self.created_unix, 3),
                "saved_unix": round(now if now is not None
                                    else time.time(), 3),
                "restarts": self.restarts,
                "rates": {k: e.to_dict()
                          for k, e in sorted(self._est.items())},
            }

    def save(self, now: Optional[float] = None) -> None:
        """Atomic persist (tmp + ``os.replace``) — callers absorb
        failures (the telemetry plane's never-fail-a-job rule)."""
        if not self.path:
            return
        blob = self.to_blob(now)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(blob, fh, sort_keys=True, indent=1)
            fh.write("\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.path)

    @classmethod
    def load(cls, path: str, worker: str = "",
             registry=None) -> "RateCard":
        """Load-or-fresh: a missing file is a fresh card; a corrupt or
        schema-mismatched file reads as ABSENT with a counter
        (``rate/card_corrupt``) — never an exception, never a failed
        job.  A successful load bumps ``restarts`` (this process is a
        new life of a persisted card)."""
        card = cls(worker=worker, path=path)
        try:
            with open(path, encoding="utf-8") as fh:
                blob = json.load(fh)
            if blob.get("schema") != SCHEMA:
                raise ValueError(f"schema {blob.get('schema')!r}")
            card.created_unix = float(
                blob.get("created_unix", card.created_unix))
            card.restarts = int(blob.get("restarts", 0)) + 1
            for key, d in (blob.get("rates") or {}).items():
                card._est[str(key)] = RateEstimator.from_dict(d)
        except FileNotFoundError:
            pass
        except Exception:
            if registry is not None:
                try:
                    registry.add("rate/card_corrupt", 1)
                except Exception:
                    pass
            card._est.clear()
            card.restarts = 0
        return card

    # -- export ---------------------------------------------------------
    def publish(self, registry, now: Optional[float] = None) -> None:
        """Refresh the card's gauge family in ``registry`` — rendered
        as ``s2c_rate{key=...}`` (+ ``_stddev``/``_samples``/
        ``_age_seconds``) by the exposition."""
        with self._lock:
            items = list(self._est.items())
            restarts = self.restarts
        for key, est in items:
            registry.gauge(f"rate/mean/{key}").set(round(est.mean, 6))
            registry.gauge(f"rate/stddev/{key}").set(
                round(est.stddev(), 6))
            registry.gauge(f"rate/samples/{key}").set(float(est.n))
            registry.gauge(f"rate/age_seconds/{key}").set(
                round(est.age_sec(now), 1))
        g = registry.gauge("rate/card")
        g.set(float(restarts))
        g.set_info({"worker": self.worker, "restarts": restarts,
                    "path": self.path or "",
                    "max_age_sec": max_age_sec()})

    def snapshot(self, now: Optional[float] = None) -> dict:
        """Health-section view: every estimator with its confidence
        verdict, so an operator sees WHY a rate is (not) being
        served."""
        with self._lock:
            return {
                "worker": self.worker,
                "restarts": self.restarts,
                "rates": {
                    k: {"mean": round(e.mean, 6),
                        "stddev": round(e.stddev(), 6),
                        "n": e.n,
                        "age_sec": round(e.age_sec(now), 1)
                        if e.updated_unix else None,
                        "confident": e.confident(now)}
                    for k, e in sorted(self._est.items())},
            }


# -- process-installed card (decision-site consultation) -------------------
_installed: Optional[RateCard] = None
_install_lock = threading.Lock()


def install(card: Optional[RateCard]) -> None:
    """Make ``card`` the process's consulted card (None uninstalls).
    The serve runner installs its worker card at startup; one-shot CLI
    runs have no card and every consult serves the default."""
    global _installed
    with _install_lock:
        _installed = card


def installed() -> Optional[RateCard]:
    return _installed


def consult(key: str, default: float,
            now: Optional[float] = None) -> Tuple[float, dict]:
    """Decision-site entry point: the installed card's learned rate
    when confident, else ``default`` — always with the provenance
    stamp for the decision's ledger inputs."""
    card = installed()
    if card is None:
        return float(default), {"source": "default", "key": key}
    return card.consult(key, default, now=now)


# -- scale-hint evidence API ------------------------------------------------
def drain_target_sec() -> float:
    """Queue-drain objective the hint plans against
    (S2C_SCALE_DRAIN_TARGET_SEC, default 600 s): a queue projected to
    drain slower than this argues for more workers."""
    try:
        return max(1.0, float(os.environ.get(
            "S2C_SCALE_DRAIN_TARGET_SEC", "600")))
    except ValueError:
        return 600.0


def compute_scale_hint(cards: List[dict], queue_depth: int,
                       workers: int,
                       burn_states: Optional[Dict[str, str]] = None,
                       target_sec: Optional[float] = None,
                       now: Optional[float] = None) -> dict:
    """Evidence-only fleet sizing verdict.

    ``cards`` are card snapshots (:meth:`RateCard.snapshot` dicts —
    the shape both live registries and the persisted JSON provide);
    ``queue_depth`` the journal's live (submitted-not-terminal) count;
    ``burn_states`` tenant -> ok/warn/page from the burn plane.
    Returns ``{verdict, delta, workers, queue_depth, jobs_per_sec,
    projected_drain_sec, target_sec, paging_tenants, reason}`` — the
    ``s2c_fleet_scale_hint`` gauge value is ``delta`` (sign IS the
    verdict), and the whole dict rides the health snapshot and the
    band=0 ``scale_hint`` ledger decision.  No actuation: ROADMAP
    item 3 consumes this."""
    target = target_sec if target_sec is not None else drain_target_sec()
    per_worker: List[float] = []
    for snap in cards:
        rates = (snap or {}).get("rates") or {}
        best = 0.0
        for key in ("warm_jobs_per_sec", "packed_jobs_per_sec"):
            ent = rates.get(key) or {}
            if ent.get("confident") and float(ent.get("mean", 0)) > 0:
                best = max(best, float(ent["mean"]))
        if best > 0:
            per_worker.append(best)
    paging = sorted(t for t, s in (burn_states or {}).items()
                    if s == "page")
    total_jps = sum(per_worker)
    mean_jps = (total_jps / len(per_worker)) if per_worker else 0.0
    hint = {
        "workers": int(workers),
        "queue_depth": int(queue_depth),
        "jobs_per_sec": round(total_jps, 6),
        "target_sec": round(target, 1),
        "paging_tenants": paging,
        "confident_cards": len(per_worker),
    }
    if not per_worker:
        # no card has cleared the confidence gate yet: refusing to
        # guess IS the evidence discipline
        hint.update(verdict="hold", delta=0,
                    projected_drain_sec=None,
                    reason="no_confident_rate")
        return hint
    drain = queue_depth / total_jps if total_jps > 0 else float("inf")
    hint["projected_drain_sec"] = round(drain, 1)
    needed = max(1, int(math.ceil(
        queue_depth / (mean_jps * target))) if queue_depth else 1)
    if paging:
        delta = max(1, needed - workers)
        hint.update(verdict="up", delta=int(delta),
                    reason="tenant_paging")
    elif drain > target and needed > workers:
        hint.update(verdict="up", delta=int(needed - workers),
                    reason="drain_over_target")
    elif workers > 1 and needed < workers and drain < 0.25 * target:
        hint.update(verdict="down", delta=int(needed - workers),
                    reason="headroom")
    else:
        hint.update(verdict="hold", delta=0, reason="in_band")
    return hint


def card_path(journal_root: str, worker: str) -> str:
    """Canonical per-worker card file next to the shared journal."""
    return os.path.join(journal_root, f"ratecard-{worker}.json")
