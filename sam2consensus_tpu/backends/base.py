"""The ``ConsensusBackend`` operator boundary (SURVEY.md §2b).

The reference is a monolith; the new framework splits it at the natural seam:
everything between "decoded SAM records" and "per-reference FASTA records"
is a backend.  Both backends must produce byte-identical FASTA text — that is
the framework's correctness gate (BASELINE.md).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Protocol, Tuple

from ..config import RunConfig
from ..io.fasta import FastaRecord  # noqa: F401  (canonical home: io.fasta)
from ..io.sam import Contig, SamRecord


@dataclass
class BackendStats:
    reads_mapped: int = 0
    reads_skipped: int = 0      # permissive-mode drops (strict=False only)
    aligned_bases: int = 0      # M/=/X + counted gap bases (pileup increments)
    consensus_bases: int = 0    # emitted consensus characters across outputs
    extra: Dict[str, float] = field(default_factory=dict)


@dataclass
class BackendResult:
    """Per-reference FASTA records, in contig file order, threshold order."""
    fastas: Dict[str, List[FastaRecord]]
    stats: BackendStats


class ConsensusBackend(Protocol):
    name: str

    def run(self, contigs: List[Contig], records: Iterable[SamRecord],
            cfg: RunConfig) -> BackendResult: ...


def format_header(prefix: str, threshold: float, refname: str,
                  sumcov: int, seq: str, stripped_len=None) -> str:
    """FASTA header, field-for-field per sam2consensus.py:394-397.

    ``coverage`` is ``round(sumcov/len(seq), 2)`` rendered via ``str``;
    ``length`` strips only ``"-"`` so a non-gap fill char counts (quirk
    10).  ``stripped_len`` is an optional precomputed ``len(seq)`` minus
    dash count (the jax backend counts it vectorized; value must equal
    ``len(seq.replace("-", ""))``).
    """
    if stripped_len is None:
        stripped_len = len(seq.replace("-", ""))
    return (">" + prefix + "|c" + str(int(threshold * 100))
            + " reference:" + refname
            + " coverage:" + str(round(float(sumcov) / float(len(seq)), 2))
            + " length:" + str(stripped_len)
            + " consensus_threshold:" + str(int(threshold * 100)) + "%")
