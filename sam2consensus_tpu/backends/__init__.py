from .base import BackendResult, BackendStats, ConsensusBackend, FastaRecord  # noqa: F401
