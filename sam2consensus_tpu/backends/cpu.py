"""CPU golden backend: spec-faithful Python 3 oracle.

This is a deliberate, documented re-implementation of the reference
algorithm's *semantics* (``/root/reference/sam2consensus.py``, analyzed in
SURVEY.md §2) — the reference itself is Python-2-only (``iteritems`` at
``:242,:247,:299,:304``) and cannot run here.  Every quirk that shapes output
bytes is reproduced:

* pileup over the fixed ``-ACGNT`` alphabet, gaps and Ns counted into
  coverage (``:237``, quirk 5);
* the per-read deletion gate: total gap length > maxdel ⇒ gap bases skipped
  but the cursor still advances (``:210-218``);
* negative Python-style indexing when POS-1 + leading deletions goes below
  zero (list indexing at ``:212`` wraps within the contig);
* count→nucleotide-group inversion with *group totals* (count × group size,
  ``:241-252``);
* the insertion "mini-alignment of motifs" with coverage-completion of the
  gap lane — which may go negative (``:256-311``, quirk 4);
* greedy threshold vote with tie groups all-or-nothing, compared against
  ``t * coverage`` in float (``:359-366``);
* insertion columns voted against the *position's* coverage and emitted after
  the position's base (right-shift placement, quirks 3/8);
* zero-coverage reference pruning (``:334-340``) and empty-sequence dropping
  (``:400-406``).

Everything here is Python dict/loop code on purpose: it is the oracle, and
its clarity is the proof of the spec.  The JAX backend must match its output
byte for byte.
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, List, Tuple

from .. import observability as obs
from ..config import RunConfig
from ..constants import AMB, ALPHABET
from ..core.cigar import walk
from ..io.sam import Contig, SamRecord
from .base import BackendResult, BackendStats, FastaRecord, format_header


def _fresh_counts() -> Dict[str, int]:
    return {s: 0 for s in ALPHABET}


class CpuBackend:
    name = "cpu"

    def run(self, contigs: List[Contig], records: Iterable[SamRecord],
            cfg: RunConfig) -> BackendResult:
        """Observability wrapper: the oracle gets the same per-run
        tracer/registry scope as the jax backend, so ``--trace-out`` /
        ``--metrics-out`` work on ``--backend cpu`` and its phase
        seconds surface through the same compat view."""
        from ..ingest.badrecords import (BadRecordBudgetExceeded,
                                         abort_bookkeeping)

        robs = obs.start_run(
            trace_out=getattr(cfg, "trace_out", None),
            metrics_out=getattr(cfg, "metrics_out", None))
        try:
            result = self._run(contigs, records, cfg)
            obs.publish_stats_extra(result.stats.extra)
            return result
        except BadRecordBudgetExceeded as exc:
            abort_bookkeeping(exc, obs.metrics())
            raise
        finally:
            obs.finish_run(robs, meta={"backend": self.name})

    def _run(self, contigs: List[Contig], records: Iterable[SamRecord],
             cfg: RunConfig) -> BackendResult:
        from ..encoder.events import render_record
        from ..ingest.badrecords import sink_from_config

        # tolerant decode (--on-bad-record skip|quarantine): the oracle
        # is its own single rung — parse errors absorb through the
        # iter_records hook, validation errors through the loop below;
        # both into one stream-order partition
        bad_sink = sink_from_config(cfg)
        source = records
        # any stream-shaped source (io.sam.ReadStream, formats.bam
        # BamReadStream) yields parsed records; bare record iterables
        # pass through
        if hasattr(records, "records"):
            on_bad = None
            if bad_sink is not None:
                def on_bad(line, exc):
                    # parse-level bad record: quarantine AND count the
                    # skip, exactly like the native rungs' replay lane.
                    # BAM parse errors know their record offset (the
                    # text lane has no offset tracking — documented)
                    off = getattr(exc, "s2c_offset", None)
                    if off is None:
                        off = getattr(exc, "offset", None)
                        if not isinstance(off, int) or off < 0:
                            off = None
                    bad_sink.record(line, exc, offset=off)
                    stats.reads_skipped += 1
            records = source.records(on_bad=on_bad)
        stats = BackendStats()
        tr = obs.tracer()
        reg = obs.metrics()

        # --- allocation (header pass, sam2consensus.py:160-169) ---
        # Duplicate @SQ names overwrite like the reference's dict assignment
        # (last LN wins); iteration order is first-seen, as in py3 dicts.
        lengths: Dict[str, int] = {}
        for c in contigs:
            lengths[c.name] = c.length
        order = list(lengths)
        sequences = {name: [_fresh_counts() for _ in range(length)]
                     for name, length in lengths.items()}
        coverages = {name: [0] * length for name, length in lengths.items()}
        insertions: Dict[str, list] = {name: [] for name in lengths}

        # --- accumulation (sam2consensus.py:191-221) ---
        t0 = time.perf_counter()
        for rec in records:
            err = None
            seqs_ref = seqout = insert = None
            if rec.refname not in sequences:
                err = KeyError(
                    f"read mapped to unknown reference {rec.refname!r} "
                    "(reference would KeyError here too)")
            else:
                seqs_ref = sequences[rec.refname]
                seqout, insert = walk(rec.cigar, rec.seq, rec.pos)
                # Validate the whole read *before* touching the pileup so
                # a skip (permissive OR tolerant) leaves no partial
                # increments behind.  A zero-span read (all S/H/I ops)
                # touches no position and is accepted at any POS, like
                # the reference's zero-iteration loop.
                span_end = rec.pos + len(seqout)
                in_bounds = (len(seqout) == 0
                             or (-len(seqs_ref) <= rec.pos
                                 and span_end <= len(seqs_ref)))
                if not in_bounds:
                    err = IndexError(
                        f"read at pos {rec.pos} spans [{rec.pos},"
                        f" {span_end}) outside reference "
                        f"{rec.refname!r} of length {len(seqs_ref)} "
                        "(reference would IndexError here too)")
                elif not (all(ch in "-ACGNT" for ch in seqout)
                          and all(ch in "-ACGNT"
                                  for _pos, motif in insert
                                  for ch in motif)):
                    err = KeyError(
                        f"read at pos {rec.pos} contains an "
                        "out-of-alphabet "
                        "base (input contract is uppercase ACGTN; the "
                        "reference would KeyError here too, though for "
                        "insertion motifs only later, in its reformat "
                        "pass)")
            if err is not None:
                if bad_sink is not None:
                    # tolerant decode: quarantine/count per record (the
                    # sink raises the budget error when it is spent)
                    bad_sink.record(render_record(rec), err)
                    stats.reads_skipped += 1
                    continue
                if cfg.strict:
                    raise err from None
                stats.reads_skipped += 1
                continue
            pos_ref = rec.pos
            if cfg.maxdel is None or seqout.count("-") <= cfg.maxdel:
                for nuc in seqout:
                    seqs_ref[pos_ref][nuc] += 1
                    stats.aligned_bases += 1
                    pos_ref += 1
            else:
                for nuc in seqout:
                    if nuc != "-":
                        seqs_ref[pos_ref][nuc] += 1
                        stats.aligned_bases += 1
                    pos_ref += 1
            insertions[rec.refname] += insert
            stats.reads_mapped += 1
        reg.add("phase/accumulate_sec", time.perf_counter() - t0)
        tr.complete("accumulate", t0)
        reg.add("reads/mapped", stats.reads_mapped)
        reg.add("reads/skipped", stats.reads_skipped)
        reg.add("pileup/cells", stats.aligned_bases)
        if bad_sink is not None:
            total = int(getattr(source, "n_lines", 0) or 0)
            if total <= 0:
                total = stats.reads_mapped + stats.reads_skipped
            summary = bad_sink.finish(total)
            bad_sink.publish(reg)
            if summary["bad_records"]:
                stats.extra["bad_records"] = summary["bad_records"]
                if summary.get("sidecar"):
                    stats.extra["quarantine_sidecar"] = summary["sidecar"]

        # --- reformat + insertion table (sam2consensus.py:233-311) ---
        t0 = time.perf_counter()
        for refname in order:
            for pos in range(len(coverages[refname])):
                coverages[refname][pos] = sum(sequences[refname][pos].values())
                count_nucs: Dict[int, List[str]] = {}
                for key, value in sequences[refname][pos].items():
                    if value != 0:
                        count_nucs.setdefault(value, []).append(key)
                groups = sorted(count_nucs.items(), reverse=True)
                sequences[refname][pos] = [[cnt * len(nucs), nucs]
                                           for cnt, nucs in groups]

            if insertions[refname]:
                ins_tmp1: Dict[int, Dict[str, int]] = {}
                for pos_i, motif in insertions[refname]:
                    ins_tmp1.setdefault(pos_i, {})
                    ins_tmp1[pos_i][motif] = ins_tmp1[pos_i].get(motif, 0) + 1

                ins_tmp2: Dict[int, list] = {}
                for pos_i in sorted(ins_tmp1):
                    longest = max(len(m) for m in ins_tmp1[pos_i])
                    ins_tmp2[pos_i] = [_fresh_counts() for _ in range(longest)]
                for pos_i in sorted(ins_tmp1):
                    for motif, mcount in ins_tmp1[pos_i].items():
                        for col, ch in enumerate(motif):
                            ins_tmp2[pos_i][col][ch] += mcount

                for pos_i in sorted(ins_tmp2):
                    for col in range(len(ins_tmp2[pos_i])):
                        colcounts = ins_tmp2[pos_i][col]
                        # gap lane completed from coverage; may be negative
                        # when inserting reads contribute no coverage at pos
                        # (quirk 4). pos_i == reflength (end-of-contig insert)
                        # would IndexError in the reference via coverages[pos];
                        # Python list indexing accepts it only when < len, so
                        # mirror: such keys exist but are never emitted.
                        cov_here = (coverages[refname][pos_i]
                                    if pos_i < len(coverages[refname]) else 0)
                        colcounts["-"] = cov_here - sum(colcounts.values())
                        count_nucs = {}
                        for key, value in colcounts.items():
                            if value != 0:
                                count_nucs.setdefault(value, []).append(key)
                        groups = sorted(count_nucs.items(), reverse=True)
                        ins_tmp2[pos_i][col] = [[cnt * len(nucs), nucs]
                                                for cnt, nucs in groups]
                insertions[refname] = ins_tmp2
        reg.add("phase/reformat_sec", time.perf_counter() - t0)
        tr.complete("reformat", t0)

        # --- zero-coverage prune (sam2consensus.py:334-340) ---
        for refname in list(order):
            if sum(coverages[refname]) == 0:
                del sequences[refname]
                del insertions[refname]

        # --- consensus call (sam2consensus.py:345-406) ---
        t0 = time.perf_counter()
        fastas: Dict[str, List[FastaRecord]] = {}
        for refname in order:
            if refname not in sequences:
                continue
            for t in cfg.thresholds:
                out_chars: List[str] = []
                sumcov = 0
                for pos in range(len(sequences[refname])):
                    if sequences[refname][pos] != []:
                        cov = coverages[refname][pos]
                        sumcov += cov
                        if cov >= cfg.min_depth:
                            out_chars.append(_vote(sequences[refname][pos],
                                                   t * cov))
                            ins_table = insertions[refname]
                            if isinstance(ins_table, dict) and pos in ins_table:
                                for colgroups in ins_table[pos]:
                                    call = _vote(colgroups, t * cov)
                                    if call == "-":
                                        continue
                                    out_chars.append(call)
                                    sumcov += cov
                        else:
                            out_chars.append(cfg.fill)
                    else:
                        out_chars.append(cfg.fill)

                seq = "".join(out_chars)
                if len(seq.replace("-", "")) > 0:
                    header = format_header(cfg.prefix, t, refname, sumcov, seq)
                    fastas.setdefault(refname, []).append(
                        FastaRecord(header, seq))
                    stats.consensus_bases += len(seq)
        reg.add("phase/consensus_sec", time.perf_counter() - t0)
        tr.complete("consensus", t0)

        return BackendResult(fastas=fastas, stats=stats)


def _vote(groups: List[list], cutoff: float) -> str:
    """Greedy tie-group accumulation (sam2consensus.py:359-367).

    ``groups`` is the reformatted ``[[group_total, [nucs]], ...]`` list sorted
    by descending per-nucleotide count; groups are taken whole while the
    accumulated total stays below ``cutoff`` (``t * coverage`` in float).
    """
    nucs: List[str] = []
    cov_nucs = 0
    for total, members in groups:
        if cov_nucs < cutoff:
            nucs += members
            cov_nucs += total
        else:
            break
    # Empty called set — reachable two ways, both of which the reference
    # crashes on (``amb[""]`` KeyError at sam2consensus.py:367): an insertion
    # column whose lanes all cancel to zero after gap completion (requires a
    # '-' motif char, outside the ACGTN input contract), or an API-supplied
    # threshold <= 0 (cutoff <= 0 takes no group; the CLI rejects these).
    # Define it as a gap — skipping the column / filling the position —
    # matching the JAX vote exactly (mask 0 → '-' via the total LUT).
    if not nucs:
        return "-"
    return AMB["".join(sorted(nucs))]
