"""JAX/TPU backend: decode → pileup → fused one-round-trip tail → render.

The pipeline replacing the reference's interpreter loops (SURVEY.md §1
"new-framework layer map", §7 steps 3-7), shaped by the measured link
roofline (PERF.md):

1. the host decoder turns SAM text into segment rows
   (``encoder/events.py`` / ``native/decoder.cpp``), prefetched on a
   background thread; the count tensor — the entire job state, and
   sum-decomposable, which is what makes DP/psum and checkpointing
   exact — accumulates by the least-wire strategy (``ops/pileup.py``):
   4-bit-packed rows into a device scatter or MXU one-hot matmul
   (autotuned), or, for deep/small genomes, fused into the C++ decode
   pass itself and shipped as dtype-narrowed counts once
   (optionally multi-threaded, ``encoder/parallel_decode.py``);
2. the whole post-accumulation tail is ONE dispatch returning ONE packed
   buffer (``ops/fused.py``): the closed-form threshold vote with exact
   device-side float64 cutoffs (``ops/vote.py``, ``ops/cutoff.py``), the
   insertion "mini-alignment" table and vote (``ops/insertions.py``),
   per-contig coverage sums and per-site coverage — position symbols
   travel by the cheapest modeled wire encoding (dense ASCII, 5-bit
   packed planes, or emit-bitmask sparse; the output-encoding gate
   below); tails whose modeled link cost exceeds the local vote rate
   route the same jitted functions to the local XLA CPU backend;
3. the host splices insertion columns after their site's base
   (right-shift placement, quirk 3), substitutes the fill character for
   sentinel bytes and renders FASTA records byte-identically to the CPU
   oracle.

Output equality with ``CpuBackend`` over the whole fixture corpus is the
framework's correctness gate (tests/test_differential.py).
"""

from __future__ import annotations

import os
import time
from typing import Dict, Iterable, List

import numpy as np

from .. import observability as obs
from ..config import RunConfig
from ..observability import memplane
from ..constants import NUM_SYMBOLS
from ..io.sam import Contig, SamRecord
from .base import BackendResult, BackendStats, FastaRecord, format_header

#: CEILING on the sp/dpsp halo width — the encoder's worst-case bucket
#: widening bound (encoder/native_encoder.py).  The actual halo is the
#: run's observed widest row bucket (_build_sharded_acc, verdict r4 #5);
#: this constant only caps it.
SP_HALO = 1 << 16

#: tail-placement cost model for the host-counts path (counts already in
#: host memory).  The chip's vote compute is free but the link bills a
#: dispatch round trip, the counts upload, and the output fetch; the
#: local XLA CPU backend is wire-free but votes at a measured per-core
#: rate.  The link constants SELF-CALIBRATE at first use via a cheap
#: cached probe of the real device (utils/linkprobe: one null-dispatch
#: round trip + one 1 MB put, ~3 RTs once per process), so routing is
#: correct on an un-tuned host — a TPU-VM's PCIe link (~GB/s, sub-ms RT)
#: flips the same decisions the 40 MB/s tunnel pins host-side.  Env
#: overrides win and skip the probe; the defaults below are the bench
#: rig's measured numbers (tools/tunnel_probe.py), used when probing is
#: disabled (S2C_LINK_PROBE=0) or impossible.  The cpu-side rates come
#: from tools/tail_crossover.py (the sweep's T=1 crossover sits at ~4M
#: positions, the T=3 crossover at ~200k — no single cell-count gate
#: represents both).
TAIL_RT_SEC_DEFAULT = 65e-3
TAIL_LINK_BPS_DEFAULT = 40e6


def _link_constants() -> tuple:
    """(rt_sec, link_bps) for the placement model: env override, else
    the cached startup probe (real accelerators only), else the bench
    rig's defaults.  Every call re-registers the constants (and their
    provenance + age) in the run's decision ledger, joined at run end
    against the measured effective wire rate — the drift alarm that
    would have caught the round-5 baked-default rot."""
    rt_env = os.environ.get("S2C_TAIL_RT_MS")
    bps_env = os.environ.get("S2C_TAIL_LINK_MBPS")
    rt = float(rt_env) / 1e3 if rt_env else None
    bps = float(bps_env) * 1e6 if bps_env else None
    env_partial = (rt is None) != (bps is None)
    source = "env" if (rt is not None and bps is not None) else None
    if rt is None or bps is None:
        probed = _probed_link()
        if probed is not None:
            from ..utils import linkprobe

            source = linkprobe.link_info().get("source") or "probed"
            if env_partial:
                # one field env-overridden, the other probed: say so —
                # the manifest's provenance must not attribute an env
                # value to the probe (or vice versa)
                source = f"env+{source}"
            if rt is None:
                rt = probed[0]
            if bps is None:
                bps = probed[1]
        elif env_partial:
            source = "env+default"
    if source is None:
        source = "default"
    rt = TAIL_RT_SEC_DEFAULT if rt is None else rt
    bps = TAIL_LINK_BPS_DEFAULT if bps is None else bps
    from ..utils import linkprobe as _lp

    inputs = {"rt_ms": round(rt * 1e3, 3),
              "link_mbps": round(bps / 1e6, 2), "source": source}
    age = _lp.link_info().get("age_sec")
    if age is not None:
        inputs["age_sec"] = age
    # measured join: effective h2d rate over the staging + dispatch
    # windows (the only windows the wire bill occupies); runs shipping
    # under the min_num wire floor join nothing and can never drift —
    # below it the windows are encode/compute-dominated and the
    # achieved rate says nothing about the link.  A link-free default
    # backend gets NO join at all — its "wire" is a memcpy inside
    # compute-dominated windows, and the resulting rate says nothing
    # about these constants (which nothing prices there)
    try:
        import jax

        link_free = jax.default_backend() == "cpu"
    except Exception:
        link_free = True
    # rate-card consultation stamp (observability/ratecard.py): which
    # aging mechanism served these constants — the value itself still
    # comes from env/probe/cache (linkprobe feeds the card, so the two
    # agree once the card has samples), but the manifest records the
    # card's view (n, age) next to the decision either way
    from ..observability import ratecard as _rc

    _unused_bps, rc_prov = _rc.consult("link_bps", bps)
    obs.record_decision(
        "link_constants", source, inputs=inputs,
        predicted={"bps": bps},
        measured=None if link_free else
        {"bps": {"num": ["wire/bytes"],
                 "den": ["phase/stage_sec",
                         "phase/pileup_dispatch_sec"],
                 "min_num": _drift_min_wire_bytes()}},
        provenance=rc_prov)
    return (rt, bps)


def _drift_min_wire_bytes() -> float:
    """Wire-bytes floor under which bps residuals never join
    (S2C_DRIFT_MIN_WIRE_MB, default 8 MB — at the modeled 40 MB/s
    that is 0.2 s of transfer, the scale where the link constants
    start to matter at all)."""
    try:
        return float(os.environ.get("S2C_DRIFT_MIN_WIRE_MB", "8")) * 1e6
    except ValueError:
        return 8e6


def _probed_link():
    """(rt_sec, bps) from the cached startup probe, or None when probing
    is disabled (S2C_LINK_PROBE=0), impossible, or failed.  The one
    probe-gating definition shared by every link-rate consumer."""
    if os.environ.get("S2C_LINK_PROBE", "1") != "0":
        import jax

        if jax.default_backend() != "cpu":
            from ..utils.linkprobe import probe_link

            return probe_link()
    return None


def _measured_link_bps():
    """Link rate for gate-WIDENING decisions (host_pileup_max_len's
    slow-link bypass): an env override or a successful probe only —
    never the baked rig default, which (at 40 MB/s, below the bypass
    threshold) would unbound the host gate on a fast-linked machine
    whose probe didn't run."""
    bps_env = os.environ.get("S2C_TAIL_LINK_MBPS")
    if bps_env:
        return float(bps_env) * 1e6
    probed = _probed_link()
    return probed[1] if probed is not None else None
TAIL_CPU_POS_PER_SEC = float(os.environ.get(
    "S2C_TAIL_CPU_MPOS_S", "5.2")) * 1e6
#: the C++ vote's measured costs (native/decoder.cpp s2c_vote at L=1M:
#: 31 ms for T=1, +3 ms per extra threshold) — used by the placement
#: model instead of the XLA rate whenever the native library loads
TAIL_NATIVE_NS_PER_POS = float(os.environ.get("S2C_TAIL_NATIVE_NS", "31"))
TAIL_NATIVE_THR_NS = float(os.environ.get("S2C_TAIL_NATIVE_THR_NS", "3"))
#: per-position overhead of the sparse output path: device compaction
#: scatter (~12 ns) + host re-expansion (~8 ns), measured round 3 at
#: L = 40M (see the output-encoding gate below)
SPARSE_NS_PER_POS = float(os.environ.get("S2C_SPARSE_NS_PER_POS", "20"))
#: host decode cost of the 5-bit packed output encoding (pair-LUT gather
#: + high-bit fixups, _expand_packed5): 5.5 ns/char measured at L = 40M
#: with 2% high-plane fill
P5_HOST_NS_PER_CHAR = float(os.environ.get("S2C_P5_HOST_NS", "5.5"))
#: device-side cost of the packed5 plane split.  The first formulation
#: (32-way one-hot re-select of the ASCII output + stride-2 slicing)
#: measured ~22 ns/char on the chip at L = 40M — worse than the wire it
#: saved on the 40 MB/s link.  The current one votes directly in code5
#: (zero re-encode) and packs with contiguous reshapes; measured on the
#: TPU v5 lite at 1.3 ns/char (L = 40M) and 1.9 ns/char (L = 4.6M)
#: (tools/measure_p5.py, campaign/measure_p5.jsonl round 4: packed5
#: end-to-end 1.75 s vs dense 2.78 s at L = 40M on the ~15 MB/s
#: tunnel).  The default prices the slower small-L figure, so auto
#: picks packed5 whenever the link is below ~190 MB/s — on faster
#: links the 0.375 B/char wire saving stops covering even 2 ns of
#: device packing.
P5_DEV_NS_PER_CHAR = float(os.environ.get("S2C_P5_DEV_NS", "2"))
#: --insertion-kernel auto window, re-measured round 5 against the
#: FUSED in-kernel vote (the decision-relevant comparison: scatter
#: table + XLA vote vs one kernel, campaign/microbench_tpu_r05.jsonl):
#: 0.94x at 2e4 events, 0.75-0.97x at 2e5 (fetch-RT-dominated — ~65 ms
#: tunnel round trips on ~100 ms totals), 1.36x at 2e6, 2.28x at 8e6,
#: 0.77-2.23x at 1e7 (two runs; tunnel-state variance).  The window
#: below keeps the kernel where it wins consistently; outside it, and
#: for any host-routed or interpret-mode tail, scatter is the measured
#: choice.  Re-pins come from tools/ins_window_calibrate.py only —
#: median of 3 independent runs per point with the per-run samples
#: committed (campaign/ins_window_<round>.jsonl), never from a single
#: run (VERDICT r5 #4).
PALLAS_INS_MIN_EVENTS = 1_000_000
PALLAS_INS_MAX_EVENTS = 16_000_000


def _pallas_ins_auto(n_events: int, chip_tail: bool) -> bool:
    """``--insertion-kernel auto``: pallas for chip-resident tails whose
    insertion-event count falls in the kernel's measured winning window;
    XLA scatter everywhere else (see the window constants above).  The
    env overrides are read per call so a tuned rig's values apply
    without import-order games."""
    lo = int(float(os.environ.get("S2C_PALLAS_INS_MIN_EVENTS",
                                  PALLAS_INS_MIN_EVENTS)))
    hi = int(float(os.environ.get("S2C_PALLAS_INS_MAX_EVENTS",
                                  PALLAS_INS_MAX_EVENTS)))
    return chip_tail and lo <= n_events <= hi


def _tail_cpu_wins(total_len: int, n_thresholds: int,
                   upload_bytes: int, native_tail: bool,
                   aligned_bases: int = 0) -> bool:
    """True when the local CPU tail beats shipping the tail to the chip.
    ``native_tail`` (from :func:`_native_tail_possible`) says which cpu
    implementation would actually execute, so the model prices that one.
    The chip's fetch is priced as the CHEAPEST modeled output encoding
    (dense / packed5 / sparse — mirroring the output-encoding gate, which
    would pick exactly that one), so tails near the crossover are not
    mis-routed to the cpu by a dense-only pessimistic bill (round-3
    advisor finding)."""
    forced = os.environ.get("S2C_TAIL_DEVICE", "")
    if forced not in ("", "auto"):
        if forced not in ("cpu", "default"):
            # ValueError: PASSTHROUGH to the resilience policy (config
            # typo, not a device failure)
            raise ValueError(
                f"S2C_TAIL_DEVICE={forced!r}: use 'cpu' (local XLA CPU "
                f"tail), 'default' (the accelerator), or 'auto'")
        obs.metrics().gauge("dispatch/tail").set_info(
            {"chosen": "cpu" if forced == "cpu" else "device",
             "forced": forced})
        obs.record_decision(
            "tail_placement", "cpu" if forced == "cpu" else "device",
            inputs={"forced": forced},
            measured={"sec": {"counters": ["phase/vote_sec"]}})
        return forced == "cpu"
    if native_tail:
        cpu_sec = total_len * (
            TAIL_NATIVE_NS_PER_POS
            + TAIL_NATIVE_THR_NS * (n_thresholds - 1)) * 1e-9
    else:
        cpu_sec = total_len * n_thresholds / TAIL_CPU_POS_PER_SEC
    rt_sec, link_bps = _link_constants()
    if aligned_bases > 0:
        from ..ops import fused as _fused

        sparse_cap = _fused.pad_cap(min(total_len, aligned_bases) + 1)
    else:
        sparse_cap = None
    fetch = min(_fetch_costs(total_len, n_thresholds, sparse_cap,
                             link_bps).values())
    chip_sec = rt_sec + upload_bytes / link_bps + fetch
    cpu_wins = cpu_sec < chip_sec
    # the placement model's verdict AND its inputs, as a structured
    # record: the gauge feeds the stats.extra compat view (bench util
    # block) and the tracer event lands in the exported trace, so a
    # mis-route is diagnosable from the artifact alone
    decision = {"chosen": "cpu" if cpu_wins else "device",
                "cpu_sec": round(cpu_sec, 6),
                "chip_sec": round(chip_sec, 6),
                "rt_sec": round(rt_sec, 6), "link_bps": int(link_bps),
                "upload_bytes": int(upload_bytes),
                "total_len": int(total_len),
                "n_thresholds": int(n_thresholds),
                "native_tail": bool(native_tail)}
    obs.metrics().gauge("dispatch/tail").set_info(decision)
    obs.tracer().event("dispatch/tail", **decision)
    # ledger: prediction for the CHOSEN side, both alternatives, and
    # the measured join against the vote window (the tail's wall-clock
    # — upload/fetch/dispatch all complete under its host fetches).
    # Last-wins dedupe makes the model's optimistic-then-exact double
    # call (_cpu_tail_wins) leave exactly the decisive record.
    obs.record_decision(
        "tail_placement", decision["chosen"], inputs=decision,
        predicted={"sec": cpu_sec if cpu_wins else chip_sec},
        alternatives={"cpu": cpu_sec, "device": chip_sec},
        measured={"sec": {"counters": ["phase/vote_sec"]}})
    return cpu_wins


def _fetch_costs(total_len: int, n_thresholds: int,
                 sparse_cap, link_bps: float) -> dict:
    """Modeled d2h time per output encoding — THE shared pricing for the
    output-encoding gate (which picks the cheapest key) and for
    ``_tail_cpu_wins`` (which bills the chip with the cheapest value):
    one source, so placement and encoding can never disagree.  Keys:
    ``None`` dense ASCII, ``"packed5"`` 5-bit planes, ``sparse_cap``
    (the pad_cap'd capacity, when given) emit-bitmask sparse."""
    nbits = (total_len + 7) // 8
    costs = {
        None: n_thresholds * total_len / link_bps,
        "packed5":
            n_thresholds * ((total_len + 1) // 2 + nbits) / link_bps
            + n_thresholds * total_len
            * (P5_HOST_NS_PER_CHAR + P5_DEV_NS_PER_CHAR) * 1e-9,
    }
    if sparse_cap is not None:
        costs[sparse_cap] = (
            (nbits + n_thresholds * sparse_cap) / link_bps
            + total_len * SPARSE_NS_PER_POS * 1e-9)
    return costs


#: modeled per-character host cost of the CLASSIC render epilogue
#: (fill-byte translate + dash count + bytes decode, ~3 passes over
#: T*L chars — bytes.translate measured 1.1 ns/char at 40 Mbp, the
#: memchr dash count 0.28, the latin-1 decode ~0.3; the native
#: s2c_finalize single pass lands near the low end)
EPILOGUE_HOST_NS = float(os.environ.get("S2C_EPILOGUE_HOST_NS", "1.0"))
#: per-character host cost left AFTER the device-resident epilogue
#: (tobytes + latin-1 decode only — fill substitution rode the vote's
#: emit select for free and dash totals arrive pre-reduced per
#: (threshold, contig))
EPILOGUE_DEV_NS = float(os.environ.get("S2C_EPILOGUE_DEV_NS", "0.4"))


def _donate_counts(tail_dev) -> bool:
    """Whether the fused tail's counts operand is DONATED to XLA
    (S2C_DONATE_COUNTS=auto|on|off).  Auto donates on real accelerators
    only: the XLA CPU runtime cannot reuse donated buffers (jax warns
    and ignores), and a tail committed to the local cpu device is the
    same runtime.  Donation is safe by construction at the call sites —
    the operand is always a dead temp (the HostPileupAccumulator's
    cached upload, invalidated right after so a retry re-uploads from
    the host master; or the device accumulator's fresh ``[:L]`` slice,
    whose padded master survives) — so warm serve jobs and packed
    batches reuse the count allocation instead of holding counts +
    packed output live across every tail."""
    mode = os.environ.get("S2C_DONATE_COUNTS", "auto")
    if mode == "off":
        return False
    if mode == "on":
        return True
    if mode != "auto":
        # config typo: PASSTHROUGH to the resilience policy, same
        # contract as the other env knobs validated in the tail
        raise ValueError(
            f"S2C_DONATE_COUNTS={mode!r}: use 'auto', 'on', or 'off'")
    import jax

    return tail_dev is None and jax.default_backend() != "cpu"


def _fused_tail_call(fn_plain, fn_donated, donate: bool, acc, counts_op,
                     *args):
    """Dispatch one fused-tail entry point, donated or not.

    When donating, the HostPileupAccumulator's cached upload is
    invalidated afterwards — the donated buffer is dead, and a cached
    reference to it would wedge any retry (the resilience policy
    re-runs the whole tail; the re-access re-uploads from the host
    master).  The device accumulator needs nothing: its operand is a
    fresh ``[:L]`` slice whose padded master survives.  The 'not
    usable' warning is filtered for the forced-on test path on cpu,
    where donation is a no-op."""
    if not donate:
        return fn_plain(counts_op, *args)
    import warnings

    from ..ops.pileup import HostPileupAccumulator

    try:
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            return fn_donated(counts_op, *args)
    finally:
        if isinstance(acc, HostPileupAccumulator):
            acc.invalidate_upload()


def _resolve_decode_threads(cfg) -> int:
    """--decode-threads policy; canonical home is config (shared with
    the BGZF inflate pool so format decode and fused decode size their
    worker pools identically)."""
    from ..config import resolve_decode_threads

    return resolve_decode_threads(cfg)


def _native_tail_possible(cfg, has_insertions: bool = True) -> bool:
    """True when a cpu-routed tail would actually run the native C++
    vote: the library loads and nothing forces the tail elsewhere — a
    forced S2C_TAIL_ENCODING runs the fused XLA wire path, S2C_TAIL_DEVICE
    =default pins the chip, and an explicit pallas insertion kernel
    keeps the device tail (irrelevant when the run produced no insertion
    events — pass ``has_insertions=False`` then, so a pallas request
    doesn't forfeit the fast native vote for nothing).  Gates both the
    host-pileup genome bound (ops.pileup.host_pileup_max_len) and the
    placement model's rate."""
    if os.environ.get("S2C_TAIL_ENCODING", "auto") != "auto":
        return False
    if os.environ.get("S2C_TAIL_DEVICE", "") == "default":
        return False
    if has_insertions and getattr(cfg, "ins_kernel", "scatter") == "pallas":
        return False
    from .. import native

    return native.load() is not None


def _timed_iter(it, key: str = "decode"):
    """Yield from ``it``, spanning each ``next`` and accumulating the
    time into the ``phase/<key>_sec`` metric."""
    reg = obs.metrics()
    tr = obs.tracer()
    while True:
        with tr.span(key):
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                return
            reg.add(f"phase/{key}_sec", time.perf_counter() - t0)
        yield batch


class _Prefetcher:
    """Bounded background decode: overlap host decode with pileup work.

    The producer thread drains the encoder generator (spanning its
    decode work into the run's tracer/metrics) into a depth-2 queue;
    the consumer iterates batches as they land.  Exceptions — including
    strict-mode decode errors (the oracle's KeyError/IndexError types),
    whose type/message parity with the serial path is contract — are
    re-raised in the consumer at the point of consumption.

    ``stager`` (wire/pipeline.StageSlots, optional) runs each batch's
    wire encode + h2d transfer on this thread through its two pinned
    slots.  The slot ACQUIRE (backpressure — really the consumer's
    dispatch time) happens outside the ``stage`` span/clock; only the
    encode+transfer work is billed to ``phase/stage_sec``.
    """

    _DONE = object()

    #: consecutive staging failures before the pipeline gives up for
    #: the rest of the run — a single transient blip (one injected RPC
    #: fault, one dropped tunnel packet) must not permanently serialize
    #: every remaining transfer when the very next slab would stage fine
    MAX_STAGE_FAILURES = 3

    def __init__(self, gen, depth: int = 2, stager=None):
        import queue
        import threading

        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._exc = None
        self._stager = stager
        self._stage_failures = 0       # consecutive; reset on success
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._work, args=(gen,), daemon=True)
        self._thread.start()

    def _put(self, item) -> bool:
        """Bounded put that aborts when the consumer called close()."""
        import queue

        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _work(self, gen) -> None:
        reg = obs.metrics()
        tr = obs.tracer()
        tr.name_thread("decode-prefetch")
        try:
            while True:
                with tr.span("decode"):
                    t0 = time.perf_counter()
                    try:
                        batch = next(gen)
                    except StopIteration:
                        break
                    reg.add("phase/decode_sec",
                            time.perf_counter() - t0)
                if (self._stager is not None
                        and self._stage_failures < self.MAX_STAGE_FAILURES
                        and self._stager.acquire(batch)):
                    # start this batch's h2d transfer now, overlapping the
                    # consumer's dispatch of the previous batch (the device
                    # pileup otherwise serializes transfer with dispatch on
                    # the link); timed separately from decode, and the slot
                    # acquire above is OUTSIDE the clock (it is consumer
                    # dispatch time).  Staging is an OPTIMIZATION, so a
                    # failure here must not kill the decode thread: the
                    # stager invalidates the batch's slot, the batch
                    # delivers unstaged, and the consumer's own encode +
                    # dispatch replays it under the retry policy — the
                    # layer equipped to handle it.  Staging re-arms on the
                    # next batch; only MAX_STAGE_FAILURES consecutive
                    # failures turn it off for the run.
                    with tr.span("stage"):
                        t0 = time.perf_counter()
                        try:
                            self._stager.run(batch)
                            self._stage_failures = 0
                        except Exception as exc:
                            self._stage_failures += 1
                            batch.staged.clear()
                            reg.add("resilience/stage_failures", 1)
                            tr.event(
                                "resilience/stage_failure",
                                error=f"{type(exc).__name__}: {exc}",
                                consecutive=self._stage_failures,
                                disabled=self._stage_failures
                                >= self.MAX_STAGE_FAILURES)
                        reg.add("phase/stage_sec",
                                time.perf_counter() - t0)
                if not self._put(batch):
                    return                 # consumer gone; drop the rest
        except BaseException as exc:  # re-raised on the consumer side
            self._exc = exc
        self._put(self._DONE)

    def close(self) -> None:
        """Unblock and join the producer (consumer exited early)."""
        import queue

        self._stop.set()
        while self._thread.is_alive():
            try:
                self._q.get_nowait()
            except queue.Empty:
                self._thread.join(timeout=0.05)
        self._thread.join()

    def __iter__(self):
        while True:
            batch = self._q.get()
            if batch is self._DONE:
                self._thread.join()
                if self._exc is not None:
                    raise self._exc
                return
            yield batch


class JaxBackend:
    name = "jax"

    def run(self, contigs: List[Contig], records: Iterable[SamRecord],
            cfg: RunConfig) -> BackendResult:
        """Wrap one pipeline run in a fresh tracer + metrics registry
        (per-run, so the bench's warm/timed repetitions never bleed into
        each other), then derive the legacy ``stats.extra`` keys from
        the registry and write any requested exports.  The fault
        injector (resilience/faultinject.py) configures here too, so
        its per-site call counters are per-run-deterministic.

        Serve mode (sam2consensus_tpu/serve) pre-creates a job's
        instruments (``observability.prepare_run``) so the decode-ahead
        thread can record into them before the run starts; it hands the
        handle over via the ``serve_prepared_obs`` attribute, consumed
        (and cleared) here."""
        from ..resilience import faultinject

        prepared = getattr(self, "serve_prepared_obs", None)
        if prepared is not None:
            self.serve_prepared_obs = None
        robs = obs.start_run(
            trace_out=getattr(cfg, "trace_out", None),
            metrics_out=getattr(cfg, "metrics_out", None),
            config=cfg, prepared=prepared)
        faultinject.configure(getattr(cfg, "fault_inject", "") or None)
        try:
            result = self._run(contigs, records, cfg)
            # end-of-run watermark sample (observability/memplane.py):
            # the run's registry carries its RSS/device peaks into the
            # manifest + bench rows alongside the per-family gauges
            memplane.sample()
            # join the run's decision ledger against its measured
            # counters BEFORE deriving the compat view, so residual/*
            # and drift/* reach stats.extra (and the bench rows)
            obs.finalize_decisions()
            obs.publish_stats_extra(result.stats.extra)
            return result
        except BaseException as exc:
            from ..ingest.badrecords import (BadRecordBudgetExceeded,
                                             abort_bookkeeping)

            if isinstance(exc, BadRecordBudgetExceeded):
                # rotten input (--max-bad-records blown mid-decode, on
                # whichever rung/thread): leave the evidence — sidecar
                # + counters — before the typed failure propagates
                abort_bookkeeping(exc, obs.metrics())
            # OOM forensics: a CAPACITY-class escape writes
            # mem_dump.json next to the run's metrics artifact (the
            # manifest's home — one-shot runs without --metrics-out
            # have no durable home and skip; the serve runner dumps
            # next to its journal for those)
            if robs.metrics_out:
                memplane.dump_on_capacity(
                    exc, os.path.dirname(os.path.abspath(
                        robs.metrics_out)),
                    registry=robs.registry,
                    context={"backend": self.name})
            raise
        finally:
            faultinject.configure("")
            obs.finish_run(robs, meta={"backend": self.name})

    def _run(self, contigs: List[Contig], records: Iterable[SamRecord],
             cfg: RunConfig) -> BackendResult:
        # jax imports deferred so `--backend cpu` never pays them
        import jax
        import jax.numpy as jnp

        from ..encoder.events import GenomeLayout
        from ..ops.pileup import (HostPileupAccumulator, PileupAccumulator,
                                  host_pileup_max_len)

        from ..io.sam import ReadStream

        stats = BackendStats()
        tr = obs.tracer()
        reg = obs.metrics()
        layout = GenomeLayout(contigs)
        if layout.total_len == 0:
            return BackendResult(fastas={}, stats=stats)

        # run-level row wire codec (sam2consensus_tpu/wire): explicit
        # --wire wins; auto prices the SAME link constants the tail
        # placement model uses, so wire compression and tail routing
        # can never disagree about how fast the link is.  A link-free
        # default backend ships packed5 — the "saved" wire would be a
        # memcpy while the encode/decode passes stay real.
        from ..wire import resolve_codec

        _wire_link_free = jax.default_backend() == "cpu"
        wire_mode = getattr(cfg, "wire", "auto")
        _wire_bps = None
        if wire_mode == "auto" and not _wire_link_free:
            _rt_unused, _wire_bps = _link_constants()
        wire_sel, wire_reason = resolve_codec(
            wire_mode, _wire_bps, link_free=_wire_link_free)
        winfo = {"requested": wire_mode, "chosen": wire_sel,
                 "reason": wire_reason}
        if _wire_bps is not None:
            winfo["link_bps"] = int(_wire_bps)
        reg.gauge("wire/codec").set_info(winfo)
        tr.event("wire/codec", **winfo)
        # ledger: the codec's modeled compression ratio vs the measured
        # wire/raw_bytes / wire/bytes ratio — a delta8 run whose slabs
        # keep falling back (escape-dense input) shows residual << 1
        from ..wire.codec import modeled_wire_ratio

        # predicted bps optionally sourced from the learned card: the
        # card's wire_bps is the EWMA of ACHIEVED rates on this host,
        # a tighter prediction than the raw link constant once it has
        # samples (codec ROUTING stays on the link constants — the
        # card refines the prediction, not the choice)
        from ..observability import ratecard as _rc

        _pred_bps, _wire_rc_prov = (
            _rc.consult("wire_bps", _wire_bps)
            if _wire_bps is not None else (None, None))
        obs.record_decision(
            "wire_codec", wire_sel, inputs=winfo,
            predicted={"ratio": modeled_wire_ratio(wire_sel),
                       **({"bps": _pred_bps}
                          if _pred_bps is not None else {})},
            measured={"ratio": {"num": ["wire/raw_bytes"],
                                "den": ["wire/bytes"]},
                      "bps": {"num": ["wire/bytes"],
                              "den": ["phase/stage_sec",
                                      "phase/pileup_dispatch_sec"],
                              "min_num": _drift_min_wire_bytes()}},
            provenance=_wire_rc_prov)

        n_dev = len(jax.devices())
        # typed up-front capacity check (parallel.mesh): an explicit
        # --shards over the runtime's devices fails HERE, before any
        # decode or compile — MeshCapacityError, not a late XLA error
        from ..parallel.mesh import validate_shards

        validate_shards(cfg.shards, n_available=n_dev)
        shards = cfg.shards if cfg.shards > 0 else n_dev
        if getattr(cfg, "pileup", "auto") == "host" and cfg.shards == 0:
            # host pileup implies single-device: an unspecified --shards
            # (0 = all devices) must not turn the explicit host strategy
            # into an error on multi-device hosts; explicit --shards N>1
            # still conflicts below
            shards = 1
        use_sharded = shards > 1

        if use_sharded:
            if getattr(cfg, "pileup", "auto") == "host":
                raise RuntimeError(
                    "--pileup host is a single-device strategy (the count "
                    "tensor accumulates on the host); drop --shards or "
                    "pick a device pileup strategy")
            # construction is DEFERRED to the first decoded batch: the
            # sp/dpsp halo is sized from the run's observed widest row
            # bucket (verdict r4 #5) and --shard-mode auto picks its
            # layout from the first slab's shape (verdict r4 #3) — see
            # _build_sharded_acc below
            acc = None
        else:
            strategy = getattr(cfg, "pileup", "auto")
            _link_free = jax.default_backend() == "cpu"
            _native_ok = _native_tail_possible(cfg)
            if strategy == "host" or (
                    strategy == "auto"
                    and layout.total_len <= host_pileup_max_len(
                        _native_ok,
                        link_free=_link_free,
                        # only pay the startup probe when the bound
                        # would actually consult the link rate
                        link_bps=_measured_link_bps()
                        if _native_ok and not _link_free else None)):
                # wire-cost policy, measured on the tunneled chip: see
                # HostPileupAccumulator's docstring and
                # ops.pileup.host_pileup_max_len (the bound widens when
                # the native tail vote makes host runs link-free, and
                # vanishes when the probed link is tunnel-class slow)
                acc = HostPileupAccumulator(layout.total_len)
                reg.gauge("dispatch/pileup").set_info(
                    {"path": "host", "strategy": strategy,
                     "total_len": int(layout.total_len),
                     "native_tail": bool(_native_ok),
                     "link_free": bool(_link_free)})
            else:
                acc = PileupAccumulator(layout.total_len, strategy=strategy,
                                        wire=wire_sel)
                reg.gauge("dispatch/pileup").set_info(
                    {"path": "device", "strategy": strategy,
                     "wire": wire_sel,
                     "total_len": int(layout.total_len)})

        # capacity: the run's predicted peak host+device bytes as a
        # priced ledger decision (observability/memplane.py), joined
        # against the measured mem/peak_tracked_bytes ratchet at
        # finalize — the same model serve admission sheds against
        memplane.record_capacity(
            layout.total_len, n_thresholds=len(cfg.thresholds),
            chunk_reads=cfg.chunk_reads, shards=shards,
            segment_width=max(0, getattr(cfg, "segment_width", 0)))

        # checkpoint resume: counts + insertion log + consumed-line offset
        # are the entire job state (SURVEY.md §5)
        ck = None
        skip_input = False
        prior_sources: List[str] = []
        incremental = getattr(cfg, "incremental", False)
        source_id = getattr(cfg, "source_id", "")
        if incremental and not source_id:
            raise RuntimeError(
                "incremental mode needs a non-empty source_id identifying "
                "the input (the CLI passes the input file's absolute path)")
        # serve count cache (serve/countcache.py): the runner seeds the
        # job with a warm per-reference CheckpointState — the SAME
        # sum-decomposable state the checkpoint subsystem proves
        # resumable, promoted from crash recovery to the warm serving
        # path.  Consumed (and cleared) here, mirroring
        # serve_prepared_obs; the runner also sets
        # ``serve_capture_counts`` so the final state is handed back
        # for re-insertion (below).
        count_seed = getattr(self, "serve_count_seed", None)
        if count_seed is not None:
            self.serve_count_seed = None
            if cfg.checkpoint_dir:
                raise RuntimeError(
                    "count-cache seeding does not compose with "
                    "--checkpoint-dir (two sources of resumable state)")
        if cfg.checkpoint_dir:
            from ..utils import checkpoint as ckpt

            if not isinstance(records, ReadStream):
                raise RuntimeError(
                    "--checkpoint-dir requires a file-backed SAM input "
                    "stream (BAM inputs do not support checkpoint resume "
                    "yet — convert to SAM/SAM.gz or drop the checkpoint)")
            ck = ckpt.load(cfg.checkpoint_dir, layout.total_len)
            if ck is not None:
                # three incremental cases (SURVEY.md §5 "incremental
                # updates"), resolved by the checkpoint's source identity:
                # * listed in ck.sources -> this input is already fully
                #   absorbed: add nothing (idempotent re-run);
                # * ck.source (in-flight) -> crashed mid-input: resume by
                #   skipping its consumed lines;
                # * otherwise -> NEW shard on the accumulated base: start
                #   from line 0.
                # Without --incremental the checkpoint always refers to
                # the current input: plain resume.
                prior_sources = list(ck.sources or [])
                if incremental and source_id != ck.source \
                        and ck.lines_consumed > 0 and ck.source \
                        and ck.source not in prior_sources:
                    # the checkpoint holds a PARTIAL prefix of a crashed
                    # shard; any run other than resuming that shard (a new
                    # shard, or a no-op duplicate whose final write would
                    # reset source/lines_consumed) would bake the prefix in
                    # untracked, and a later rerun of the crashed shard
                    # would then double-count it
                    raise RuntimeError(
                        f"checkpoint contains a partially absorbed input "
                        f"{ck.source!r} (crashed mid-shard); rerun that "
                        f"input to completion before adding "
                        f"{source_id!r}, or delete the checkpoint")
                if incremental and source_id in prior_sources:
                    skip_input = True
                    stats.extra["incremental_duplicate"] = source_id
                elif not incremental or source_id == ck.source:
                    stats.extra["resume_mode"] = records.skip_to(
                        ck.byte_offset, ck.lines_consumed)
                else:
                    stats.extra["incremental_base"] = prior_sources
                if not use_sharded:
                    acc.set_counts(ck.counts)
                # sharded: restored inside _build_sharded_acc (the
                # accumulator does not exist until the first batch)
        elif count_seed is not None:
            # warm-reference seed: every cached input is FULLY absorbed
            # (ck.source is never set mid-input), so only two of the
            # checkpoint's three incremental cases exist — duplicate
            # input (idempotent no-op) or a new shard on the warm base
            ck = count_seed
            prior_sources = list(ck.sources or [])
            if incremental and source_id in prior_sources:
                skip_input = True
                stats.extra["incremental_duplicate"] = source_id
            else:
                stats.extra["incremental_base"] = prior_sources
            if not use_sharded:
                acc.set_counts(ck.counts)
        base_mapped = ck.reads_mapped if ck else 0
        base_skipped = ck.reads_skipped if ck else 0
        base_aligned = ck.aligned_bases if ck else 0

        # host decode: native C++ text path when a ReadStream is available
        # (SURVEY.md §2b native component), python record path otherwise
        encoder, batches = self._make_encoder(layout, records, cfg, acc)
        if skip_input:
            # already-absorbed shard: decode nothing (its contribution is in
            # the checkpointed counts; re-reading it would double-count)
            batches = iter(())
            if getattr(records, "is_predecoded", False):
                # serve decode-ahead already decoded this input into the
                # encoder (reads counted, insertions logged) before the
                # duplicate verdict existed — a duplicate contributes
                # NOTHING, so swap in an empty stand-in (the batches
                # were never accumulated; only the encoder's event log
                # and read counters would leak through)
                from ..encoder.events import ReadEncoder

                encoder = ReadEncoder(layout)
        if ck is not None:
            encoder.insertions.array_chunks.extend(ck.insertions.array_chunks)
        stats.aligned_bases = base_aligned

        t0 = time.perf_counter()
        reads_at_ckpt = 0
        max_row_width = ck.max_row_width if ck else 0
        src = iter(batches)
        if use_sharded and acc is None:
            # decode ONE batch first: its bucket widths size the sp/dpsp
            # halo and its slab shape feeds the auto-mode model
            td = time.perf_counter()
            first_batch = next(src, None)
            reg.add("phase/decode_sec", time.perf_counter() - td)
            tr.complete("decode", td)
            acc = self._build_sharded_acc(cfg, layout, shards, first_batch,
                                          max_row_width, stats,
                                          wire=wire_sel)
            if ck is not None:
                acc.restore(ck.counts)
            if first_batch is not None:
                from itertools import chain

                src = chain([first_batch], src)
        if cfg.checkpoint_dir or getattr(encoder, "counts_fused", False):
            # serial decode, two reasons share the branch:
            # - checkpointing must snapshot stream/encoder state
            #   consistent with the batches already committed to the
            #   counts, which a decode thread running ahead would break;
            # - fused host counting makes the consumer loop stats-only
            #   (counts land inside the decode pass, acc.add is a no-op),
            #   so a prefetch thread buys zero overlap while its spawn
            #   costs ~6 ms — the entire fixed budget of a small-input
            #   run (measured: phix 14.6 -> ~9 ms)
            batch_iter = _timed_iter(src)
            stager = None
        else:
            # overlap host decode with pileup work (SURVEY.md §7(d)): a
            # bounded prefetch thread decodes the next slabs while this
            # thread feeds the accumulator (ctypes/C++ decode releases the
            # GIL, so the overlap is real).  Accumulators exposing
            # ``stage`` additionally get their wire encode + h2d
            # transfers issued from the prefetch thread through TWO
            # pinned staging slots (wire/pipeline.StageSlots): slab N+1
            # encodes and transfers while slab N accumulates, with
            # backpressure when both slots are in flight, and the
            # stage/accumulate overlap measured into
            # ``pipeline/overlap_sec`` — except under --paranoid, whose
            # contract is that batches are re-validated BEFORE anything
            # ships to the device.
            stage_fn = None if cfg.paranoid else getattr(acc, "stage",
                                                         None)
            stager = None
            if stage_fn is not None:
                from ..wire.pipeline import StageSlots

                stager = StageSlots(stage_fn)
            batch_iter = _Prefetcher(src, stager=stager)

        # the accumulate loop's failure contract (resilience/): every
        # device dispatch runs under the retry policy; persistent
        # failures step down the degradation ladder (kernel -> scatter
        # -> host pileup) under --on-device-error fallback, replaying
        # the failed slab on the demoted path and writing an emergency
        # checkpoint at the demotion boundary
        from ..resilience import ladder as rladder
        from ..resilience.policy import RetryPolicy

        policy = RetryPolicy.from_config(cfg)

        def _emergency_ckpt(acc_):
            # only ever called with cfg.checkpoint_dir set, which forces
            # SERIAL decode above — so stream.n_lines is exactly the
            # consumed batch boundary.  A prefetching run would have the
            # decode thread up to queue-depth batches ahead of the
            # consumer, and a checkpoint taken then would resume past
            # decoded-but-unaccumulated batches (silent count loss).
            self._write_checkpoint(cfg, records, acc_, encoder, stats,
                                   base_mapped, base_skipped,
                                   prior_sources, max_row_width)

        def _rebind_stage(acc_):
            # a demoted accumulator must also re-route (or drop) the
            # prefetch thread's device staging — the old accumulator's
            # stage() would keep shipping batches to the failing device.
            # The stager rebinds in place (its slots and overlap log
            # survive the demotion).
            if stager is not None:
                stager.stage_fn = None if cfg.paranoid \
                    else getattr(acc_, "stage", None)

        dispatcher = rladder.ResilientDispatcher(
            policy, layout.total_len,
            checkpoint_cb=_emergency_ckpt if cfg.checkpoint_dir else None,
            on_demote=_rebind_stage)
        # serve mode: the runner plants a list here so it can intersect
        # THIS job's device-dispatch intervals with the NEXT job's
        # decode-ahead intervals (the cross-job serve/overlap_sec)
        dispatch_log = getattr(self, "serve_dispatch_log", None)
        try:
            for batch in batch_iter:
                if cfg.paranoid:
                    self._paranoid_batch(batch, layout.total_len, stats)
                if batch.buckets:
                    max_row_width = max(max_row_width,
                                        max(batch.buckets))
                ta = time.perf_counter()
                with tr.span("pileup_dispatch", n_events=batch.n_events):
                    acc = dispatcher.add(acc, batch)
                tb = time.perf_counter()
                reg.add("phase/pileup_dispatch_sec", tb - ta)
                if dispatch_log is not None:
                    dispatch_log.append((ta, tb))
                if stager is not None:
                    # release this batch's staging slot (backpressure
                    # window moves to the next slab) and log the
                    # dispatch interval for the overlap measurement
                    stager.note_consume(ta, time.perf_counter())
                    stager.consumed(batch)
                stats.aligned_bases += batch.n_events
                if (cfg.checkpoint_dir
                        and encoder.n_reads - reads_at_ckpt
                        >= cfg.checkpoint_every):
                    self._write_checkpoint(cfg, records, acc, encoder,
                                           stats, base_mapped, base_skipped,
                                           prior_sources, max_row_width)
                    reads_at_ckpt = encoder.n_reads
        finally:
            # consumer-side failure (paranoid reject, device error) must not
            # leave the decode thread blocked on a full queue (or a
            # backpressured staging slot) holding the input stream open
            if stager is not None:
                stager.close()
            if isinstance(batch_iter, _Prefetcher):
                batch_iter.close()
        if stager is not None:
            # the pipeline's measured story: how much of the staging
            # thread's encode+transfer work ran UNDER the consumer's
            # dispatch windows (a serialized pipeline reports ~0)
            ov = stager.overlap_sec()
            ssec = stager.stage_sec()
            reg.add("pipeline/overlap_sec", ov)
            reg.add("pipeline/backpressure_sec", stager.backpressure_sec)
            reg.gauge("pipeline/overlap").set_info({
                "overlap_sec": round(ov, 4),
                "stage_sec": round(ssec, 4),
                "slots": stager.slots,
                "staged_batches": stager.staged_batches,
                "overlap_frac": round(ov / ssec, 3) if ssec > 0 else 0.0})
        if dispatcher.demotions:
            # the ladder may have landed the run on a different rung
            # (scatter-pinned device acc, or the host accumulator): the
            # tail must follow the accumulator it actually has
            stats.extra["pileup_ladder"] = rladder.pileup_level(acc)
            use_sharded = use_sharded and not isinstance(
                acc, HostPileupAccumulator)
        stats.reads_mapped = base_mapped + encoder.n_reads
        stats.reads_skipped = base_skipped + encoder.n_skipped
        reg.add("reads/mapped", encoder.n_reads)
        reg.add("reads/skipped", encoder.n_skipped)
        reg.add("pileup/cells", stats.aligned_bases - base_aligned)
        bad_sink = getattr(encoder, "bad_sink", None)
        if bad_sink is not None:
            # decode is complete: enforce the percent budget against the
            # real record total, write the quarantine sidecar, publish
            # the ingest/bad_records + quarantine/* counters.  A blown
            # budget raises the typed DATA-class failure HERE — before
            # any tail work — and run()'s abort bookkeeping finalizes
            # the evidence.
            total = int(getattr(records, "n_lines", 0) or 0)
            if total <= 0:
                total = encoder.n_reads + encoder.n_skipped
            summary = bad_sink.finish(total)
            bad_sink.publish(reg)
            if summary["bad_records"]:
                stats.extra["bad_records"] = summary["bad_records"]
                if summary.get("sidecar"):
                    stats.extra["quarantine_sidecar"] = summary["sidecar"]
        stats.extra["shards"] = shards if use_sharded else 1
        stats.extra["decoder"] = encoder.__class__.__name__
        if getattr(acc, "strategy_used", None):
            stats.extra["pileup"] = dict(acc.strategy_used)
        if ((os.environ.get("S2C_SYNC_ACCUMULATE") == "1" or tr.enabled)
                and hasattr(acc, "sync")):
            # opt-in (bench forced-device rows) — and whenever tracing is
            # on, so the accumulate span closes under a device barrier:
            # device scatters are async — without this the accumulate
            # window ends with the dispatch queue still draining and the
            # drain is billed to the tail's first fetch, so the chip's
            # cell rate is not attributable to any one phase
            with tr.span("accumulate_sync"):
                acc.sync()
            stats.extra["accumulate_synced"] = True
        reg.add("phase/accumulate_sec", time.perf_counter() - t0)
        tr.complete("accumulate", t0)
        if ck is not None and "incremental_base" not in stats.extra:
            stats.extra["resumed_from_line"] = ck.lines_consumed

        # Post-accumulation tail + render: shared with the serve batch
        # scheduler's per-job extraction path (run_from_counts), so the
        # packed and cold tails are ONE code path by construction.
        fastas, acc = self._finish_consensus(
            acc, cfg, layout, encoder, stats, use_sharded, policy,
            ckpt_cb=_emergency_ckpt if cfg.checkpoint_dir else None)

        if getattr(self, "serve_capture_counts", False) and skip_input:
            # duplicate input: the job absorbed nothing, so the seed IS
            # the final state — hand it straight back instead of
            # rebuilding a byte-identical entry via a full counts_host
            # pull (on a real accelerator that pull is the whole L*6
            # tensor over the link, for nothing)
            self.serve_capture_counts = False
            self.serve_count_result = ck
        if getattr(self, "serve_capture_counts", False):
            # hand the job's final count state back to the serve count
            # cache (runner-side put happens only after the job commits
            # — the count-bank rule: a failed job inserts nothing)
            self.serve_capture_counts = False
            from ..encoder.events import InsertionEvents
            from ..utils import checkpoint as ckpt

            merge = getattr(encoder, "merge_shadow", None)
            if merge is not None:
                merge()
            done = list(prior_sources)
            if source_id and source_id not in done:
                done.append(source_id)
            ic, il, im, ich = encoder.insertions.to_arrays()
            ins_ev = InsertionEvents()
            ins_ev.array_chunks.append(
                (ic.astype(np.int32), il.astype(np.int32),
                 im.astype(np.int32), ich))
            self.serve_count_result = ckpt.CheckpointState(
                counts=acc.counts_host(),
                lines_consumed=0,
                reads_mapped=stats.reads_mapped,
                reads_skipped=stats.reads_skipped,
                aligned_bases=stats.aligned_bases,
                insertions=ins_ev,
                source="", sources=done,
                byte_offset=-1, max_row_width=max_row_width)

        if cfg.checkpoint_dir:
            from ..utils import checkpoint as ckpt

            if getattr(cfg, "incremental", False):
                # incremental: the checkpoint IS the accumulated base for
                # the next shard — persist the final state, and record this
                # input as FULLY absorbed so a later rerun of it (even with
                # other shards in between) adds nothing
                done = list(prior_sources)
                if source_id and source_id not in done:
                    done.append(source_id)
                self._write_checkpoint(cfg, records, acc, encoder, stats,
                                       base_mapped, base_skipped, done,
                                       max_row_width)
            else:
                # a completed run invalidates its checkpoint: remove it so
                # a rerun starts from scratch, not replaying a finished job
                p = ckpt.path_for(cfg.checkpoint_dir)
                if os.path.exists(p):
                    os.unlink(p)
        return BackendResult(fastas=fastas, stats=stats)

    # -- shared tail + render (cold run AND packed extraction) -------------
    def _finish_consensus(self, acc, cfg: RunConfig, layout, encoder,
                          stats, use_sharded: bool, policy,
                          ckpt_cb=None):
        """Post-accumulation tail in ONE device round trip, then render;
        returns ``(fastas, acc)`` (``acc`` may have been tail-demoted).

        The tail is a pure function of the accumulated counts, so the
        retry policy can recompute it whole on a transient device
        failure; a persistent failure demotes it host-side
        (resilience/ladder: emergency checkpoint via ``ckpt_cb`` first,
        then cpu-committed counts and the link-free tail), with
        injection suppressed on the demoted attempt — the host rung is
        the ladder's bottom.  Shared by ``_run`` and
        :meth:`run_from_counts` (the serve batch scheduler's per-job
        extraction), so a packed job's consensus is byte-identical to a
        cold run's by construction, not by parallel maintenance."""
        from ..resilience import ladder as rladder

        tr = obs.tracer()
        reg = obs.metrics()
        demoted_tail = False
        while True:
            try:
                (syms, ins_syms, contig_sums, site_cov, ins, out,
                 link_free, dash_counts) = policy.run(
                    lambda: self._tail(acc, cfg, layout, encoder, stats,
                                       use_sharded,
                                       suppress_faults=demoted_tail),
                    site="tail")
                break
            except BaseException as exc:
                from ..resilience.policy import (DATA, PASSTHROUGH,
                                                 classify)

                if (demoted_tail
                        or classify(exc) in (PASSTHROUGH, DATA)
                        or policy.on_error != "fallback"):
                    raise
                acc = rladder.demote_tail_and_record(
                    acc, layout.total_len, exc, checkpoint_cb=ckpt_cb)
                use_sharded = False
                demoted_tail = True
        # wire accounting (bench utilization rows): BOTH directions now
        # mirror the registry's choke points — h2d billed per upload at
        # wire.account_h2d (staged slabs, kernel plans, counts uploads,
        # prewarm compiles), d2h per fetch at wire.account_d2h — so
        # stats.extra reads the ledger instead of re-summing
        # per-accumulator attributes, and no route can escape either
        # direction's measurement.
        stats.extra["h2d_bytes"] = int(reg.value("wire/h2d_bytes"))
        stats.extra["d2h_bytes"] = int(reg.value("wire/d2h_bytes"))
        if getattr(acc, "strategy_used", None):
            # refresh: the host-counts path records its wire dtype at upload
            stats.extra["pileup"] = dict(acc.strategy_used)
        if cfg.paranoid:
            self._paranoid_result(acc, contig_sums, layout, stats,
                                  ins=ins, site_cov=site_cov)

        t0 = time.perf_counter()
        with tr.span("render"):
            fastas = self._assemble(layout, syms, contig_sums, ins,
                                    ins_syms, site_cov, cfg, stats,
                                    dash_counts=dash_counts)
        reg.add("phase/render_sec", time.perf_counter() - t0)
        return fastas, acc

    # -- packed-batch extraction (serve/scheduler.py) ----------------------
    def run_from_counts(self, contigs: List[Contig], cfg: RunConfig,
                        counts, insertions=None, n_reads: int = 0,
                        n_skipped: int = 0,
                        aligned_bases: int = 0) -> BackendResult:
        """Consensus from an externally accumulated count partition.

        The serve batch scheduler packs N small jobs' segment rows into
        one shared count tensor (serve/packing.py — pileup addition
        commutes, so each job's extracted slice is bit-for-bit the
        tensor its own accumulation would have produced) and then calls
        this per job: the SAME tail + render path a cold run takes
        (:meth:`_finish_consensus`), over a
        :class:`~..ops.pileup.HostPileupAccumulator` seeded with the
        partition, so per-job byte identity is structural.  ``counts``
        is the job's ``[total_len, 6]`` int32 partition; ``insertions``
        the job's own :class:`~..encoder.events.InsertionEvents` (never
        packed — insertion keys are (contig, local) and stay per-job).

        Run lifecycle mirrors :meth:`run`: fresh (or serve-prepared)
        instruments, fault-injector configuration, decision finalize,
        ``stats.extra`` compat view — so a packed job's manifest and
        metrics look exactly like any other job's.  ``checkpoint_dir``
        is deliberately ignored: a packed member's replay unit is the
        whole (small) job, journaled at the serve layer."""
        from ..resilience import faultinject

        prepared = getattr(self, "serve_prepared_obs", None)
        if prepared is not None:
            self.serve_prepared_obs = None
        robs = obs.start_run(
            trace_out=getattr(cfg, "trace_out", None),
            metrics_out=getattr(cfg, "metrics_out", None),
            config=cfg, prepared=prepared)
        faultinject.configure(getattr(cfg, "fault_inject", "") or None)
        try:
            result = self._run_from_counts(contigs, cfg, counts,
                                           insertions, n_reads,
                                           n_skipped, aligned_bases)
            obs.finalize_decisions()
            obs.publish_stats_extra(result.stats.extra)
            return result
        finally:
            faultinject.configure("")
            obs.finish_run(robs, meta={"backend": self.name,
                                       "mode": "packed"})

    def assemble_partition(self, contigs: List[Contig], cfg: RunConfig,
                           syms, contig_sums, ins, ins_syms, site_cov,
                           n_reads: int = 0, n_skipped: int = 0,
                           aligned_bases: int = 0,
                           dash_counts=None) -> BackendResult:
        """Render one packed member's slice of a SHARED tail.

        The serve batch scheduler may run the post-accumulation tail
        ONCE over the whole packed batch (the vote is per-position and
        insertion sites are keyed (contig, local), so a member's slice
        of the combined outputs is bit-for-bit what its own tail would
        have produced — serve/scheduler.py documents the slicing); this
        entry point is the member's render-only run: same instruments
        lifecycle as any job (prepared-obs handoff, decision finalize,
        stats compat view, manifest), with ``_assemble`` the one shared
        render path."""
        robs = obs.start_run(
            trace_out=getattr(cfg, "trace_out", None),
            metrics_out=getattr(cfg, "metrics_out", None),
            config=cfg,
            prepared=getattr(self, "serve_prepared_obs", None))
        self.serve_prepared_obs = None
        try:
            from ..encoder.events import GenomeLayout

            stats = BackendStats()
            reg = obs.metrics()
            tr = obs.tracer()
            layout = GenomeLayout(contigs)
            stats.reads_mapped = int(n_reads)
            stats.reads_skipped = int(n_skipped)
            stats.aligned_bases = int(aligned_bases)
            reg.add("reads/mapped", int(n_reads))
            reg.add("reads/skipped", int(n_skipped))
            reg.add("pileup/cells", int(aligned_bases))
            reg.gauge("dispatch/pileup").set_info(
                {"path": "packed", "strategy": "shared_tail",
                 "total_len": int(layout.total_len)})
            stats.extra["decoder"] = "packed"
            stats.extra["shards"] = 1
            t0 = time.perf_counter()
            with tr.span("render"):
                fastas = self._assemble(layout, syms, contig_sums, ins,
                                        ins_syms, site_cov, cfg, stats,
                                        dash_counts=dash_counts)
            reg.add("phase/render_sec", time.perf_counter() - t0)
            result = BackendResult(fastas=fastas, stats=stats)
            obs.finalize_decisions()
            obs.publish_stats_extra(result.stats.extra)
            return result
        finally:
            obs.finish_run(robs, meta={"backend": self.name,
                                       "mode": "packed"})

    def _run_from_counts(self, contigs, cfg, counts, insertions,
                         n_reads, n_skipped, aligned_bases
                         ) -> BackendResult:
        from ..encoder.events import GenomeLayout, InsertionEvents
        from ..ops.pileup import HostPileupAccumulator
        from ..resilience.policy import RetryPolicy

        stats = BackendStats()
        reg = obs.metrics()
        layout = GenomeLayout(contigs)
        if layout.total_len == 0:
            return BackendResult(fastas={}, stats=stats)
        stats.reads_mapped = int(n_reads)
        stats.reads_skipped = int(n_skipped)
        stats.aligned_bases = int(aligned_bases)
        reg.add("reads/mapped", int(n_reads))
        reg.add("reads/skipped", int(n_skipped))
        reg.add("pileup/cells", int(aligned_bases))
        acc = HostPileupAccumulator(layout.total_len)
        acc.set_counts(counts)
        reg.gauge("dispatch/pileup").set_info(
            {"path": "packed", "strategy": "extracted",
             "total_len": int(layout.total_len)})
        stats.extra["decoder"] = "packed"
        stats.extra["shards"] = 1

        class _Carrier:
            """Insertion-events holder standing in for the encoder the
            tail reads (``_tail_attempt`` touches only ``.insertions``)."""

        carrier = _Carrier()
        carrier.insertions = insertions if insertions is not None \
            else InsertionEvents()
        policy = RetryPolicy.from_config(cfg)
        fastas, acc = self._finish_consensus(
            acc, cfg, layout, carrier, stats, use_sharded=False,
            policy=policy)
        return BackendResult(fastas=fastas, stats=stats)

    # -- post-accumulation tail (resilient) --------------------------------
    def _tail(self, acc, cfg: RunConfig, layout, encoder, stats,
              use_sharded: bool, suppress_faults: bool = False):
        """One attempt of the post-accumulation tail; returns
        ``(syms, ins_syms, contig_sums, site_cov, ins, out, link_free)``.

        Pure with respect to the accumulated counts (it mutates nothing
        a subsequent attempt reads), which is what makes the resilience
        layer's retry/demote loop in ``_run`` sound: a transient device
        failure recomputes the whole tail, a ladder demotion re-runs it
        against host-committed counts.  ``suppress_faults`` exempts the
        demoted attempt from fault injection (the host rung is the
        ladder's bottom; resilience/faultinject.py)."""
        from ..resilience import faultinject

        if suppress_faults:
            with faultinject.suppress():
                return self._tail_attempt(acc, cfg, layout, encoder,
                                          stats, use_sharded)
        return self._tail_attempt(acc, cfg, layout, encoder, stats,
                                  use_sharded)

    def _tail_attempt(self, acc, cfg: RunConfig, layout, encoder, stats,
                      use_sharded: bool):
        import jax
        import jax.numpy as jnp

        from ..encoder.events import group_insertions
        from ..ops import fused
        from ..ops.cutoff import encode_thresholds
        from ..ops.insertions import build_insertion_table, vote_insertions
        from ..ops.pileup import HostPileupAccumulator
        from ..resilience.faultinject import fault_check

        tr = obs.tracer()
        reg = obs.metrics()
        # Post-accumulation tail in ONE device round trip (a dispatch→fetch
        # costs ~65 ms on the tunneled chip and the link moves ~40 MB/s —
        # tools/tunnel_probe.py): the host groups insertion events, then a
        # single fused dispatch computes vote + insertion table + insertion
        # vote + per-contig coverage sums + per-site coverage, returning one
        # packed uint8 buffer.  Nothing depends on max(cov) because the
        # threshold cutoffs are computed exactly on device (ops/cutoff.py).
        t0 = time.perf_counter()
        # Per-position coverage always fits int32 (the count lanes are
        # int32), but GLOBAL coverage sums can overflow the device-side
        # int32 cumsum once total aligned bases pass 2^31.  The fused
        # tail's site coverage is a per-position gather (safe); only the
        # per-contig sums need the round-2 style full-coverage fetch then.
        overflow_sums = stats.aligned_bases > np.iinfo(np.int32).max
        thr_enc_np = encode_thresholds(cfg.thresholds)
        offsets32 = layout.offsets.astype(np.int32)
        out = None               # packed tail fetch; stays None when the
        n_thresholds = len(cfg.thresholds)  # native link-free tail runs
        total_len = layout.total_len
        n_contigs = len(layout.names)
        if isinstance(acc, HostPileupAccumulator):
            # tail placement: the counts are already host-side, so run the
            # tail wherever the measured cost model says it finishes first
            # (_tail_cpu_wins — link RT + upload + fetch vs the local
            # core's vote rate).  JAX computations follow committed
            # operands, so committing the counts upload to the cpu device
            # routes the whole fused tail (same jitted functions) there.
            # An explicit pallas insertion kernel keeps the device tail:
            # interpret-mode Pallas on CPU can dwarf the saved link
            # latency at scale.
            # when the default backend IS the local cpu there is no link
            # and nothing to route (link_free covers the tail below); the
            # cost-model call would still pay wire_itemsize's full-tensor
            # max scan (~0.1 s at 40 M positions) for nothing
            def _cpu_tail_wins():
                # optimistic chip bill first (wire itemsize 1): chip cost
                # only grows with the real itemsize, so a cpu win against
                # this lower bound is decisive — and skips
                # wire_itemsize()'s full-tensor max scan (~0.15 s at
                # 40 M positions, pure waste on an obvious call)
                native_ok = _native_tail_possible(cfg)
                if _tail_cpu_wins(total_len, n_thresholds,
                                  total_len * NUM_SYMBOLS, native_ok,
                                  aligned_bases=stats.aligned_bases):
                    return True
                return _tail_cpu_wins(total_len, n_thresholds,
                                      total_len * NUM_SYMBOLS
                                      * acc.wire_itemsize(), native_ok,
                                      aligned_bases=stats.aligned_bases)

            if (jax.default_backend() != "cpu" and _cpu_tail_wins()
                    and getattr(cfg, "ins_kernel", "scatter") != "pallas"):
                try:
                    cpus = jax.devices("cpu")
                    acc.tail_device = cpus[0] if cpus else None
                except RuntimeError:
                    acc.tail_device = None
                if acc.tail_device is not None:
                    stats.extra["tail_device"] = "cpu"
            # touch counts now: the upload (cached in the accumulator)
            # starts here and overlaps the host-side insertion grouping
            # below.  Device accumulators are excluded — their counts
            # property is an uncached slice.  Skipped when the native
            # link-free tail will serve instead: it reads counts_host()
            # directly and the dtype-narrowed copy + device_put would be
            # pure wasted memory traffic.
            if not ((acc.tail_device is not None
                     or jax.default_backend() == "cpu")
                    and _native_tail_possible(cfg)):
                _ = acc.counts
        tail_dev = getattr(acc, "tail_device", None)

        def put(x):
            """Tail-operand placement: EVERY operand must land on the
            tail's device up front — an uncommitted jnp.asarray would
            materialize on the default (tunneled) device first and bounce
            back over the link to join the cpu-committed computation."""
            return (jax.device_put(x, tail_dev) if tail_dev is not None
                    else jnp.asarray(x))

        thr_enc = put(thr_enc_np)
        ins = group_insertions(encoder.insertions, layout)
        reg.add("phase/insertions_sec", time.perf_counter() - t0)
        tr.complete("insertions", t0)

        t0 = time.perf_counter()
        fault_check("vote")
        # output-encoding gate: the position symbols can travel dense
        # ASCII (T*L bytes), 5-bit packed (0.625 B/char — the vote's
        # whole alphabet is 32 symbols, constants.SYM32_ASCII), or sparse
        # (emit bitmask + chars compacted to the covered positions, which
        # aligned bases bound).  None is free: packed5 costs a host
        # decode pass (~P5 ns/char), sparse costs a device compaction
        # scatter (~12 ns/position — XLA scatters serialize on TPU) plus
        # host re-expansion (~8 ns/position).  Pick the cheapest modeled
        # time; a link-free tail (cpu-routed, or the default backend IS
        # the local cpu) always ships dense — the "saved" fetch would be
        # a memcpy while the decode costs stay real.
        sparse_cap = fused.pad_cap(
            min(total_len, max(1, stats.aligned_bases)) + 1)
        if "S2C_SPARSE_OUTPUT" in os.environ:
            # ValueError, not RuntimeError: config typos are PASSTHROUGH
            # to the resilience policy — retrying/demoting a tail that
            # failed env validation would record a phantom recovery and
            # then die with the same error anyway
            raise ValueError(
                "S2C_SPARSE_OUTPUT was renamed: use "
                "S2C_TAIL_ENCODING=auto|dense|sparse|packed5")
        enc_mode = os.environ.get("S2C_TAIL_ENCODING", "auto")
        if enc_mode not in ("auto", "dense", "sparse", "packed5"):
            raise ValueError(
                f"S2C_TAIL_ENCODING={enc_mode!r}: use "
                f"auto|dense|sparse|packed5")
        link_free = tail_dev is not None or jax.default_backend() == "cpu"
        if link_free and obs.ledger().get("tail_placement") is None:
            # a link-free tail that never consulted the cost model (the
            # default backend IS the local cpu, or the upload committed
            # before pricing was needed): record the placement anyway —
            # no prediction, so no residual, but the manifest still
            # shows where the tail ran and what it measured
            obs.record_decision(
                "tail_placement", "cpu",
                inputs={"link_free": True,
                        "total_len": int(total_len)},
                measured={"sec": {"counters": ["phase/vote_sec"]}})
        if enc_mode == "auto":
            _rt, link_bps = _link_constants()
            costs = _fetch_costs(total_len, n_thresholds, sparse_cap,
                                 link_bps)
            out_enc = None if link_free else min(costs, key=costs.get)
        else:
            out_enc = {"dense": None, "packed5": "packed5",
                       "sparse": sparse_cap}[enc_mode]
        # device-resident epilogue (ops/fused.py): the fill character
        # substitutes INSIDE the vote's emit select and per-(T, C) dash
        # totals ride the packed buffer, so the fetched symbols are
        # final FASTA body bytes — the host render drops its O(T*L)
        # translate + dash-count passes.  Host-routed when the fill is
        # not representable in the wire symbol space
        # (ops.vote.device_fill_code) or forced off (S2C_EPILOGUE).
        ep_mode = os.environ.get("S2C_EPILOGUE", "auto")
        if ep_mode not in ("auto", "device", "host"):
            raise ValueError(
                f"S2C_EPILOGUE={ep_mode!r}: use auto|device|host")
        from ..ops.vote import device_fill_code

        fill_code = None
        if ep_mode != "host":
            space = "code5" if out_enc == "packed5" else "ascii"
            fill_code = device_fill_code(cfg.fill, space)
            if ep_mode == "device" and fill_code is None:
                # forced device must not silently measure the host
                # path: an unrepresentable fill is a config conflict
                # (ValueError: PASSTHROUGH, like the other env knobs)
                raise ValueError(
                    f"S2C_EPILOGUE=device: fill {cfg.fill!r} is not "
                    f"representable in the {space} wire symbol space "
                    f"(single latin-1 char required; packed5 "
                    f"additionally needs a 32-symbol-alphabet char) — "
                    f"change the fill or use S2C_EPILOGUE=auto")
        epilogue = fill_code is not None
        donate = (not use_sharded) and _donate_counts(tail_dev)
        dash_counts = None
        if ins is not None:
            fault_check("insertion_build")
            k = len(ins["key_flat"])
            # pad sites and columns to powers of two: pad events scatter
            # into the sacrificial last row (kp > k always), pad columns
            # vote past n_cols and come back as skip sentinels
            kp = fused.next_pow2(k + 1)
            cp = fused.next_pow2(ins["max_cols"])
            # residency: the [kp, cp, 6] int32 table plus the padded
            # event lanes are the insertion path's real allocations
            # (observability/memplane.py insertion_table family).
            # Tracked against the ACCUMULATOR — the table's lifetime is
            # the tail's, which the accumulator outlives by one release
            # point; a dict can't carry the weakref the auto-release
            # needs.
            memplane.track_obj(
                "insertion_table", acc,
                kp * cp * 6 * 4
                + 3 * 4 * fused.next_pow2(max(len(ins["ev_key"]), 1)))
            ik = getattr(cfg, "ins_kernel", "auto")
            if ik == "auto":
                # chip-resident tails only (never preempts the
                # link-free native tail or the cpu-routed tail, never
                # runs the kernel in interpret mode), and only inside
                # the measured winning event-count window
                chip_tail = (jax.default_backend() == "tpu"
                             and tail_dev is None)
                use_pallas = _pallas_ins_auto(len(ins["ev_key"]),
                                              chip_tail)
            else:
                use_pallas = ik == "pallas"

            def padded_sites(pad_to):
                sk = np.full(pad_to, -1, dtype=np.int32)
                sk[:k] = ins["key_flat"].astype(np.int32)
                ncp = np.zeros(pad_to, dtype=np.int32)
                ncp[:k] = ins["n_cols"]
                return sk, ncp

            def padded_events(pad_rows_to):
                """Pad events to a power of two; pad events scatter into
                the sacrificial row pad_rows_to-1 (> k always)."""
                e = len(ins["ev_key"])
                ep = fused.next_pow2(max(e, 1))
                ek = np.full(ep, pad_rows_to - 1, dtype=np.int32)
                ek[:e] = ins["ev_key"]
                ec = np.zeros(ep, dtype=np.int32)
                ec[:e] = ins["ev_col"]
                eb = np.zeros(ep, dtype=np.int32)
                eb[:e] = ins["ev_code"]
                return ek, ec, eb

            if use_pallas:
                from ..ops import pallas_insertion

                # shared pallas setup: the kernel's table is
                # [eplan.kp, cp, 6] — pad the site arrays to ITS key
                # padding (a KEY_BLOCK multiple), not the scatter kp
                eplan = pallas_insertion.plan_events(
                    ins["ev_key"], ins["ev_col"], ins["ev_code"], k, cp)
                sk_pl, nc_pl = padded_sites(eplan.kp)
                interp = (jax.default_backend() != "tpu"
                          or getattr(acc, "tail_device", None) is not None)

            if use_sharded:
                # position vote + stats run position-sharded; the insertion
                # table + vote run on the default device (K is small)
                sk, ncp = (sk_pl, nc_pl) if use_pallas \
                    else padded_sites(kp)
                contig_sums, site_cov_p = acc.tail_stats(offsets32, sk)
                syms = acc.vote(thr_enc_np, cfg.min_depth)
                site_cov = site_cov_p[:k]
                sc_dev = jnp.asarray(site_cov_p.astype(np.int32))
                if use_pallas \
                        and cp <= pallas_insertion.FUSED_VOTE_MAX_CP:
                    # fused in-kernel vote: the count table never
                    # leaves VMEM (round-4 verdict #2)
                    from ..wire import fetch_d2h

                    ins_syms = fetch_d2h(
                        pallas_insertion.vote_insertions_fused(
                            jnp.asarray(eplan.key3),
                            jnp.asarray(eplan.cc3),
                            jnp.asarray(eplan.blk_lo),
                            jnp.asarray(eplan.blk_n),
                            sc_dev, jnp.asarray(ncp), thr_enc,
                            kp=eplan.kp, c6p=eplan.c6p, cp=cp,
                            max_blocks=eplan.max_blocks,
                            interpret=interp))[:, :k, :]
                    stats.extra["insertion_kernel"] = "pallas"
                else:
                    if use_pallas:
                        out = pallas_insertion._table_call(
                            jnp.asarray(eplan.key3),
                            jnp.asarray(eplan.cc3),
                            jnp.asarray(eplan.blk_lo),
                            jnp.asarray(eplan.blk_n),
                            kp=eplan.kp, c6p=eplan.c6p,
                            max_blocks=eplan.max_blocks,
                            interpret=interp)
                        table = out.reshape(eplan.kp, eplan.c6p)[
                            :, : cp * 6].reshape(eplan.kp, cp, 6)
                        stats.extra["insertion_kernel"] = "pallas"
                    else:
                        ev_key, ev_col, ev_code = padded_events(kp)
                        table = jnp.zeros((kp, cp, 6), dtype=jnp.int32)
                        table = build_insertion_table(
                            table, jnp.asarray(ev_key),
                            jnp.asarray(ev_col), jnp.asarray(ev_code))
                    from ..wire import fetch_d2h

                    ins_syms = fetch_d2h(vote_insertions(
                        table, sc_dev, jnp.asarray(ncp),
                        thr_enc))[:, :k, :]                   # [T, K, Cp]
            elif use_pallas:
                packed = _fused_tail_call(
                    fused.vote_packed_pallas,
                    fused.vote_packed_pallas_donated, donate, acc,
                    acc.counts, thr_enc, put(offsets32),
                    put(sk_pl), put(nc_pl),
                    put(eplan.key3), put(eplan.cc3),
                    put(eplan.blk_lo), put(eplan.blk_n),
                    cfg.min_depth, cp, eplan.kp, eplan.c6p,
                    eplan.max_blocks, interp, out_enc,
                    fill_code or 0, epilogue)
                from ..wire import fetch_d2h

                out = fetch_d2h(packed, link_free)
                (syms, ins_syms, contig_sums, site_cov,
                 dash_counts) = self._unpack_tail(
                    out, n_thresholds, total_len, eplan.kp, cp, n_contigs,
                    k, out_enc=out_enc, epilogue=epilogue,
                    fill_code=fill_code)
                stats.extra["insertion_kernel"] = "pallas"
            elif link_free and _native_tail_possible(cfg) \
                    and (native_tail := self._native_vote(
                        acc, cfg, layout)) is not None:
                # link-free tail with the C++ vote: cpu-routed host
                # counts, OR any accumulator when the default backend is
                # already the local cpu (counts_host() is then a host
                # memcpy and the fused XLA vote — ~5 M pos/s/thread —
                # would be the bottleneck; the 40 Mbp config measured
                # 28 s there vs ~1.3 s native).  The position vote and
                # coverage run at memory speed (native/decoder.cpp
                # s2c_vote); the insertion table + vote run host-side
                # too (s2c_ins_table / s2c_ins_vote via
                # ops.insertions.insertion_tail_host).
                # _native_tail_possible is the ONE definition of when
                # this branch may serve (shared with the skip-upload
                # gate above and the host-pileup genome bound): a forced
                # S2C_TAIL_ENCODING explicitly asks for the fused wire
                # path and S2C_TAIL_DEVICE=default pins the device tail,
                # so both fall through (round-3 advisor finding).
                syms, cov_np, contig_sums = native_tail
                sk, ncp = padded_sites(kp)
                site_cov_p = np.where(
                    sk >= 0, cov_np[np.maximum(sk, 0)], 0).astype(np.int32)
                site_cov = site_cov_p[:k].astype(np.int64)
                ev_key, ev_col, ev_code = padded_events(kp)
                # host twins keep the whole tail off XLA: the CPU-backend
                # scatter + vote dispatches measured ~125 ms warm at
                # north-star scale vs ~5 ms native (PERF.md round 4)
                from ..ops.insertions import insertion_tail_host

                ins_syms = insertion_tail_host(
                    kp, cp, ev_key, ev_col, ev_code, site_cov_p, ncp,
                    cfg.thresholds, k)                        # [T, K, Cp]
            else:
                sk, ncp = padded_sites(kp)
                ev_key, ev_col, ev_code = padded_events(kp)
                packed = _fused_tail_call(
                    fused.vote_packed, fused.vote_packed_donated,
                    donate, acc,
                    acc.counts, thr_enc, put(offsets32),
                    put(sk), put(ncp),
                    put(ev_key), put(ev_col),
                    put(ev_code), cfg.min_depth, cp, out_enc,
                    fill_code or 0, epilogue)
                from ..wire import fetch_d2h

                out = fetch_d2h(packed, link_free)
                (syms, ins_syms, contig_sums, site_cov,
                 dash_counts) = self._unpack_tail(
                    out, n_thresholds, total_len, kp, cp, n_contigs, k,
                    out_enc=out_enc, epilogue=epilogue,
                    fill_code=fill_code)
        else:
            site_cov = None
            ins_syms = None
            if use_sharded:
                contig_sums, _ = acc.tail_stats(
                    offsets32, np.zeros(0, dtype=np.int32))
                syms = acc.vote(thr_enc_np, cfg.min_depth)
            elif link_free and _native_tail_possible(cfg,
                                                     has_insertions=False) \
                    and (native_tail := self._native_vote(
                        acc, cfg, layout)) is not None:
                syms, _cov_np, contig_sums = native_tail
            else:
                from ..wire import fetch_d2h

                out = fetch_d2h(_fused_tail_call(
                    fused.vote_packed_simple,
                    fused.vote_packed_simple_donated, donate, acc,
                    acc.counts, thr_enc, put(offsets32),
                    cfg.min_depth, out_enc, fill_code or 0, epilogue),
                    link_free)
                if out_enc == "packed5":
                    syms, split = self._expand_packed5(
                        out, n_thresholds, total_len)
                elif out_enc is not None:
                    syms, split = self._expand_sparse(
                        out, n_thresholds, total_len, out_enc,
                        fill_code=fill_code)
                else:
                    split = n_thresholds * total_len
                    syms = out[:split].reshape(n_thresholds, total_len)
                split2 = split + 4 * n_contigs
                contig_sums = fused.unpack_i32(out[split:split2],
                                               n_contigs)
                if epilogue:
                    dash_counts = fused.unpack_i32(
                        out[split2:], n_thresholds * n_contigs).reshape(
                        n_thresholds, n_contigs)
        if overflow_sums:
            if isinstance(acc, HostPileupAccumulator):
                cov64 = acc.counts_host().sum(axis=-1, dtype=np.int64)
            else:
                from ..wire import fetch_d2h

                cov64 = fetch_d2h(fused.coverage(
                    acc.counts))[:total_len].astype(np.int64)
            contig_sums = np.asarray([
                cov64[int(layout.offsets[i]):int(layout.offsets[i + 1])]
                .sum() for i in range(n_contigs)], dtype=np.int64)
            stats.extra["contig_sums_host_fallback"] = True
        # ledger: where the render epilogue ran and what it saved —
        # predicted per-char cost of the side that will execute, joined
        # against the measured render wall.  band=0 (informational, the
        # shard_mode precedent): render also pays the insertion splice,
        # which neither side's per-char model prices, so the residual
        # belongs in the manifest but must not false-alarm drift.
        chars = n_thresholds * total_len
        epi_chosen = "device" if dash_counts is not None else "host"
        obs.record_decision(
            "epilogue", epi_chosen,
            inputs={"mode": ep_mode, "fill": cfg.fill,
                    "out_enc": str(out_enc), "donate": bool(donate),
                    "sharded": bool(use_sharded),
                    "total_len": int(total_len),
                    "n_thresholds": int(n_thresholds)},
            predicted={"sec": chars * 1e-9 * (
                EPILOGUE_DEV_NS if epi_chosen == "device"
                else EPILOGUE_HOST_NS)},
            alternatives={"device": chars * EPILOGUE_DEV_NS * 1e-9,
                          "host": chars * EPILOGUE_HOST_NS * 1e-9},
            measured={"sec": {"counters": ["phase/render_sec"]}},
            band=0)
        if dash_counts is not None:
            reg.add("epilogue/device_tails", 1)
        else:
            reg.add("epilogue/host_tails", 1)
        # the vote section's device work all completes under host fetches
        # (np.asarray / the native vote), so this span's close already
        # sits after device completion — the block_until_ready guarantee
        # without an extra barrier
        reg.add("phase/vote_sec", time.perf_counter() - t0)
        tr.complete("vote", t0)
        return (syms, ins_syms, contig_sums, site_cov, ins, out,
                link_free, dash_counts)

    # -- sharded-accumulator construction ---------------------------------
    @staticmethod
    def _build_sharded_acc(cfg, layout, shards: int, first_batch,
                           ck_max_width: int, stats,
                           wire: str = "packed5"):
        """Build the sharded accumulator from the first decoded batch.

        Two round-4 verdict items live here:

        * **#5 dynamic halo** — the sp/dpsp halo is the run's observed
          widest segment-row bucket (checkpoint-carried across resumes),
          with ``SP_HALO`` (2^16, the encoder's widening ceiling) only
          as the static upper bound.  Short-read inputs thus get halos
          of a few hundred positions, so position sharding stays
          feasible (and its exchange cheap) at block sizes far below
          64 k; rows wider than the halo that appear in LATER batches
          are exact regardless (the routers split them,
          parallel.base.split_wide_rows).
        * **#3 model-driven auto** — ``--shard-mode auto`` prices
          dp/sp/dpsp per-slab overheads from the first slab's shape
          (rows, bytes, imbalance, sortedness), the mesh, and the
          calibrated link/ICI constants (parallel/auto.py), instead of
          the old single ``total_len >= 2^25`` test.
        """
        from ..parallel import auto as shard_auto
        from ..parallel.base import block_for
        from ..parallel.mesh import make_mesh

        mode = getattr(cfg, "shard_mode", "auto")
        block = block_for(layout.total_len, shards)
        widths = list(first_batch.buckets) if first_batch is not None \
            else []
        max_w = max([*widths, ck_max_width, 64])
        halo = min(SP_HALO, max_w)
        mesh = make_mesh(shards)
        if mode == "auto":
            if first_batch is not None:
                # link terms bill POST-codec bytes: the routers ship the
                # same slab payloads, through the same wire codec
                rows, rb, _mw, imb, sfrac = shard_auto.slab_stats(
                    first_batch.buckets, layout.total_len, wire=wire)
            else:
                rows, rb, imb, sfrac = 0, 0, 1.0, 0.0
            _rt, link_bps = _link_constants()
            from ..parallel.partition import mesh_process_count

            n_hosts = mesh_process_count(mesh)
            mode, mode_costs = shard_auto.shard_mode_costs(
                layout.total_len, shards, dict(mesh.shape), rows, rb,
                imb, sfrac, halo, link_bps, n_hosts=n_hosts)
            stats.extra["shard_auto"] = {
                "rows": int(rows), "peak_frac": round(float(imb), 2),
                "sorted_frac": round(float(sfrac), 2), "halo": int(halo),
                "hosts": int(n_hosts)}
            # ledger: the model prices per-slab OVERHEAD deltas between
            # layouts, not absolute slab time — so the measured
            # per-slab dispatch seconds join is informational (band=0:
            # residual recorded, drift never fired on it)
            obs.record_decision(
                "shard_mode", mode,
                inputs={"total_len": int(layout.total_len),
                        "shards": int(shards), "rows": int(rows),
                        "row_bytes": int(rb),
                        "peak_frac": round(float(imb), 3),
                        "sorted_frac": round(float(sfrac), 3),
                        "halo": int(halo), "link_bps": int(link_bps)},
                predicted={"sec": mode_costs.get(mode)},
                alternatives=mode_costs,
                measured={"sec": {"num": ["phase/pileup_dispatch_sec"],
                                  "den": ["pileup/slabs"]}},
                band=0)
        # the sp/dpsp routers compose with every device kernel (verdict
        # r4 #4): rows route by position block, then each device runs
        # the scatter, the Pallas tile-CSR histogram, or the MXU tile
        # plan over its local coordinate space.  "auto" keeps the
        # scatter there (the routed grids are transfer-shaped).
        sp_pileup = getattr(cfg, "pileup", "auto")
        if sp_pileup not in ("mxu", "pallas"):
            sp_pileup = "scatter"
        if mode == "sp":
            from ..parallel.sp import PositionShardedConsensus

            acc = PositionShardedConsensus(
                mesh, layout.total_len, halo=min(block, halo),
                pileup=sp_pileup, wire=wire)
        elif mode == "dpsp":
            from ..parallel.dpsp import ProductShardedConsensus

            macro = block * shards // mesh.shape["sp"]
            acc = ProductShardedConsensus(
                mesh, layout.total_len,
                halo=max(1, min(macro, halo)), pileup=sp_pileup,
                wire=wire)
        else:
            from ..parallel.dp import ShardedConsensus

            acc = ShardedConsensus(mesh, layout.total_len,
                                   pileup=getattr(cfg, "pileup", "auto"),
                                   wire=wire)
        stats.extra["shard_mode"] = mode
        if hasattr(acc, "halo"):
            stats.extra["halo"] = int(acc.halo)
        obs.metrics().gauge("dispatch/pileup").set_info(
            {"path": "sharded", "mode": mode, "shards": int(shards),
             "pileup": sp_pileup if mode in ("sp", "dpsp")
             else getattr(cfg, "pileup", "auto"),
             "halo": int(getattr(acc, "halo", 0)),
             "total_len": int(layout.total_len)})
        return acc

    # -- checkpointing -----------------------------------------------------
    def _write_checkpoint(self, cfg, stream, acc, encoder, stats,
                          base_mapped, base_skipped, sources,
                          max_row_width: int = 0) -> None:
        from ..utils import checkpoint as ckpt

        # fused decode keeps in-flight counts in a uint8 shadow; a
        # checkpoint must snapshot the merged int32 pileup
        merge = getattr(encoder, "merge_shadow", None)
        if merge is not None:
            merge()
        ckpt.save(cfg.checkpoint_dir, ckpt.CheckpointState(
            counts=acc.counts_host(),
            lines_consumed=stream.n_lines,
            reads_mapped=base_mapped + encoder.n_reads,
            reads_skipped=base_skipped + encoder.n_skipped,
            aligned_bases=stats.aligned_bases,
            insertions=encoder.insertions,
            source=getattr(cfg, "source_id", ""),
            sources=list(sources),
            byte_offset=stream.byte_offset(),
            max_row_width=max_row_width))
        stats.extra["checkpoints_written"] = (
            stats.extra.get("checkpoints_written", 0) + 1)

    @staticmethod
    def _native_vote(acc, cfg: RunConfig, layout):
        """C++ position vote + int64 contig sums for a cpu-routed tail
        (native/decoder.cpp ``s2c_vote``); None when the native library
        is unavailable (the XLA CPU fused tail handles it then)."""
        from ..ops.vote import vote_positions_native

        nat = vote_positions_native(acc.counts_host(), cfg.thresholds,
                                    cfg.min_depth,
                                    threads=_resolve_decode_threads(cfg))
        if nat is None:
            return None
        syms, cov = nat
        # per-contig coverage sums in C (s2c_cov_sums: SIMD
        # widen-accumulate at memory speed) — the numpy alternatives
        # both measured slow at 40 M positions: a full int64 prefix sum
        # ~0.6 s, np.add.reduceat ~0.21 s (no SIMD through the dtype
        # cast); the C segmented sum is ~0.02 s and handles empty
        # contigs structurally.
        from .. import native

        offs = np.ascontiguousarray(layout.offsets, dtype=np.int64)
        contig_sums = np.empty(len(offs) - 1, dtype=np.int64)
        native.load().s2c_cov_sums(cov, offs, len(offs) - 1, contig_sums)
        return syms, cov, contig_sums

    @staticmethod
    def _expand_sparse(out: np.ndarray, n_thresholds: int, total_len: int,
                       cap: int, fill_code=None):
        """Inflate the sparse-output prefix (emit bitmask + compacted
        chars, ops/fused.py ``_sparse_syms``) back to dense ``[T, L]``.
        ``fill_code`` (device-resident epilogue) pre-fills unemitted
        positions with the final fill byte — the expansion buffer IS
        the substitution pass, so no separate translate walk remains.
        Returns (syms, bytes consumed)."""
        nbits = (total_len + 7) // 8
        emit = np.unpackbits(out[:nbits], bitorder="little",
                             count=total_len).astype(bool)
        kcov = int(emit.sum())
        compact = out[nbits:nbits + n_thresholds * cap].reshape(
            n_thresholds, cap)
        if fill_code:
            syms = np.full((n_thresholds, total_len), fill_code,
                           np.uint8)
        else:
            syms = np.zeros((n_thresholds, total_len), np.uint8)
        syms[:, emit] = compact[:, :kcov]
        return syms, nbits + n_thresholds * cap

    @staticmethod
    def _expand_packed5(out: np.ndarray, n_thresholds: int,
                        total_len: int):
        """Decode the 5-bit packed symbol planes (ops/fused.py
        ``_packed5_syms``) back to dense ASCII ``[T, L]``.

        The common case — high bit clear — decodes two characters per
        nibble byte through one 256-entry uint16 pair-LUT gather; only
        bytes of the high-bit plane that are nonzero (lowercase calls,
        'B', 'n' — rare) get per-position fixups.  Returns
        (syms, bytes consumed)."""
        from ..constants import SYM32_ASCII

        nb = (total_len + 1) // 2
        hb = (total_len + 7) // 8
        nibs = out[:n_thresholds * nb].reshape(n_thresholds, nb)
        hbits = out[n_thresholds * nb:
                    n_thresholds * (nb + hb)].reshape(n_thresholds, hb)
        # pair LUT: byte b -> ASCII of (b & 15) | ASCII of (b >> 4) << 8
        # (little-endian uint16 view puts the low-nibble char first)
        lo16 = SYM32_ASCII[:16].astype(np.uint16)
        pair_lut = (lo16[np.arange(256) & 15]
                    | (lo16[np.arange(256) >> 4] << 8)).astype("<u2")
        pairs = pair_lut[nibs]                       # [T, nb] uint16
        syms = np.ascontiguousarray(pairs).view(np.uint8).reshape(
            n_thresholds, nb * 2)[:, :total_len].copy()
        rows, bytecols = np.nonzero(hbits)
        if rows.size:
            bits = np.unpackbits(hbits[rows, bytecols][:, None], axis=1,
                                 bitorder="little")            # [n, 8]
            brow, bbit = np.nonzero(bits)
            prow = rows[brow]
            ppos = bytecols[brow] * 8 + bbit
            ok = ppos < total_len
            prow, ppos = prow[ok], ppos[ok]
            low = (nibs[prow, ppos // 2] >> (4 * (ppos & 1))) & 15
            syms[prow, ppos] = SYM32_ASCII[16 + low]
        return syms, n_thresholds * (nb + hb)

    @classmethod
    def _unpack_tail(cls, out: np.ndarray, n_thresholds: int,
                     total_len: int, kp: int, cp: int, n_contigs: int,
                     k: int, out_enc=None, epilogue: bool = False,
                     fill_code=None):
        """Split the fused tail's packed uint8 buffer (ops/fused.py);
        ``epilogue`` additionally parses the trailing per-(T, C) dash
        counts (device-resident epilogue), returned as the 5th element
        (None otherwise)."""
        from ..ops import fused

        if out_enc is None:
            split1 = n_thresholds * total_len
            syms = out[:split1].reshape(n_thresholds, total_len)
        elif out_enc == "packed5":
            syms, split1 = cls._expand_packed5(out, n_thresholds,
                                               total_len)
        else:
            syms, split1 = cls._expand_sparse(out, n_thresholds, total_len,
                                              out_enc, fill_code=fill_code)
        split2 = split1 + n_thresholds * kp * cp
        split3 = split2 + 4 * n_contigs
        split4 = split3 + 4 * kp
        ins_syms = out[split1:split2].reshape(
            n_thresholds, kp, cp)[:, :k, :]                   # [T, K, Cp]
        contig_sums = fused.unpack_i32(out[split2:split3], n_contigs)
        site_cov = fused.unpack_i32(out[split3:split4], kp)[:k]
        dash_counts = None
        if epilogue:
            dash_counts = fused.unpack_i32(
                out[split4:], n_thresholds * n_contigs).reshape(
                n_thresholds, n_contigs)
        return syms, ins_syms, contig_sums, site_cov, dash_counts

    # -- paranoid mode (SURVEY.md §5 sanitizers) ---------------------------
    def _paranoid_batch(self, batch, total_len: int, stats) -> None:
        """Re-validate scatter inputs before they reach the device."""
        for w, (starts, codes) in batch.buckets.items():
            rows, cols = np.nonzero(codes < NUM_SYMBOLS)
            pos = starts[rows].astype(np.int64) + cols
            if len(pos) and (pos.min() < 0 or pos.max() >= total_len):
                raise RuntimeError(
                    "paranoid: scatter position out of bounds "
                    f"(width-{w} bucket, range [{pos.min()}, {pos.max()}], "
                    f"genome length {total_len})")
            bad = (codes > NUM_SYMBOLS - 1) & (codes != 255)
            if bad.any():
                raise RuntimeError(
                    f"paranoid: {int(bad.sum())} invalid symbol codes in "
                    f"width-{w} bucket")
        stats.extra["paranoid_batches"] = (
            stats.extra.get("paranoid_batches", 0) + 1)

    def _paranoid_result(self, acc, contig_sums: np.ndarray, layout,
                         stats, ins=None, site_cov=None) -> None:
        """Fetch the full count tensor and cross-check the device-computed
        tail stats (contig sums AND per-site coverage — both feed emission
        gates) against an independent host recomputation."""
        counts = acc.counts_host()
        if (counts < 0).any():
            raise RuntimeError("paranoid: negative pileup count")
        cov = counts.sum(axis=-1, dtype=np.int64)
        if int(cov.sum()) != stats.aligned_bases:
            raise RuntimeError(
                f"paranoid: device event total {int(cov.sum())} != host "
                f"accounting {stats.aligned_bases}")
        want = np.asarray([
            cov[int(layout.offsets[i]):int(layout.offsets[i + 1])].sum()
            for i in range(len(layout.names))], dtype=np.int64)
        if not np.array_equal(np.asarray(contig_sums, dtype=np.int64), want):
            raise RuntimeError(
                "paranoid: device per-contig coverage sums diverge from "
                "host recomputation")
        if ins is not None and site_cov is not None:
            kf = ins["key_flat"]
            want_sc = np.where(kf >= 0, cov[np.maximum(kf, 0)], 0)
            if not np.array_equal(np.asarray(site_cov, dtype=np.int64),
                                  want_sc.astype(np.int64)):
                raise RuntimeError(
                    "paranoid: device per-site coverage diverges from "
                    "host recomputation")
        stats.extra["paranoid_result_ok"] = True

    def _make_encoder(self, layout, records, cfg: RunConfig, acc=None):
        """Pick the host decode path; returns (encoder, batch iterator).

        Tolerant decode: the run's ONE quarantine sink is created here
        (``--on-bad-record skip|quarantine`` — None under the strict
        default) and carried on the encoder as ``bad_sink``, so every
        caller — the cold path, serve's decode-ahead thread (which
        builds the encoder through this same method), the BAM stream's
        ``make_encoder`` — shares run-lifecycle code in ``_run``."""
        from ..encoder.events import (GenomeLayout, ReadEncoder,  # noqa: F811
                                      resolve_segment_width)
        from ..ingest.badrecords import sink_from_config
        from ..io.sam import ReadStream
        from ..ops.pileup import HostPileupAccumulator

        if getattr(records, "is_predecoded", False):
            # serve mode (sam2consensus_tpu/serve): the job's decode ran
            # ahead on a side thread — overlapping the PREVIOUS job's
            # device work — and arrives as a ready encoder + its batch
            # stream (already-decoded batches first, then any live
            # remainder).  Decode seconds were billed to this job's
            # registry by the decode-ahead thread.
            return records.encoder, records.batches()

        seg_w = resolve_segment_width(getattr(cfg, "segment_width", 0))
        self._record_layout_decision(cfg, seg_w)
        bad_sink = sink_from_config(cfg)

        if hasattr(records, "make_encoder"):
            # binary formats (formats/bam.py BamReadStream): the stream
            # owns its vectorized record decode and hands back the same
            # (encoder, batches) surface as the text paths
            return records.make_encoder(layout, cfg, acc,
                                        bad_sink=bad_sink)

        if isinstance(records, ReadStream) and cfg.decoder != "py":
            from ..encoder import native_encoder

            if native_encoder.available():
                # host-counts strategy: fuse accumulation into the C++
                # decode pass (single memory walk — the one-core-host
                # fast path).  Paranoid mode keeps the two-pass row path
                # so batches can be re-validated.
                fuse = (isinstance(acc, HostPileupAccumulator)
                        and not cfg.paranoid)
                threads = _resolve_decode_threads(cfg)
                parallel = (threads > 1 and not cfg.checkpoint_dir
                            and not cfg.paranoid)
                self._record_decode_decision(cfg, records, threads,
                                             parallel, fuse)
                if parallel:
                    # multi-core hosts: shard-owned ingest
                    # (encoder/parallel_decode.py) — byte-range workers
                    # decode GIL-free into per-worker partitions merged
                    # via s2c_merge_u8 (fused), or emit slabs straight
                    # into the wire-encode/staging pipeline (device
                    # path).  Checkpointing needs ordered consumption
                    # offsets and paranoid wants ordered re-validated
                    # batches, so both keep the serial path.
                    from ..encoder.parallel_decode import \
                        ParallelFusedDecoder

                    enc = ParallelFusedDecoder(
                        layout, acc.counts_host() if fuse else None,
                        threads,
                        maxdel=cfg.maxdel, strict=cfg.strict,
                        on_lines=records.add_lines,
                        on_bytes=records.add_bytes,
                        segment_width=seg_w, bad_sink=bad_sink)
                    return enc, enc.encode_input(records)
                enc = native_encoder.NativeReadEncoder(
                    layout, maxdel=cfg.maxdel, strict=cfg.strict,
                    on_lines=records.add_lines, on_bytes=records.add_bytes,
                    accumulate_into=acc.counts_host() if fuse else None,
                    segment_width=seg_w, bad_sink=bad_sink)
                return enc, enc.encode_blocks_from(records)
            if cfg.decoder == "native":
                from .. import native

                raise RuntimeError("--decoder native requested but the C++ "
                                   f"decoder is unavailable: "
                                   f"{native.load_error()}")
        enc = ReadEncoder(layout, maxdel=cfg.maxdel, strict=cfg.strict,
                          segment_width=seg_w, bad_sink=bad_sink)
        on_bad = None
        if bad_sink is not None:
            def on_bad(line, exc):
                # pure-python rung parse errors (iter_records): same
                # sink, single stream-order partition — and the same
                # n_skipped accounting as the native rungs' replay lane
                bad_sink.record(line, exc)
                enc.n_skipped += 1
        source = records.records(on_bad=on_bad) \
            if isinstance(records, ReadStream) else records
        return enc, enc.encode_segments(source, cfg.chunk_reads)

    @staticmethod
    def _record_decode_decision(cfg, records, threads: int,
                                parallel: bool, fuse: bool = True) -> None:
        """Ledger the ``--decode-threads`` policy like every other
        priced gate: predicted decode seconds (body bytes over the
        measured per-core shard-decode rate, scaled by the thread
        count with a parallel-efficiency factor) joined at run end
        against the run's real ``phase/decode_sec`` — so a host where
        the shard scheduler stops scaling (memory-bandwidth-bound, or
        an input stuck on the streaming rung) shows up as residual
        drift in the manifest instead of silently recording the
        single-core floor (the round-5 verdict's gap)."""
        def _envf(name, default):
            # telemetry-only knobs: a malformed value falls back to the
            # default instead of failing the run before decode starts
            try:
                return float(os.environ.get(name, default))
            except ValueError:
                return float(default)

        # the decode rate, by precedence: explicit env override, then
        # the learned rate card (serve workers: the card converges on
        # THIS host's measured per-core rate after a few jobs), then
        # the baked 330 MB/s default — with the consultation stamped
        # into the ledger inputs either way
        from ..observability import ratecard as _rc

        if "S2C_DECODE_MBPS_PER_CORE" in os.environ:
            rate_mbps = _envf("S2C_DECODE_MBPS_PER_CORE", "330")
            rc_prov = {"source": "env", "key": "decode_mbps_per_core"}
        else:
            rate_mbps, rc_prov = _rc.consult("decode_mbps_per_core",
                                             330.0)
        rate = rate_mbps * 1e6
        eff = _envf("S2C_DECODE_PAR_EFF", "0.85")
        cores = os.cpu_count() or 1
        inputs = {"threads": int(threads),
                  "requested": int(getattr(cfg, "decode_threads", 1)),
                  "cores": int(cores), "parallel": bool(parallel),
                  "rate_mbps_per_core": round(rate_mbps, 2),
                  "rung": "fused" if fuse else "slab"}
        # priced only for plain uncompressed files (ReadStream owns the
        # ONE plain-file rule: a gzip handle's fstat size is COMPRESSED
        # bytes while decode_sec walks uncompressed text) and only on
        # fresh runs — checkpoint resume decodes the un-committed
        # remainder while fstat sees the whole body; either would
        # manufacture drift
        body_bytes = None
        probe = getattr(records, "body_bytes_total", None)
        if probe is not None and not getattr(cfg, "checkpoint_dir", None):
            body_bytes = probe()
        predicted = {}
        alternatives = {}
        if body_bytes is not None:
            inputs["body_bytes"] = int(body_bytes)
            serial_sec = body_bytes / rate

            def _sec(n):
                speedup = 1.0 + (n - 1) * eff if n > 1 else 1.0
                return serial_sec / speedup

            n_eff = min(threads, cores) if parallel else 1
            predicted["sec"] = _sec(n_eff)
            alternatives = {"1": serial_sec,
                            str(cores): _sec(cores)}
        # the model prices decode WORK; phase/decode_sec measures decode
        # WALL.  On the fused host rung they coincide, so the drift band
        # is enforced.  On the slab (device) rung the whole point of the
        # pipeline is wall << work — decode hides under wire encode and
        # dispatch — so the decision is informational there (band=0:
        # residual still joined into the manifest, no false alarm)
        obs.record_decision(
            "decode_threads", str(threads if parallel else 1),
            inputs=inputs, predicted=predicted,
            alternatives=alternatives,
            measured={"sec": {"counters": ["phase/decode_sec"]}},
            band=None if fuse or not parallel else 0.0,
            provenance=rc_prov)

    @staticmethod
    def _record_layout_decision(cfg, seg_w: int) -> None:
        """Ledger the long-read slab layout choice (segmented vs fixed):
        the priced trade is worst-case bucket width — bounded by W under
        segmentation vs the widest read span (native slab ceiling 2^16)
        under fixed buckets — which is exactly the padded-cell and wire
        bill a dense-indel long read would otherwise run up.  Joined
        against the run's realized row count so a pathological split
        blowup (rows/read >> predicted) is visible as drift."""
        from ..encoder.events import DEFAULT_SEGMENT_W

        chosen = "segmented" if seg_w else "fixed"
        obs.record_decision(
            "longread_layout", chosen,
            inputs={"segment_width": int(seg_w),
                    "configured": int(getattr(cfg, "segment_width", 0))},
            predicted={"max_bucket_w": float(seg_w if seg_w else 1 << 16)},
            alternatives={"fixed" if seg_w else "segmented": float(
                (1 << 16) if seg_w else DEFAULT_SEGMENT_W)},
            band=0.0)

    # -- host-side rendering ---------------------------------------------
    def _assemble(self, layout, syms: np.ndarray, contig_sums: np.ndarray,
                  ins, ins_syms, site_cov, cfg: RunConfig,
                  stats: BackendStats,
                  dash_counts=None) -> Dict[str, List[FastaRecord]]:
        """Render FASTA records from device outputs.  Coverage facts arrive
        pre-reduced from the fused tail (ops/fused.py): per-contig sums and
        per-insertion-site depths — the full [L] coverage vector never
        reaches the host.

        ``dash_counts`` (``[T, C]``, device-resident epilogue) means the
        symbols already carry the substituted fill byte and the per-
        contig dash totals were reduced on device: the render is then a
        pure slice + splice + decode — no translate walk, no memchr
        count, no full-sequence C pass (the only remaining O(L) host
        work is ``tobytes``/latin-1 decode of the final string)."""
        n_thresholds = syms.shape[0]
        fastas: Dict[str, List[FastaRecord]] = {}

        if ins is not None:
            # per-contig site ranges in one searchsorted: key_contig is
            # sorted by construction (group_insertions orders sites by
            # (contig, local) via np.unique on a packed composite key),
            # so the old per-contig boolean mask — O(contigs x sites),
            # ~25 M compares on the 500-contig north-star config — is a
            # binary search instead
            _kc_bounds = np.searchsorted(
                ins["key_contig"], np.arange(len(layout.names) + 1))

        for ci, name in enumerate(layout.names):
            off = int(layout.offsets[ci])
            length = int(layout.lengths[ci])
            sumcov_base = int(contig_sums[ci])
            if sumcov_base == 0:
                continue  # zero-coverage prune (sam2consensus.py:334-340)

            # insertion sites for this contig, emittable ones only:
            # local key within [0, length) and site depth passes the gates
            # (emission is nested inside cov>0 and cov>=min_depth branches,
            # sam2consensus.py:356-385).  site_cov[row] is exactly
            # cov[off + local] for these rows (fused tail gather).
            site_rows = np.zeros(0, dtype=np.int64)
            if ins is not None:
                lo, hi = int(_kc_bounds[ci]), int(_kc_bounds[ci + 1])
                loc_all = ins["key_local"][lo:hi]
                keep = (loc_all >= 0) & (loc_all < length)
                site_rows = np.arange(lo, hi, dtype=np.int64)[keep]
                # loc_all is already ascending within the contig (same
                # np.unique ordering), so the splice order matches the
                # oracle without a sort
                locs = loc_all[keep].astype(np.int64)
                sc = site_cov[site_rows]
                depth_ok = (sc > 0) & (sc >= cfg.min_depth)
                site_rows, locs = site_rows[depth_ok], locs[depth_ok]

            for t in range(n_thresholds):
                base = syms[t, off:off + length]
                if len(site_rows):
                    # splice every site's surviving columns after its
                    # base position in ONE vectorized pass: np.insert
                    # with repeated positions places each site's chars
                    # in order at loc+1 (right-shift placement, quirk 3).
                    # A python per-site loop here measured ~3 us/site —
                    # the dominant render cost at 40k+ sites.
                    block = ins_syms[t, site_rows]             # [S, Cp]
                    nz = block != 0
                    lens = nz.sum(axis=1)
                    arr = np.insert(base, np.repeat(locs + 1, lens),
                                    block[nz])
                    sumcov = sumcov_base + int(
                        (site_cov[site_rows] * lens).sum())
                else:
                    arr = base
                    sumcov = sumcov_base

                if dash_counts is not None:
                    # device epilogue: fill substituted in the vote's
                    # emit select, base dash totals pre-reduced per
                    # (threshold, contig) — only the (tiny) spliced
                    # insertion block still needs a host dash count
                    dashes = int(dash_counts[t, ci])
                    if len(site_rows):
                        dashes += int((block[nz] == ord("-")).sum())
                    seq = arr.tobytes().decode("latin-1")
                    stripped = len(seq) - dashes
                    if stripped == 0:
                        continue  # empty-sequence drop (:400-406)
                    header = format_header(cfg.prefix, cfg.thresholds[t],
                                           name, sumcov, seq,
                                           stripped_len=stripped)
                elif len(cfg.fill) == 1 and ord(cfg.fill) < 256:
                    nat = None
                    if len(arr) >= (1 << 20):
                        from .. import native

                        nat = native.load()
                    if nat is not None:
                        # one C pass does fill substitution + '-' count
                        # (s2c_finalize); the python chain below walks
                        # the sequence ~4x (~0.1 s at 40 Mbp)
                        buf = np.empty(len(arr), np.uint8)
                        dashes = nat.s2c_finalize(
                            np.ascontiguousarray(arr), len(arr),
                            ord(cfg.fill), buf)
                        seq = buf.tobytes().decode("latin-1")
                        stripped = len(seq) - dashes
                    else:
                        # fill substitution via bytes.translate — the
                        # fastest measured PYTHON pass at 40 Mbp (45 ms
                        # vs 187 ms for np.where); the find() probe
                        # skips the copy when no position needs filling,
                        # and the dash count rides the decoded str's
                        # memchr path (11 ms vs 25 ms on the uint8 view)
                        raw = arr.tobytes()
                        if raw.find(b"\x00") >= 0:
                            raw = raw.translate(bytes.maketrans(
                                b"\x00", cfg.fill.encode("latin-1")))
                        seq = raw.decode("latin-1")
                        stripped = len(seq) - seq.count("-")
                    if stripped == 0:
                        continue  # empty-sequence drop (:400-406)
                    header = format_header(cfg.prefix, cfg.thresholds[t],
                                           name, sumcov, seq,
                                           stripped_len=stripped)
                else:
                    # multi-char (or non-latin) fill: the plain-string path
                    seq = arr.tobytes().decode("latin-1").replace(
                        "\x00", cfg.fill)
                    if len(seq) - seq.count("-") == 0:
                        continue  # empty-sequence drop (:400-406)
                    header = format_header(cfg.prefix, cfg.thresholds[t],
                                           name, sumcov, seq)
                fastas.setdefault(name, []).append(FastaRecord(header, seq))
                stats.consensus_bases += len(seq)

        return fastas
