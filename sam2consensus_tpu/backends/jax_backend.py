"""JAX/TPU backend: encoder → scatter-add pileup → jit vote → host render.

The TPU-native pipeline replacing the reference's interpreter loops
(SURVEY.md §1 "new-framework layer map", §7 steps 3-7):

1. host encoder turns records into flat (position, code) event arrays
   (``encoder/events.py``);
2. device scatter-add accumulates the ``[total_len, 6]`` count tensor
   (``ops/pileup.py``) — the count tensor is the entire job state and is
   sum-decomposable, which is what makes DP/psum and checkpointing exact;
3. the threshold vote runs as a closed-form int32 reduction vmapped over
   thresholds (``ops/vote.py``), and the insertion "mini-alignment" table is
   scatter-built and voted the same way (``ops/insertions.py``);
4. the host splices insertion columns after their site's base (right-shift
   placement, quirk 3), substitutes the fill character for sentinel bytes and
   renders FASTA records byte-identically to the CPU oracle.

Output equality with ``CpuBackend`` over the whole fixture corpus is the
framework's correctness gate (tests/test_differential.py).
"""

from __future__ import annotations

import os
import time
from typing import Dict, Iterable, List

import numpy as np

from ..config import RunConfig
from ..io.sam import Contig, SamRecord
from .base import BackendResult, BackendStats, FastaRecord, format_header

#: halo width for the position-sharded (sp) accumulator; must cover the
#: widest segment-row bucket the native encoder will emit (it widens up
#: to 1<<16 on overflow, encoder/native_encoder.py)
SP_HALO = 1 << 16


class JaxBackend:
    name = "jax"

    def run(self, contigs: List[Contig], records: Iterable[SamRecord],
            cfg: RunConfig) -> BackendResult:
        # jax imports deferred so `--backend cpu` never pays them
        import jax
        import jax.numpy as jnp

        from ..encoder.events import GenomeLayout, ReadEncoder, group_insertions
        from ..ops import fused
        from ..ops.insertions import build_insertion_table, vote_insertions
        from ..ops.pileup import PileupAccumulator
        from ..ops.vote import threshold_luts, vote_positions

        from ..io.sam import ReadStream

        stats = BackendStats()
        layout = GenomeLayout(contigs)
        if layout.total_len == 0:
            return BackendResult(fastas={}, stats=stats)

        n_dev = len(jax.devices())
        shards = cfg.shards if cfg.shards > 0 else n_dev
        use_sharded = shards > 1

        if use_sharded:
            from ..parallel.mesh import make_mesh

            from ..parallel.base import block_for

            mode = getattr(cfg, "shard_mode", "auto")
            block = block_for(layout.total_len, shards)
            if mode == "auto":
                # sp (position-sharded blocks + halo exchange) once the
                # dp pipeline's transient full-length local tensor per
                # device stops being cheap; dp otherwise (it needs no
                # host-side read routing and reduce-scatter is optimal).
                # An explicit --pileup mxu pins dp: the MXU tile plan
                # composes with the dp layout only.
                mode = ("sp" if layout.total_len >= (1 << 25)
                        and block >= SP_HALO
                        and getattr(cfg, "pileup", "auto") != "mxu"
                        else "dp")
            if mode == "sp":
                from ..parallel.sp import PositionShardedConsensus

                if getattr(cfg, "pileup", "auto") == "mxu":
                    raise RuntimeError(
                        "--pileup mxu composes with the dp shard layout "
                        "only; use --shard-mode dp (sp routes rows to "
                        "position blocks, which the MXU tile plan does not "
                        "model yet)")
                acc = PositionShardedConsensus(
                    make_mesh(shards), layout.total_len,
                    halo=min(block, SP_HALO))
            else:
                from ..parallel.dp import ShardedConsensus

                acc = ShardedConsensus(make_mesh(shards), layout.total_len,
                                       pileup=getattr(cfg, "pileup", "auto"))
            stats.extra["shard_mode"] = mode
        else:
            acc = PileupAccumulator(layout.total_len,
                                    strategy=getattr(cfg, "pileup", "auto"))

        # checkpoint resume: counts + insertion log + consumed-line offset
        # are the entire job state (SURVEY.md §5)
        ck = None
        skip_input = False
        prior_sources: List[str] = []
        incremental = getattr(cfg, "incremental", False)
        source_id = getattr(cfg, "source_id", "")
        if incremental and not source_id:
            raise RuntimeError(
                "incremental mode needs a non-empty source_id identifying "
                "the input (the CLI passes the input file's absolute path)")
        if cfg.checkpoint_dir:
            from ..utils import checkpoint as ckpt

            if not isinstance(records, ReadStream):
                raise RuntimeError(
                    "--checkpoint-dir requires a file-backed input stream")
            ck = ckpt.load(cfg.checkpoint_dir, layout.total_len)
            if ck is not None:
                # three incremental cases (SURVEY.md §5 "incremental
                # updates"), resolved by the checkpoint's source identity:
                # * listed in ck.sources -> this input is already fully
                #   absorbed: add nothing (idempotent re-run);
                # * ck.source (in-flight) -> crashed mid-input: resume by
                #   skipping its consumed lines;
                # * otherwise -> NEW shard on the accumulated base: start
                #   from line 0.
                # Without --incremental the checkpoint always refers to
                # the current input: plain resume.
                prior_sources = list(ck.sources or [])
                if incremental and source_id != ck.source \
                        and ck.lines_consumed > 0 and ck.source \
                        and ck.source not in prior_sources:
                    # the checkpoint holds a PARTIAL prefix of a crashed
                    # shard; any run other than resuming that shard (a new
                    # shard, or a no-op duplicate whose final write would
                    # reset source/lines_consumed) would bake the prefix in
                    # untracked, and a later rerun of the crashed shard
                    # would then double-count it
                    raise RuntimeError(
                        f"checkpoint contains a partially absorbed input "
                        f"{ck.source!r} (crashed mid-shard); rerun that "
                        f"input to completion before adding "
                        f"{source_id!r}, or delete the checkpoint")
                if incremental and source_id in prior_sources:
                    skip_input = True
                    stats.extra["incremental_duplicate"] = source_id
                elif not incremental or source_id == ck.source:
                    stats.extra["resume_mode"] = records.skip_to(
                        ck.byte_offset, ck.lines_consumed)
                else:
                    stats.extra["incremental_base"] = prior_sources
                if use_sharded:
                    acc.restore(ck.counts)
                else:
                    acc.set_counts(ck.counts)
        base_mapped = ck.reads_mapped if ck else 0
        base_skipped = ck.reads_skipped if ck else 0
        base_aligned = ck.aligned_bases if ck else 0

        # host decode: native C++ text path when a ReadStream is available
        # (SURVEY.md §2b native component), python record path otherwise
        encoder, batches = self._make_encoder(layout, records, cfg)
        if skip_input:
            # already-absorbed shard: decode nothing (its contribution is in
            # the checkpointed counts; re-reading it would double-count)
            batches = iter(())
        if ck is not None:
            encoder.insertions.array_chunks.extend(ck.insertions.array_chunks)
        stats.aligned_bases = base_aligned

        t0 = time.perf_counter()
        reads_at_ckpt = 0
        for batch in batches:
            if cfg.paranoid:
                self._paranoid_batch(batch, layout.total_len, stats)
            acc.add(batch)
            stats.aligned_bases += batch.n_events
            if (cfg.checkpoint_dir
                    and encoder.n_reads - reads_at_ckpt
                    >= cfg.checkpoint_every):
                self._write_checkpoint(cfg, records, acc, encoder, stats,
                                       base_mapped, base_skipped,
                                       prior_sources)
                reads_at_ckpt = encoder.n_reads
        stats.reads_mapped = base_mapped + encoder.n_reads
        stats.reads_skipped = base_skipped + encoder.n_skipped
        stats.extra["shards"] = shards if use_sharded else 1
        stats.extra["decoder"] = encoder.__class__.__name__
        if getattr(acc, "strategy_used", None):
            stats.extra["pileup"] = dict(acc.strategy_used)
        stats.extra["accumulate_sec"] = round(time.perf_counter() - t0, 4)
        if ck is not None and "incremental_base" not in stats.extra:
            stats.extra["resumed_from_line"] = ck.lines_consumed

        # Post-accumulation tail in exactly two device round trips (each
        # fetch of a computed array costs tens of ms on a tunneled chip):
        # 1. coverage — fetched asynchronously while the host groups
        #    insertion events; host needs it for the LUTs / gates / headers;
        # 2. one fused dispatch (vote + insertion table + insertion vote)
        #    returning one packed uint8 buffer.
        t0 = time.perf_counter()
        if use_sharded:
            cov = np.asarray(acc.counts_host().sum(axis=-1), dtype=np.int64)
            ins = group_insertions(encoder.insertions, layout)
            luts_np = threshold_luts(cfg.thresholds, int(cov.max(initial=0)))
            t_luts = jnp.asarray(luts_np)   # device copy for insertion vote
            syms, _cov_dev = acc.vote(luts_np, cfg.min_depth)
        else:
            counts = acc.counts                               # [L, 6] device
            cov_dev = fused.coverage(counts)
            cov_dev.copy_to_host_async()
            ins = group_insertions(encoder.insertions, layout)  # overlaps
            cov = np.asarray(cov_dev).astype(np.int64)
            t_luts = jnp.asarray(
                threshold_luts(cfg.thresholds, int(cov.max(initial=0))))
        stats.extra["vote_sec"] = round(time.perf_counter() - t0, 4)
        if cfg.paranoid:
            self._paranoid_result(acc, cov, stats)

        t0 = time.perf_counter()
        n_thresholds = len(cfg.thresholds)
        total_len = layout.total_len
        if ins is not None:
            k = len(ins["key_flat"])
            # pad sites and columns to powers of two: pad events scatter
            # into the sacrificial last row (kp > k always), pad columns
            # vote past n_cols and come back as skip sentinels
            kp = fused.next_pow2(k + 1)
            cp = fused.next_pow2(ins["max_cols"])
            site_cov = np.where(ins["key_flat"] >= 0,
                                cov[np.maximum(ins["key_flat"], 0)],
                                0).astype(np.int32)
            use_pallas = getattr(cfg, "ins_kernel", "scatter") == "pallas"

            def padded_scatter_inputs():
                """Pad sites to kp and events to a power of two; pad events
                scatter into the sacrificial row kp-1 (> k always)."""
                scp = np.zeros(kp, dtype=np.int32)
                scp[:k] = site_cov
                ncp = np.zeros(kp, dtype=np.int32)
                ncp[:k] = ins["n_cols"]
                e = len(ins["ev_key"])
                ep = fused.next_pow2(max(e, 1))
                ek = np.full(ep, kp - 1, dtype=np.int32)
                ek[:e] = ins["ev_key"]
                ec = np.zeros(ep, dtype=np.int32)
                ec[:e] = ins["ev_col"]
                eb = np.zeros(ep, dtype=np.int32)
                eb[:e] = ins["ev_code"]
                return scp, ncp, ek, ec, eb

            if use_pallas:
                from ..ops import pallas_insertion

                # shared pallas setup: the kernel's table is
                # [eplan.kp, cp, 6] — pad the site arrays to ITS key
                # padding (a KEY_BLOCK multiple), not the scatter kp
                eplan = pallas_insertion.plan_events(
                    ins["ev_key"], ins["ev_col"], ins["ev_code"], k, cp)
                sc = np.zeros(eplan.kp, dtype=np.int32)
                sc[:k] = site_cov
                nc = np.zeros(eplan.kp, dtype=np.int32)
                nc[:k] = ins["n_cols"]
                interp = jax.default_backend() != "tpu"

            if use_sharded and use_pallas:
                # the position vote already ran position-sharded
                # (acc.vote); only the insertion table + vote remain, so
                # the Pallas kernel runs standalone on the default device
                out = pallas_insertion._table_call(
                    jnp.asarray(eplan.key3), jnp.asarray(eplan.cc3),
                    jnp.asarray(eplan.blk_lo), jnp.asarray(eplan.blk_n),
                    kp=eplan.kp, c6p=eplan.c6p,
                    max_blocks=eplan.max_blocks, interpret=interp)
                table = out.reshape(eplan.kp, eplan.c6p)[
                    :, : cp * 6].reshape(eplan.kp, cp, 6)
                ins_syms = np.asarray(vote_insertions(
                    table, jnp.asarray(sc), jnp.asarray(nc),
                    t_luts))[:, :k, :]                        # [T, K, Cp]
                stats.extra["insertion_kernel"] = "pallas"
            elif use_sharded:
                site_cov_p, n_cols_p, ev_key, ev_col, ev_code = \
                    padded_scatter_inputs()
                table = jnp.zeros((kp, cp, 6), dtype=jnp.int32)
                table = build_insertion_table(
                    table, jnp.asarray(ev_key), jnp.asarray(ev_col),
                    jnp.asarray(ev_code))
                ins_syms = np.asarray(vote_insertions(
                    table, jnp.asarray(site_cov_p), jnp.asarray(n_cols_p),
                    t_luts))[:, :k, :]                        # [T, K, Cp]
            elif use_pallas:
                packed = fused.vote_packed_pallas(
                    counts, t_luts, jnp.asarray(eplan.key3),
                    jnp.asarray(eplan.cc3), jnp.asarray(eplan.blk_lo),
                    jnp.asarray(eplan.blk_n), jnp.asarray(sc),
                    jnp.asarray(nc), cfg.min_depth, cp, eplan.kp,
                    eplan.c6p, eplan.max_blocks, interp)
                out = np.asarray(packed)
                split = n_thresholds * total_len
                syms = out[:split].reshape(n_thresholds, total_len)
                ins_syms = out[split:].reshape(
                    n_thresholds, eplan.kp, cp)[:, :k, :]     # [T, K, Cp]
                stats.extra["insertion_kernel"] = "pallas"
            else:
                site_cov_p, n_cols_p, ev_key, ev_col, ev_code = \
                    padded_scatter_inputs()
                packed = fused.vote_packed(
                    counts, t_luts, jnp.asarray(ev_key), jnp.asarray(ev_col),
                    jnp.asarray(ev_code), jnp.asarray(site_cov_p),
                    jnp.asarray(n_cols_p), cfg.min_depth, cp)
                out = np.asarray(packed)
                split = n_thresholds * total_len
                syms = out[:split].reshape(n_thresholds, total_len)
                ins_syms = out[split:].reshape(
                    n_thresholds, kp, cp)[:, :k, :]           # [T, K, Cp]
        else:
            site_cov = None
            ins_syms = None
            if not use_sharded:
                syms_dev, _ = vote_positions(counts, t_luts, cfg.min_depth)
                syms = np.asarray(syms_dev)                   # [T, L] uint8
        stats.extra["insertions_sec"] = round(time.perf_counter() - t0, 4)

        t0 = time.perf_counter()
        fastas = self._assemble(layout, syms, cov, ins, ins_syms, site_cov,
                                cfg, stats)
        stats.extra["render_sec"] = round(time.perf_counter() - t0, 4)

        if cfg.checkpoint_dir:
            from ..utils import checkpoint as ckpt

            if getattr(cfg, "incremental", False):
                # incremental: the checkpoint IS the accumulated base for
                # the next shard — persist the final state, and record this
                # input as FULLY absorbed so a later rerun of it (even with
                # other shards in between) adds nothing
                done = list(prior_sources)
                if source_id and source_id not in done:
                    done.append(source_id)
                self._write_checkpoint(cfg, records, acc, encoder, stats,
                                       base_mapped, base_skipped, done)
            else:
                # a completed run invalidates its checkpoint: remove it so
                # a rerun starts from scratch, not replaying a finished job
                p = ckpt.path_for(cfg.checkpoint_dir)
                if os.path.exists(p):
                    os.unlink(p)
        return BackendResult(fastas=fastas, stats=stats)

    # -- checkpointing -----------------------------------------------------
    def _write_checkpoint(self, cfg, stream, acc, encoder, stats,
                          base_mapped, base_skipped, sources) -> None:
        from ..utils import checkpoint as ckpt

        ckpt.save(cfg.checkpoint_dir, ckpt.CheckpointState(
            counts=acc.counts_host(),
            lines_consumed=stream.n_lines,
            reads_mapped=base_mapped + encoder.n_reads,
            reads_skipped=base_skipped + encoder.n_skipped,
            aligned_bases=stats.aligned_bases,
            insertions=encoder.insertions,
            source=getattr(cfg, "source_id", ""),
            sources=list(sources),
            byte_offset=stream.byte_offset()))
        stats.extra["checkpoints_written"] = (
            stats.extra.get("checkpoints_written", 0) + 1)

    # -- paranoid mode (SURVEY.md §5 sanitizers) ---------------------------
    def _paranoid_batch(self, batch, total_len: int, stats) -> None:
        """Re-validate scatter inputs before they reach the device."""
        from ..constants import NUM_SYMBOLS

        for w, (starts, codes) in batch.buckets.items():
            rows, cols = np.nonzero(codes < NUM_SYMBOLS)
            pos = starts[rows].astype(np.int64) + cols
            if len(pos) and (pos.min() < 0 or pos.max() >= total_len):
                raise RuntimeError(
                    "paranoid: scatter position out of bounds "
                    f"(width-{w} bucket, range [{pos.min()}, {pos.max()}], "
                    f"genome length {total_len})")
            bad = (codes > NUM_SYMBOLS - 1) & (codes != 255)
            if bad.any():
                raise RuntimeError(
                    f"paranoid: {int(bad.sum())} invalid symbol codes in "
                    f"width-{w} bucket")
        stats.extra["paranoid_batches"] = (
            stats.extra.get("paranoid_batches", 0) + 1)

    def _paranoid_result(self, acc, cov: np.ndarray, stats) -> None:
        counts = acc.counts_host()
        if (counts < 0).any():
            raise RuntimeError("paranoid: negative pileup count")
        if not np.array_equal(counts.sum(axis=-1), cov):
            raise RuntimeError("paranoid: coverage != sum of count lanes")
        if int(cov.sum()) != stats.aligned_bases:
            raise RuntimeError(
                f"paranoid: device event total {int(cov.sum())} != host "
                f"accounting {stats.aligned_bases}")
        stats.extra["paranoid_result_ok"] = True

    def _make_encoder(self, layout, records, cfg: RunConfig):
        """Pick the host decode path; returns (encoder, batch iterator)."""
        from ..encoder.events import GenomeLayout, ReadEncoder  # noqa: F811
        from ..io.sam import ReadStream

        if isinstance(records, ReadStream) and cfg.decoder != "py":
            from ..encoder import native_encoder

            if native_encoder.available():
                enc = native_encoder.NativeReadEncoder(
                    layout, maxdel=cfg.maxdel, strict=cfg.strict,
                    on_lines=records.add_lines, on_bytes=records.add_bytes)
                return enc, enc.encode_blocks(records.blocks())
            if cfg.decoder == "native":
                from .. import native

                raise RuntimeError("--decoder native requested but the C++ "
                                   f"decoder is unavailable: "
                                   f"{native.load_error()}")
        enc = ReadEncoder(layout, maxdel=cfg.maxdel, strict=cfg.strict)
        source = records.records() if isinstance(records, ReadStream) \
            else records
        return enc, enc.encode_segments(source, cfg.chunk_reads)

    # -- host-side rendering ---------------------------------------------
    def _assemble(self, layout, syms: np.ndarray, cov: np.ndarray, ins,
                  ins_syms, site_cov, cfg: RunConfig,
                  stats: BackendStats) -> Dict[str, List[FastaRecord]]:
        n_thresholds = syms.shape[0]
        fastas: Dict[str, List[FastaRecord]] = {}

        for ci, name in enumerate(layout.names):
            off = int(layout.offsets[ci])
            length = int(layout.lengths[ci])
            ref_cov = cov[off:off + length]
            sumcov_base = int(ref_cov.sum())
            if sumcov_base == 0:
                continue  # zero-coverage prune (sam2consensus.py:334-340)

            # insertion sites for this contig, emittable ones only:
            # local key within [0, length) and site depth passes the gates
            # (emission is nested inside cov>0 and cov>=min_depth branches,
            # sam2consensus.py:356-385).
            site_rows = np.zeros(0, dtype=np.int64)
            if ins is not None:
                mask = ((ins["key_contig"] == ci)
                        & (ins["key_local"] >= 0)
                        & (ins["key_local"] < length))
                site_rows = np.nonzero(mask)[0]
                locs = ins["key_local"][site_rows].astype(np.int64)
                order = np.argsort(locs, kind="stable")
                site_rows, locs = site_rows[order], locs[order]
                depth_ok = (cov[off + locs] > 0) & (
                    cov[off + locs] >= cfg.min_depth)
                site_rows, locs = site_rows[depth_ok], locs[depth_ok]

            for t in range(n_thresholds):
                base = syms[t, off:off + length]
                if len(site_rows):
                    pieces: List[bytes] = []
                    prev = 0
                    extra_cov = 0
                    for row, loc in zip(site_rows, locs):
                        cols = ins_syms[t, row][ins_syms[t, row] != 0]
                        pieces.append(base[prev:loc + 1].tobytes())
                        pieces.append(cols.tobytes())
                        extra_cov += int(site_cov[row]) * len(cols)
                        prev = loc + 1
                    pieces.append(base[prev:].tobytes())
                    raw = b"".join(pieces)
                    sumcov = sumcov_base + extra_cov
                else:
                    raw = base.tobytes()
                    sumcov = sumcov_base

                seq = raw.decode("latin-1").replace("\x00", cfg.fill)
                if len(seq) - seq.count("-") == 0:
                    continue  # empty-sequence drop (sam2consensus.py:400-406)
                header = format_header(cfg.prefix, cfg.thresholds[t], name,
                                       sumcov, seq)
                fastas.setdefault(name, []).append(FastaRecord(header, seq))
                stats.consensus_bases += len(seq)

        return fastas
