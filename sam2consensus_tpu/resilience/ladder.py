"""Graceful-degradation ladder for the device path.

When retries can't fix it, demote it: the count tensor is a
sum-decomposable sufficient statistic, so at any point the accumulated
state can be fetched off the failing path and the run continued on a
simpler one without losing a single counted base.

Accumulation rungs (top = fastest, bottom = most survivable)::

    device kernel (pallas / mxu / autotune)
      └─> device scatter        (same accumulator, kernel pinned off)
            └─> host pileup     (native C++ slab walk; no device at all)

Tail rungs::

    device fused tail  ──>  host-routed tail (cpu-committed counts;
                            native C++ vote when the library loads,
                            the XLA CPU fused tail otherwise)

Demotion protocol (ResilientDispatcher.add / the backend's tail loop):

1. the failing dispatch UNIT — one width bucket, or one half of a
   capacity split; the same granularity at which the accumulators
   commit — has made no committed contribution (injection sites raise
   before dispatch; real transport errors mean the op never landed —
   see the exactness note below);
2. the accumulator demotes: kernel rungs mutate the existing
   accumulator in place; the host rung fetches ``counts_host()`` into a
   :class:`~..ops.pileup.HostPileupAccumulator`;
3. ONLY the failed unit replays on the demoted path — units of the
   batch that already committed are never re-dispatched;
4. an EMERGENCY CHECKPOINT is written once the whole batch has landed
   (the first consistent batch boundary), so a hard crash during the
   degraded remainder still resumes — and the demotion itself is
   durable evidence in the metrics/trace exports
   (``resilience/demotions``, ``resilience/emergency_checkpoints``).

Exactness note: retries and demotions are exact for every injected
fault (sites raise before side effects, and the retry/replay unit
matches the commit unit) and for transport failures where the dispatch
never committed.  A REAL device failure that lands mid-UNIT (a bucket
whose scatter ran some row slices before dying) can still double-count
that unit's committed slices on replay; the paranoid-mode invariants
(``--paranoid``) detect exactly that, and the emergency checkpoint
keeps the blast radius to one bucket.  True exactly-once under
arbitrary mid-unit loss would need per-slice idempotence tokens —
out of scope here and called out in README "Failure semantics".
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Tuple

import numpy as np

from .. import observability as obs
from . import faultinject
from .policy import DATA, PASSTHROUGH, RetryPolicy, classify

#: smallest bucket-row count a capacity split will produce; below this
#: an OOM is not a batch-size problem and demotion is the answer
MIN_SPLIT_ROWS = 8


def _record_demotion(stage: str, frm: str, to: str, reason: str,
                     checkpointed: bool) -> None:
    reg = obs.metrics()
    reg.add("resilience/demotions", 1)
    reg.add(f"resilience/demotions/{stage}", 1)
    reg.gauge(f"resilience/ladder/{stage}").set_info(
        {"from": frm, "to": to, "reason": reason,
         "emergency_checkpoint": bool(checkpointed)})
    obs.tracer().event("resilience/demotion", stage=stage,
                       **{"from": frm, "to": to}, reason=reason,
                       emergency_checkpoint=bool(checkpointed))


def job_rungs(snapshot: dict) -> dict:
    """The degradation rungs a finished run ENDED on, read back from its
    registry snapshot's ``resilience/ladder/<stage>`` gauges — the
    serve-mode per-job isolation surface (sam2consensus_tpu/serve): a
    warm server asserts the job AFTER a faulting one returns ``{}``
    here, i.e. the previous job's demotions never leaked.  Keys are the
    stages that demoted (``pileup``, ``tail``), values the rung landed
    on; an empty dict means the run never left the fast path."""
    rungs = {}
    for stage in ("pileup", "tail"):
        g = snapshot.get("gauges", {}).get(f"resilience/ladder/{stage}")
        if g is not None and g.get("info"):
            rungs[stage] = g["info"].get("to", "")
    return rungs


def job_host_rung_config(cfg):
    """The JOB-level demotion: a whole-job re-run pinned to the
    ladder's bottom rung (host pileup, plain packed5 wire, single
    shard).  Used by the serve watchdog after a hang — a wedged
    dispatch says nothing about WHICH device stage wedged, so the only
    rung known to avoid it is the one that never touches the device
    path at all — and by admission control to keep a degraded tenant's
    jobs off the fleet's device path (serve/admission.py)."""
    import dataclasses

    return dataclasses.replace(cfg, pileup="host", wire="packed5",
                               shards=1, shard_mode="auto")


def record_job_demotion(registry, reason: str) -> None:
    """Mark a registry (a serve job's) as having run on the job-level
    host rung, in the same ``resilience/ladder/pileup`` gauge shape
    :func:`job_rungs` reads — so a watchdog-retried or tenant-pinned
    job shows ``rungs == {"pileup": "host"}`` exactly like an in-run
    ladder demotion would."""
    registry.add("resilience/demotions", 1)
    registry.add("resilience/demotions/job", 1)
    registry.gauge("resilience/ladder/pileup").set_info(
        {"from": "device", "to": "host", "reason": reason,
         "emergency_checkpoint": False, "job_level": True})


def pileup_level(acc) -> str:
    """Name the accumulation rung ``acc`` currently sits on."""
    from ..ops.pileup import HostPileupAccumulator, PileupAccumulator

    if isinstance(acc, HostPileupAccumulator):
        return "host"
    if isinstance(acc, PileupAccumulator):
        strat = acc.strategy
    else:                           # sharded accumulators (parallel/*)
        strat = getattr(acc, "pileup", "scatter")
    if strat == "scatter" and getattr(acc, "_tuner", None) is None:
        return "device_scatter"
    return f"device_{strat}"


def demote_pileup(acc, total_len: int) -> Tuple[Optional[object], str]:
    """One rung down; returns ``(new_acc, level)`` or ``(None, "")``
    when already on the bottom rung (host)."""
    from ..ops.pileup import HostPileupAccumulator, PileupAccumulator

    if isinstance(acc, HostPileupAccumulator):
        return None, ""
    # rung 1: pin the device kernel off — the autotuner and any explicit
    # pallas/mxu choice demote to the plain XLA scatter (a trace/compile
    # failure in a kernel must not kill the run when scatter would work).
    # The wire codec pins off with it: a failure at the wire_encode /
    # decode boundary must cost ONE rung, not walk the whole ladder, so
    # the demoted scatter rung ships the plain packed5 lanes.
    if isinstance(acc, PileupAccumulator):
        if acc.strategy != "scatter" or acc._tuner is not None \
                or getattr(acc, "wire", "packed5") != "packed5":
            acc.strategy = "scatter"
            acc._tuner = None
            acc.wire = "packed5"
            return acc, "device_scatter"
    elif getattr(acc, "pileup", "scatter") != "scatter" \
            or getattr(acc, "_tuner", None) is not None \
            or getattr(acc, "wire", "packed5") != "packed5":
        acc.pileup = "scatter"
        acc._tuner = None
        if hasattr(acc, "wire"):
            acc.wire = "packed5"
        return acc, "device_scatter"
    # rung 2: off the device entirely — fetch the accumulated counts
    # (sum-decomposable state, exact at any boundary) into the host
    # accumulator; the remainder of the stream accumulates at native
    # memory speed and the tail routes host-side
    host = HostPileupAccumulator(total_len)
    host.set_counts(np.asarray(acc.counts_host(), dtype=np.int32))
    # carry the wire accounting: the pre-demotion transfers happened and
    # must stay in the run's h2d bill
    host.bytes_h2d = int(getattr(acc, "bytes_h2d", 0))
    return host, "host"


def demote_tail(acc, total_len: int):
    """Demote the TAIL off the device: host-committed counts routed to
    the local XLA CPU backend (or the native C++ vote, which the
    link-free tail path picks on its own when the library loads).
    Returns the (possibly new) accumulator."""
    import jax

    from ..ops.pileup import HostPileupAccumulator

    if not isinstance(acc, HostPileupAccumulator):
        host = HostPileupAccumulator(total_len)
        host.set_counts(np.asarray(acc.counts_host(), dtype=np.int32))
        host.bytes_h2d = int(getattr(acc, "bytes_h2d", 0))
        acc = host
    acc.invalidate_upload()            # drop any default-device upload
    if jax.default_backend() != "cpu":
        try:
            cpus = jax.devices("cpu")
            acc.tail_device = cpus[0] if cpus else None
        except RuntimeError:
            acc.tail_device = None
    return acc


def demote_tail_and_record(acc, total_len: int, exc: BaseException,
                           checkpoint_cb: Optional[Callable] = None):
    """Tail demotion with the full recovery story recorded: emergency
    checkpoint FIRST (the accumulate phase is complete, so the current
    counts are a consistent boundary — persist them before touching
    anything), then route the tail host-side.  Returns the (possibly
    new) accumulator; the caller re-runs the tail with injection
    suppressed (the host rung is the ladder's bottom)."""
    checkpointed = False
    if checkpoint_cb is not None:
        checkpoint_cb(acc)
        checkpointed = True
        obs.metrics().add("resilience/emergency_checkpoints", 1)
        obs.tracer().event("resilience/emergency_checkpoint",
                           stage="tail", level="host")
    acc = demote_tail(acc, total_len)
    _record_demotion("tail", "device", "host",
                     f"{type(exc).__name__}: {exc}", checkpointed)
    return acc


def split_batch(batch):
    """Split a SegmentBatch's buckets in half row-wise (capacity/OOM
    recovery: the halves dispatch as two smaller slabs).  Staged device
    operands are dropped — they belong to the failing dispatch.
    Returns a list of 1-2 batches (1 when nothing is splittable)."""
    from ..encoder.events import SegmentBatch

    halves = ({}, {})
    splittable = False
    for w, (starts, codes) in batch.buckets.items():
        n = len(starts)
        if n >= 2 * MIN_SPLIT_ROWS:
            mid = n // 2
            halves[0][w] = (starts[:mid], codes[:mid])
            halves[1][w] = (starts[mid:], codes[mid:])
            splittable = True
        else:
            halves[0][w] = (starts, codes)
    if not splittable:
        return [batch]
    return [SegmentBatch(buckets=h, n_reads=0, n_events=0)
            for h in halves if h]


class ResilientDispatcher:
    """The accumulate loop's failure contract, in one place.

    ``add(acc, batch)`` dispatches one batch under the retry policy and
    returns the accumulator to use from now on (the same object, or the
    demoted one).  ``checkpoint_cb(acc)`` — when provided — persists an
    emergency checkpoint at each demotion boundary (the backend wires
    it to its ``_write_checkpoint``); ``on_demote(acc)`` lets the
    backend rebind prefetch staging to the new accumulator.

    The RETRY/REPLAY UNIT matches the COMMIT UNIT: a batch is dispatched
    as one single-bucket sub-batch per width (device commits happen per
    bucket inside every accumulator's ``add``), and a capacity split's
    halves are each their own unit.  A failure therefore only ever
    retries or replays work that has NOT committed — a multi-bucket
    batch whose second bucket dies does not re-scatter its first.
    """

    def __init__(self, policy: RetryPolicy, total_len: int,
                 checkpoint_cb: Optional[Callable] = None,
                 on_demote: Optional[Callable] = None):
        self.policy = policy
        self.total_len = total_len
        self.checkpoint_cb = checkpoint_cb
        self.on_demote = on_demote
        self.demotions = 0             # ladder steps taken this run
        self._acc = None
        self._pending: list = []

    # -- one dispatch attempt ------------------------------------------
    def _attempt(self, unit) -> None:
        from ..ops.pileup import HostPileupAccumulator

        if not isinstance(self._acc, HostPileupAccumulator):
            # the host rung carries no injection sites: it IS the
            # bottom of the ladder.  job_hang sits on the same device
            # boundary but SLEEPS instead of raising (a wedged XLA
            # dispatch, faultinject.py) — the serve watchdog's prey.
            faultinject.fault_check("job_hang")
            faultinject.fault_check("accumulate")
        self._acc.add(unit)

    def _dispatch_unit(self, unit, depth: int = 0) -> None:
        """Policy-run one unit; CAPACITY splits it and recurses on the
        halves (each its own unit), persistent failure demotes and
        replays THIS unit only."""

        def on_capacity(exc):
            if depth >= 4:
                raise exc              # splitting isn't helping: persist
            parts = split_batch(unit)
            if len(parts) == 1:
                raise exc              # nothing left to split
            reg = obs.metrics()
            reg.add("resilience/capacity_splits", 1)
            # predicted-vs-actual at the moment the rung fired
            # (observability/memplane.py): the capacity model's
            # prediction next to the tracked/process/device residency,
            # so the split threshold is evidence, not folklore
            from ..observability import memplane

            actuals = memplane.capacity_actuals()
            reg.gauge("resilience/capacity_split").set_info(
                {"depth": depth,
                 "error": f"{type(exc).__name__}: {exc}", **actuals})
            obs.tracer().event("resilience/capacity_split",
                               depth=depth,
                               error=f"{type(exc).__name__}: {exc}",
                               **{k: v for k, v in actuals.items()
                                  if v is not None})
            for part in parts:
                self._dispatch_unit(part, depth + 1)

        while True:
            try:
                self.policy.run(lambda: self._attempt(unit),
                                site="pileup", on_capacity=on_capacity)
                return
            except BaseException as exc:
                kind = classify(exc)
                if kind in (PASSTHROUGH, DATA) \
                        or self.policy.on_error != "fallback":
                    # DATA: malformed input fails identically on every
                    # rung — demoting would re-decode the same poison
                    # bytes on a slower path and still fail
                    raise
                frm = pileup_level(self._acc)
                new_acc, level = demote_pileup(self._acc, self.total_len)
                if new_acc is None:
                    raise              # bottom rung already: truly fatal
                self._acc = new_acc
                if self.on_demote is not None:
                    self.on_demote(new_acc)
                self._pending.append((frm, level, exc))
                # loop: replay ONLY this unit on the demoted rung;
                # already-committed units of the batch are not re-run

    def _units(self, batch) -> list:
        """One single-bucket sub-batch per width — the commit unit of
        every accumulator's ``add`` (staged operands follow their
        bucket).  Fused/empty batches pass through whole."""
        from ..encoder.events import SegmentBatch

        if batch.accumulated or not batch.buckets:
            return [batch]
        units = []
        for w in sorted(batch.buckets):
            staged = {w: batch.staged[w]} if w in batch.staged else {}
            units.append(SegmentBatch(buckets={w: batch.buckets[w]},
                                      staged=staged))
        return units

    # -- public entry ---------------------------------------------------
    def add(self, acc, batch):
        """Dispatch ``batch``; returns the accumulator for the NEXT
        batch (demoted when the ladder stepped down).

        A failing replay after a demotion continues DOWN the ladder
        (kernel → scatter → host) until a rung absorbs the unit or the
        bottom rung itself fails.  The emergency checkpoint is written
        once per batch, after every unit has landed — the first
        consistent batch boundary (the stream offsets already include
        this batch's lines, so its counts must too; the backend runs
        serial decode whenever checkpointing is on, so the stream never
        reads ahead of the consumer).
        """
        self._acc = acc
        self._pending = []
        t0 = time.perf_counter()
        for unit in self._units(batch):
            self._dispatch_unit(unit)
        acc = self._acc
        if self._pending:
            self.demotions += len(self._pending)
            checkpointed = False
            if self.checkpoint_cb is not None:
                self.checkpoint_cb(acc)
                checkpointed = True
                obs.metrics().add("resilience/emergency_checkpoints", 1)
                obs.tracer().event("resilience/emergency_checkpoint",
                                   stage="pileup",
                                   level=self._pending[-1][1])
            for frm, level, exc in self._pending:
                _record_demotion("pileup", frm, level,
                                 f"{type(exc).__name__}: {exc}",
                                 checkpointed)
            obs.metrics().observe("resilience/demotion_sec",
                                  time.perf_counter() - t0)
        return acc
