"""Deterministic, seed-addressable fault injection for the device path.

Faults are injected at named SITES — the device-touching boundaries of
the pipeline — and raise exceptions indistinguishable (to the policy
layer) from the real failure modes they model:

====================  =====================================================
site                  boundary
====================  =====================================================
``device_put``        host→device operand/counts transfer
``pileup_dispatch``   a device accumulator's per-slab dispatch
``accumulate``        the backend's per-batch device accumulate step
``vote``              the fused tail dispatch (vote + stats)
``insertion_build``   the insertion table build / vote dispatch
``link_probe``        the startup link probe (utils/linkprobe.py)
``wire_encode``       the delta8 wire-codec slab encode (wire/codec.py;
                      fires on the staging thread AND the consumer's
                      unstaged fallback, so a persistent fault walks
                      the ladder to the wire-free host rung)
``serve_decode_ahead``the serve runner's decode-ahead thread, per
                      decoded batch (serve/runner.py; checked against
                      the RUNNER's queue-lifetime injector rather than
                      the per-job one, so a spec's call counts stay
                      deterministic across the queue)
``journal_write``     a serve job-journal segment append
                      (serve/journal.py; runner-scope injector too)
``job_hang``          the per-unit device dispatch (next to
                      ``accumulate``) — but instead of raising
                      immediately, a firing rule SLEEPS
                      ``S2C_FAULT_HANG_S`` seconds (default 3600)
                      first, modeling a wedged XLA dispatch that never
                      returns; the serve watchdog (serve/runner.py) is
                      what is supposed to notice.  The rule's kind is
                      what the sleep eventually raises, if it wakes.
``session_wave_append``a streaming session's per-wave absorb step
                      (serve/session.py), fired after the durable
                      ``wave_received`` intent but before the wave's
                      ``wave_absorbed`` commit — the crash window the
                      count-bank rule exists for: the wave's partition
                      is invalidated whole and replayed, never
                      half-counted
``session_revote``    a streaming session's re-vote dispatch (the
                      scatter-new-reads + vote path that never
                      re-ingests; serve/session.py)
``ingest_conn``       the network front door's per-request handling
                      (serve/stream_server.py) — models a connection
                      torn mid-request; the server must answer a typed
                      5xx (or drop the socket) and stay alive
``mem_alloc``         the device count-tensor allocation boundary
                      (ops/pileup.py ``PileupAccumulator``) — the
                      memory plane's OOM-forensics test hook
                      (observability/memplane.py): an ``oom`` rule here
                      models host/HBM exhaustion at allocation time,
                      exercising the CAPACITY classification, the
                      ``mem_dump.json`` forensic write, and the serve
                      host-rung demotion.  The host accumulator carries
                      no site (the bottom rung, by construction).
====================  =====================================================

Spec grammar (CLI ``--fault-inject`` or env ``S2C_FAULT_INJECT``;
comma-separated specs)::

    site:kind:after_n[:times]

* ``kind`` — ``rpc`` (ConnectionError, transient), ``timeout``
  (TimeoutError, transient), ``oom`` (MemoryError "RESOURCE_EXHAUSTED",
  capacity), ``fatal`` (RuntimeError, fatal), ``trace`` (RuntimeError
  modeling a kernel trace failure, fatal);
* ``after_n`` — integer: the first N calls to the site pass, the
  (N+1)-th fails; or ``pP`` (e.g. ``p0.05``): each call fails with
  probability P, decided by a seed-addressable hash of
  ``(seed, site, call_index)`` — deterministic run-to-run for a given
  ``S2C_FAULT_SEED`` (default 0);
* ``times`` — the rule's total fault budget: how many calls fail once
  triggered (counted specs default to 1; probabilistic specs default
  to unbounded); ``inf``/``*``/``-1`` = persistent (every matching
  call from then on), the shape that forces a ladder demotion.

Counting is per-site and per-:func:`configure` (the jax backend
configures the injector at run start, so bench warm/timed repetitions
and test runs each count from zero).  The ladder's demoted host rung
runs under :func:`suppress` — injection models DEVICE-path faults, and
the last rung is by construction host-side.
"""

from __future__ import annotations

import os
import zlib
from typing import Dict, List, Optional

SITES = ("device_put", "pileup_dispatch", "accumulate", "vote",
         "insertion_build", "link_probe", "wire_encode",
         "serve_decode_ahead", "journal_write", "job_hang",
         "bam_inflate", "ingest_decode_shard", "mem_alloc",
         "session_wave_append", "session_revote", "ingest_conn")

#: how long a firing ``job_hang`` rule sleeps before raising (seconds);
#: far past any sane --job-timeout, so the watchdog always wins the race
DEFAULT_HANG_S = 3600.0


def _hang_seconds() -> float:
    try:
        return max(0.0, float(os.environ.get("S2C_FAULT_HANG_S",
                                             DEFAULT_HANG_S)))
    except ValueError:
        return DEFAULT_HANG_S

KINDS = ("rpc", "timeout", "oom", "fatal", "trace")


class InjectedFault(Exception):
    """Mixin marking an exception as injected (tests introspect it)."""

    site = ""
    kind = ""


class InjectedRpcError(InjectedFault, ConnectionError):
    """Models a dropped tunnel / RPC transport error (transient)."""


class InjectedTimeoutError(InjectedFault, TimeoutError):
    """Models a hung dispatch past its deadline (transient)."""


class InjectedOomError(InjectedFault, MemoryError):
    """Models device HBM exhaustion (capacity: split/halve and retry)."""


class InjectedFatalError(InjectedFault, RuntimeError):
    """Models a non-retryable device failure (ladder territory)."""


class InjectedTraceError(InjectedFault, RuntimeError):
    """Models a kernel trace/compile failure (fatal at kernel level)."""


_KIND_EXC = {
    "rpc": (InjectedRpcError, "injected: UNAVAILABLE: connection dropped"),
    "timeout": (InjectedTimeoutError,
                "injected: DEADLINE_EXCEEDED: dispatch timed out"),
    "oom": (InjectedOomError,
            "injected: RESOURCE_EXHAUSTED: out of memory allocating"),
    "fatal": (InjectedFatalError,
              "injected: INTERNAL: device core dumped"),
    "trace": (InjectedTraceError,
              "injected: Mosaic lowering failed while tracing kernel"),
}

PERSISTENT = -1


class _Rule:
    __slots__ = ("site", "kind", "after_n", "prob", "times", "fired")

    def __init__(self, site: str, kind: str, after_n: Optional[int],
                 prob: Optional[float], times: int):
        self.site = site
        self.kind = kind
        self.after_n = after_n
        self.prob = prob
        self.times = times
        self.fired = 0


def parse_spec(spec: str) -> List[_Rule]:
    """Parse a comma-separated fault spec; raises ValueError on nonsense
    (unknown site/kind, malformed counts) so a typo'd --fault-inject
    fails the run up front instead of silently injecting nothing."""
    rules: List[_Rule] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) not in (3, 4):
            raise ValueError(
                f"fault spec {part!r}: expected site:kind:after_n[:times]")
        site, kind, trigger = fields[0], fields[1], fields[2]
        if site not in SITES:
            raise ValueError(
                f"fault spec {part!r}: unknown site {site!r} "
                f"(use one of {', '.join(SITES)})")
        if kind not in KINDS:
            raise ValueError(
                f"fault spec {part!r}: unknown kind {kind!r} "
                f"(use one of {', '.join(KINDS)})")
        after_n: Optional[int] = None
        prob: Optional[float] = None
        if trigger.startswith("p"):
            try:
                prob = float(trigger[1:])
            except ValueError:
                raise ValueError(
                    f"fault spec {part!r}: bad probability {trigger!r} "
                    f"(use e.g. p0.05)") from None
            if not 0.0 <= prob <= 1.0:
                raise ValueError(
                    f"fault spec {part!r}: probability {prob} outside "
                    f"[0, 1]")
        else:
            try:
                after_n = int(trigger)
            except ValueError:
                raise ValueError(
                    f"fault spec {part!r}: bad after_n {trigger!r} "
                    f"(an integer call count, or pP for probabilistic)"
                ) from None
            if after_n < 0:
                raise ValueError(
                    f"fault spec {part!r}: after_n must be >= 0")
        # counted specs default to ONE fault; probabilistic specs keep
        # rolling their coin forever unless an explicit budget caps them
        times = PERSISTENT if prob is not None else 1
        if len(fields) == 4:
            t = fields[3]
            if t in ("inf", "*"):
                times = PERSISTENT
            else:
                try:
                    times = int(t)
                except ValueError:
                    raise ValueError(
                        f"fault spec {part!r}: bad times {t!r} "
                        f"(an integer, 'inf', or '*')") from None
                if times == -1:
                    times = PERSISTENT
                elif times < 1:
                    raise ValueError(
                        f"fault spec {part!r}: times must be >= 1, "
                        f"'inf', '*', or -1")
        rules.append(_Rule(site, kind, after_n, prob, times))
    return rules


class FaultInjector:
    """Seed-addressable injector over a parsed rule set.

    ``check(site)`` increments the site's call counter, evaluates every
    rule bound to the site in spec order, and raises the first match
    (recording ``fault/injected`` + ``fault/injected/<site>`` counters
    and a ``fault/injected`` tracer event first, so the recovery story
    is visible even when the fault is later swallowed by a retry).
    """

    def __init__(self, rules: List[_Rule], seed: int = 0):
        self.rules = rules
        self.seed = seed
        self.calls: Dict[str, int] = {}
        self.injected: Dict[str, int] = {}
        self._suppress = 0

    def _roll(self, site: str, n: int, prob: float) -> bool:
        """Deterministic per-call coin: crc32 of (seed, site, n)."""
        h = zlib.crc32(f"{self.seed}:{site}:{n}".encode())
        return (h / 0xFFFFFFFF) < prob

    def check(self, site: str) -> None:
        if self._suppress:
            return
        n = self.calls.get(site, 0)
        self.calls[site] = n + 1
        for rule in self.rules:
            if rule.site != site:
                continue
            budget = (rule.times == PERSISTENT
                      or rule.fired < rule.times)
            if rule.prob is not None:
                fire = budget and self._roll(site, n, rule.prob)
            else:
                fire = budget and n >= rule.after_n
            if not fire:
                continue
            rule.fired += 1
            self.injected[site] = self.injected.get(site, 0) + 1
            exc_cls, msg = _KIND_EXC[rule.kind]
            exc = exc_cls(f"{msg} (site={site}, call #{n})")
            exc.site = site
            exc.kind = rule.kind
            from .. import observability as obs

            reg = obs.metrics()
            reg.add("fault/injected", 1)
            reg.add(f"fault/injected/{site}", 1)
            hang = _hang_seconds() if site == "job_hang" else 0.0
            obs.tracer().event("fault/injected", site=site,
                               kind=rule.kind, call=n,
                               **({"hang_s": hang} if hang else {}))
            if hang:
                # the wedged-dispatch model: counters/trace record the
                # injection FIRST (the thread is about to stop making
                # progress), then the dispatch just... doesn't return.
                # The serve watchdog abandons the thread long before
                # the sleep expires; if it ever wakes, the kind's
                # exception surfaces like any other injected fault.
                import time

                time.sleep(hang)
            raise exc


#: process-current injector; None = injection inactive (the fast path —
#: one attribute load + is-None test per site call)
_injector: Optional[FaultInjector] = None


def configure(spec: Optional[str] = None,
              seed: Optional[int] = None) -> Optional[FaultInjector]:
    """Install (or clear) the process-current injector.

    ``spec`` falls back to env ``S2C_FAULT_INJECT``; an empty/absent
    spec clears the injector.  ``seed`` falls back to
    ``S2C_FAULT_SEED`` (default 0).  Returns the installed injector (or
    None).  Called by the jax backend at run start so call counters are
    per-run-deterministic.
    """
    global _injector
    if spec is None:
        spec = os.environ.get("S2C_FAULT_INJECT", "")
    if not spec:
        _injector = None
        return None
    if seed is None:
        seed = int(os.environ.get("S2C_FAULT_SEED", "0"))
    _injector = FaultInjector(parse_spec(spec), seed=seed)
    return _injector


def active() -> Optional[FaultInjector]:
    return _injector


def fault_check(site: str) -> None:
    """Site hook: no-op unless an injector is configured."""
    if _injector is not None:
        _injector.check(site)


class suppress:
    """Context manager exempting a region from injection — the ladder's
    demoted host rung runs under this (the injector models DEVICE-path
    faults; the last rung is host-side by construction).  Depth-counted,
    not thread-isolated: the only concurrent thread (decode prefetch)
    carries no injection sites."""

    def __enter__(self):
        if _injector is not None:
            _injector._suppress += 1
        return self

    def __exit__(self, *exc):
        if _injector is not None and _injector._suppress > 0:
            _injector._suppress -= 1
        return False


def _reset_for_tests() -> None:
    global _injector
    _injector = None
