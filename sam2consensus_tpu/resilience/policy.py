"""Retry policy for device dispatches: classify, back off, retry.

Every device-touching call site routes its failures through one
classification so the retry/demote behavior cannot drift between
layers:

* ``TRANSIENT`` — RPC/link/timeout-shaped failures (the tunnel dropped,
  a dispatch deadline expired, the transport reset): retry with
  exponential backoff + deterministic jitter;
* ``CAPACITY`` — device memory exhaustion (OOM): don't just retry the
  same shape — split the slab / halve the work and retry the halves;
* ``FATAL`` — a device-side failure that retrying the same path won't
  fix (kernel trace failure, device core dump): no retry; under
  ``--on-device-error fallback`` the degradation ladder demotes the
  path instead (resilience/ladder.py);
* ``PASSTHROUGH`` — plain Python errors (KeyError/ValueError/TypeError
  …, including the oracle-parity strict-mode decode errors) and
  process-control exceptions: never retried, never demoted — they are
  bugs or contract errors, and masking them with a host fallback would
  hide them while still costing a full recompute.
* ``DATA`` — the input bytes are malformed (a bad-record error budget
  blown, a poison upload): like PASSTHROUGH it is never retried and
  never demotes a rung — re-reading the same bytes on any rung fails
  identically — but it is its own class so the serve layer can tell "a
  tenant sent us garbage" (fail fast with the quarantine manifest, no
  tenant demotion, count ``serve/admission_poison``) apart from "this
  code path is broken".  Marked by a ``data_error`` attribute on the
  exception (``ingest/badrecords.py``), same marker protocol as
  ``transient``.  Streaming-session wave rejections ride the same
  marker (``serve/session.SessionError`` with a 422 status): a
  malformed or torn wave is quarantined and answered with a typed
  reason — never retried, never a rung demotion, never a wedge.

The classifier is name/message-based for the jax runtime's exception
types (``XlaRuntimeError`` carries its gRPC-style status in the
message) so no jaxlib import is needed here.
"""

from __future__ import annotations

import os
import random
import re
import threading
import time
from typing import Callable, Optional

from .faultinject import (InjectedFatalError, InjectedOomError,
                          InjectedRpcError, InjectedTimeoutError,
                          InjectedTraceError)

TRANSIENT = "transient"
CAPACITY = "capacity"
FATAL = "fatal"
PASSTHROUGH = "passthrough"
DATA = "data"

#: status substrings the jax/gRPC runtime uses for retryable transport
#: failures; checked case-sensitively first (they are SHOUTY status
#: names), then a lowercase sweep for socket-ish message shapes
_TRANSIENT_STATUS = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "CANCELLED",
                     "ABORTED", "UNKNOWN: Stream removed")
_TRANSIENT_RE = re.compile(
    r"connection (reset|refused|dropped|closed)|broken pipe|socket"
    r"|timed? ?out|unreachable|transport|tunnel", re.IGNORECASE)
_CAPACITY_RE = re.compile(
    r"RESOURCE_EXHAUSTED|out of memory|\bOOM\b|failed to allocate"
    r"|allocation .* exceeds", re.IGNORECASE)

#: exception types that are never device failures: re-raise untouched.
#: Strict-mode decode errors (KeyError/IndexError — reference parity is
#: contract, tests/test_differential.py) land here by TYPE, so a retry
#: wrapper around a dispatch can never eat them.
_PASSTHROUGH_TYPES = (KeyboardInterrupt, SystemExit, GeneratorExit,
                      StopIteration, TypeError, ValueError, KeyError,
                      IndexError, AttributeError, NameError,
                      AssertionError, NotImplementedError, ImportError)


class RetriesExhausted(RuntimeError):
    """Raised by :meth:`RetryPolicy.run` when transient/capacity retries
    ran out; carries the last underlying failure as ``__cause__``."""


class AttemptDeadlineExceeded(TimeoutError):
    """A dispatch overran its per-attempt deadline (classified
    transient: a hung tunnel round trip looks exactly like this)."""


class JobDeadlineExceeded(TimeoutError):
    """A serve-mode JOB overran its ``--job-timeout`` wall-clock budget
    (serve/runner.py watchdog).  TimeoutError => classified TRANSIENT:
    the job-level ladder may re-run the job on the host rung, but the
    fleet (the warm server and its queue) is never torn down for it."""


class HungDispatchError(TimeoutError):
    """The serve watchdog saw no dispatch-interval heartbeat for longer
    than the stall budget: a device dispatch (or the decode feeding it)
    is wedged, not slow.  TimeoutError => TRANSIENT, same job-level
    handling as :class:`JobDeadlineExceeded`."""


def classify(exc: BaseException) -> str:
    """Map an exception to TRANSIENT/CAPACITY/FATAL/PASSTHROUGH/DATA."""
    if getattr(exc, "data_error", False):
        # checked FIRST: a data-malformation error must never match the
        # transient/capacity message heuristics below ("exhausted" is in
        # the budget message AND the capacity regex's vocabulary...)
        return DATA
    if isinstance(exc, (InjectedRpcError, InjectedTimeoutError)):
        return TRANSIENT
    if isinstance(exc, InjectedOomError):
        return CAPACITY
    if isinstance(exc, (InjectedFatalError, InjectedTraceError)):
        return FATAL
    if getattr(exc, "transient", False):
        # self-describing transients (e.g. formats.bgzf.BgzfCorruptBlock:
        # storage-level bitrot is transport-shaped) — a marker attribute
        # instead of an import so low layers never cycle into this one.
        # Checked BEFORE the passthrough types: BgzfCorruptBlock IS a
        # ValueError, but it is infrastructure damage, not user input.
        return TRANSIENT
    if isinstance(exc, _PASSTHROUGH_TYPES):
        return PASSTHROUGH
    msg = str(exc)
    if isinstance(exc, MemoryError) or _CAPACITY_RE.search(msg):
        return CAPACITY
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return TRANSIENT
    if any(s in msg for s in _TRANSIENT_STATUS) or _TRANSIENT_RE.search(msg):
        return TRANSIENT
    if isinstance(exc, OSError):
        return TRANSIENT           # EIO/EPIPE-shaped transport failures
    # XlaRuntimeError (a RuntimeError subclass) without a transient or
    # capacity status, kernel lowering failures, anything else device-ish
    return FATAL


class RetryPolicy:
    """Configurable retry with exponential backoff + deterministic jitter
    and optional per-attempt deadlines.

    ``retries`` counts RE-attempts (retries=3 → up to 4 attempts).
    Backoff for attempt ``i`` is ``backoff * 2**i``, capped at
    ``max_backoff``, jittered by ±``jitter`` fraction with a seeded PRNG
    so a run's retry schedule is reproducible (seed-addressable, like
    the fault injector).  ``deadline_s`` (or env
    ``S2C_ATTEMPT_DEADLINE_S``) bounds each attempt: the call runs on a
    watchdog thread and overruns raise :class:`AttemptDeadlineExceeded`
    (transient) — same discipline as the link probe's watchdog, and the
    same caveat: the abandoned attempt's daemon thread may still
    complete later, so deadline-bounded calls must be idempotent (every
    wrapped dispatch here is: accumulation retries replay the same
    slab, tail retries recompute a pure function of the counts).
    """

    def __init__(self, retries: int = 3, backoff: float = 0.25,
                 max_backoff: float = 8.0, jitter: float = 0.1,
                 seed: int = 0, deadline_s: Optional[float] = None,
                 on_error: str = "retry"):
        if on_error not in ("fail", "retry", "fallback"):
            raise ValueError(
                f"on_error={on_error!r}: use fail|retry|fallback")
        self.retries = max(0, int(retries)) if on_error != "fail" else 0
        self.backoff = float(backoff)
        self.max_backoff = float(max_backoff)
        self.jitter = float(jitter)
        self.seed = seed
        self.deadline_s = deadline_s
        self.on_error = on_error
        self._rng = random.Random(seed)

    @classmethod
    def from_config(cls, cfg) -> "RetryPolicy":
        """Policy from RunConfig (+ env overrides: S2C_ON_DEVICE_ERROR
        wins over --on-device-error so the campaign's chaos leg can
        flip an unmodified bench invocation to fallback mode;
        S2C_ATTEMPT_DEADLINE_S enables per-attempt deadlines)."""
        deadline = os.environ.get("S2C_ATTEMPT_DEADLINE_S")
        return cls(
            retries=getattr(cfg, "retries", 3),
            backoff=getattr(cfg, "retry_backoff", 0.25),
            seed=int(os.environ.get("S2C_FAULT_SEED", "0")),
            deadline_s=float(deadline) if deadline else None,
            on_error=os.environ.get(
                "S2C_ON_DEVICE_ERROR",
                getattr(cfg, "on_device_error", "retry")))

    def delay(self, attempt: int) -> float:
        """Backoff before re-attempt ``attempt`` (0-based), jittered."""
        base = min(self.backoff * (2 ** attempt), self.max_backoff)
        return max(0.0, base * (1.0 + self.jitter
                                * self._rng.uniform(-1.0, 1.0)))

    def _call(self, fn: Callable):
        if self.deadline_s is None:
            return fn()
        box: list = []

        def work():
            try:
                box.append(("ok", fn()))
            except BaseException as exc:  # re-raised on the caller side
                box.append(("exc", exc))

        t = threading.Thread(target=work, daemon=True)
        t.start()
        t.join(self.deadline_s)
        if not box:
            raise AttemptDeadlineExceeded(
                f"dispatch exceeded its {self.deadline_s:.3g}s "
                f"per-attempt deadline")
        tag, val = box[0]
        if tag == "exc":
            raise val
        return val

    def run(self, fn: Callable, site: str = "dispatch",
            on_capacity: Optional[Callable] = None,
            sleep: Callable[[float], None] = time.sleep):
        """Run ``fn`` under the policy; returns its result.

        TRANSIENT failures retry with backoff up to ``retries`` times,
        then raise :class:`RetriesExhausted` (cause = last failure).
        CAPACITY failures call ``on_capacity(exc)`` once per failure if
        given — its return value becomes the result (the caller split
        the work and dispatched the halves itself); without a handler
        they retry like transients (the allocator may simply have been
        fragmented by a peer).  FATAL and PASSTHROUGH raise immediately.
        Every retry is recorded: ``resilience/retries`` counter + a
        ``resilience/retry`` tracer event with site/kind/delay.
        """
        from .. import observability as obs

        last: Optional[BaseException] = None
        for attempt in range(self.retries + 1):
            try:
                return self._call(fn)
            except BaseException as exc:
                kind = classify(exc)
                if kind in (PASSTHROUGH, FATAL, DATA):
                    raise
                if self.on_error == "fail":
                    raise             # fail mode: no splits, no retries
                if kind == CAPACITY and on_capacity is not None:
                    return on_capacity(exc)
                last = exc
                if attempt >= self.retries:
                    if self.retries == 0:
                        # no retry budget (--on-device-error fail, or
                        # --retries 0): surface the ORIGINAL exception,
                        # not a wrapper — old-behavior parity
                        raise
                    break
                d = self.delay(attempt)
                reg = obs.metrics()
                reg.add("resilience/retries", 1)
                reg.add(f"resilience/retries/{site}", 1)
                obs.tracer().event("resilience/retry", site=site,
                                   kind=kind, attempt=attempt,
                                   delay_s=round(d, 4),
                                   error=f"{type(exc).__name__}: {exc}")
                if d > 0:
                    sleep(d)
        raise RetriesExhausted(
            f"{site}: {self.retries} retries exhausted "
            f"(last: {type(last).__name__}: {last})") from last
