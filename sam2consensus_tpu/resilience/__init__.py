"""Resilience subsystem: the device path's failure contract.

The north-star is a production service on flaky infrastructure — round
5's own measurement campaign died when the TPU tunnel dropped mid-sweep.
The count tensor is fully sum-decomposable and checkpointable
(utils/checkpoint.py), so no mid-run device failure has to be terminal;
this package threads one consistent failure contract through every
device-touching layer:

* :mod:`.policy` — exception classification (transient / capacity /
  fatal / passthrough) and configurable retry with exponential backoff
  + deterministic jitter and optional per-attempt deadlines;
* :mod:`.ladder` — the graceful-degradation ladder: device kernel →
  device scatter → host pileup for accumulation, and device tail →
  host-routed tail, demoting MID-RUN without losing accumulated counts
  and writing an emergency checkpoint at each demotion boundary;
* :mod:`.faultinject` — deterministic, seed-addressable fault injection
  (``--fault-inject site:kind:after_n[:times]`` / ``S2C_FAULT_INJECT``)
  used by tests and the campaign's chaos bench leg.

Every retry, demotion, and emergency checkpoint is emitted as a
structured observability event/counter (``resilience/*`` and
``fault/*``), so ``--metrics-out`` / ``--trace-out`` show the full
recovery story.

This module deliberately imports only :mod:`.policy` and
:mod:`.faultinject` (both jax-free); :mod:`.ladder` is imported as a
submodule by its consumers to keep ``ops.pileup`` ↔ ``resilience``
import-cycle-free.
"""

from __future__ import annotations

from . import faultinject, policy
from .faultinject import FaultInjector, fault_check
from .policy import (CAPACITY, FATAL, PASSTHROUGH, TRANSIENT, RetryPolicy,
                     RetriesExhausted, classify)

__all__ = [
    "faultinject", "policy", "FaultInjector", "fault_check",
    "RetryPolicy", "RetriesExhausted", "classify",
    "TRANSIENT", "CAPACITY", "FATAL", "PASSTHROUGH",
]
