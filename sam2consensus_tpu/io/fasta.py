"""FASTA output layer, shared by all backends (L7 in SURVEY.md §1).

File naming, record joining, optional wrapping and the per-file messages all
follow ``/root/reference/sam2consensus.py:411-424`` so that output
*directories* — not just sequences — compare byte-identical.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List


@dataclass
class FastaRecord:
    header: str   # full ">..." header line
    seq: str      # unwrapped sequence text


def render_file(records: List[FastaRecord], nchar: int) -> str:
    """Join one reference's records; wrap every ``nchar`` if nonzero."""
    if nchar == 0:
        body = "\n".join(r.header + "\n" + r.seq for r in records)
    else:
        body = "\n".join(
            r.header + "\n" + "\n".join(r.seq[s:s + nchar]
                                        for s in range(0, len(r.seq), nchar))
            for r in records)
    return body + "\n"


def write_outputs(fastas: Dict[str, List[FastaRecord]], outfolder: str,
                  prefix: str, nchar: int, thresholds: List[float],
                  echo=print) -> List[str]:
    """One ``{ref}__{prefix}.fasta`` per reference; returns paths written."""
    paths = []
    for reference, records in fastas.items():
        outnameprefix = reference + "__" + prefix
        path = outfolder + outnameprefix + ".fasta"
        with open(path, "w") as fh:
            fh.write(render_file(records, nchar))
        paths.append(path)
        pcts = [str(int(t * 100)) + "%" for t in thresholds]
        if len(thresholds) == 1:
            echo("Consensus sequence at " + pcts[0] + " saved for "
                 + reference + " in: " + path)
        else:
            echo("Consensus sequences at " + ",".join(pcts) + " saved for "
                 + reference + " in: " + path)
    return paths
