"""Streaming SAM input: header scan and record iteration.

Reproduces the reference's I/O layer (L2/L3 in SURVEY.md §1):

* gzip-or-plain opener keyed on the ``.gz`` suffix
  (``/root/reference/sam2consensus.py:110-114``);
* header pass that reads ``@SQ`` lines positionally — field 1 with every
  ``"SN:"`` substring removed then whitespace-truncated, field 2 with every
  ``"LN:"`` substring removed and int()'d (``sam2consensus.py:160-169``) —
  and stops at the first non-``@`` line (``sam2consensus.py:171-172``);
* record pass that keeps only lines whose CIGAR field is not ``"*"``
  (``sam2consensus.py:195``) and uses exactly four fields: RNAME
  (whitespace-truncated, ``:200``), 0-based POS (``:201``), CIGAR and SEQ
  (``:206``).  No FLAG/MAPQ/quality filtering, matching the reference.

Unlike the reference (two full passes over the file,
``sam2consensus.py:149,180``) this module streams in a single pass: the
header is consumed from the same handle the records then come from.
"""

from __future__ import annotations

import gzip
import io
from dataclasses import dataclass
from typing import Iterable, Iterator, List, Optional, TextIO, Tuple


def opener(filename: str, binary: bool = False, threads: int = 1):
    """Open plain or gzip text by suffix (sam2consensus.py:110-114).

    ``.gz`` files are SNIFFED, not trusted: htslib-written ``.sam.gz``
    are actually BGZF (gzip members with the FEXTRA ``BC`` subfield),
    whose independently-deflated blocks decode through the
    block-parallel reader (``formats/bgzf.py`` — ordered reassembly, so
    downstream semantics are identical) on ``threads`` workers; plain
    single-member gzip keeps the serial streaming path it always had.

    ``binary=True`` returns a bytes handle: the native decoder parses raw
    bytes, so decoding 100s of MB of SAM text to ``str`` on the way in would
    be pure overhead.  (Header lines are still ascii-decoded individually in
    ``read_header``; a non-ascii *body* byte then surfaces as a decode error
    from the C++/Python encoder rather than a ``UnicodeDecodeError``.)
    """
    if filename.endswith(".gz"):
        from ..formats import _fault_check, _metrics
        from ..formats import bgzf as _bgzf

        if _bgzf.is_bgzf(filename):
            # same fault-site/counter wiring as open_alignment_input:
            # the bam_inflate injection site and the format/bgzf_corrupt
            # retry counter apply to THIS entry point too
            raw = _bgzf.BgzfReader(filename, threads=max(1, threads),
                                   fault_check=_fault_check,
                                   metrics=_metrics())
            if binary:
                return raw
            return io.TextIOWrapper(io.BufferedReader(raw),
                                    encoding="ascii", errors="strict")
        raw = gzip.open(filename, "rb")
        if binary:
            return raw
        return io.TextIOWrapper(raw, encoding="ascii", errors="strict")
    if binary:
        return open(filename, "rb")
    return open(filename, "r", encoding="ascii", errors="strict")


@dataclass(frozen=True)
class Contig:
    """One ``@SQ`` header entry, in file order."""
    name: str
    length: int


@dataclass(frozen=True)
class SamRecord:
    """The four fields the consensus algorithm consumes."""
    refname: str
    pos: int          # 0-based leftmost reference position (POS - 1)
    cigar: str
    seq: str


def parse_sq_line(line: str) -> Contig:
    """Positional @SQ parse, faithful to sam2consensus.py:163-164."""
    fields = line.split("\t")
    name = fields[1].replace("SN:", "").split()[0]
    length = int(fields[2].replace("LN:", "").strip())
    return Contig(name, length)


def read_header(handle) -> Tuple[List[Contig], int, str]:
    """Consume header lines; return (contigs, header_line_count, first_body_line).

    ``first_body_line`` is the line that terminated the header ("" at EOF); the
    caller feeds it back into record iteration so a single pass suffices.
    Accepts text or binary handles; header lines are ascii-decoded per line
    (they are few and short), and ``first_body_line`` keeps the handle's type.
    """
    contigs: List[Contig] = []
    n_header = 0
    for line in handle:
        text = line.decode("ascii") if isinstance(line, bytes) else line
        if text.startswith("@"):
            n_header += 1
            if text.startswith("@SQ"):
                contigs.append(parse_sq_line(text))
        else:
            return contigs, n_header, line
    return contigs, n_header, ""


def iter_records(handle: TextIO, first_line: str = "",
                 on_bad=None) -> Iterator[SamRecord]:
    """Yield mapped records (CIGAR != "*"), skipping any stray header lines.

    Mirrors the reference's body loop (sam2consensus.py:191-206); chunked
    reading is an I/O detail there (``readlines(50000)``), not a semantic one,
    so plain line iteration is used here.

    ``on_bad`` is the tolerant-decode hook (``--on-bad-record``): a line
    whose positional parse raises (too few fields, unparsable POS) calls
    ``on_bad(line, exc)`` and iteration continues instead of dying —
    the per-record quarantine contract.  ``None`` (default) keeps the
    strict reference semantics: the parse error propagates.
    """
    def make(line: str) -> Optional[SamRecord]:
        try:
            # the un-rstripped CIGAR probe first, exactly like the
            # reference's body loop: a 6-field line ending "...\t*\n"
            # compares "*\n" != "*" and proceeds to the fields[9]
            # IndexError, it is NOT an unmapped skip
            if line.split("\t")[5] == "*":
                return None
            fields = line.rstrip("\n").split("\t")
            return SamRecord(
                refname=fields[2].split()[0],
                pos=int(fields[3]) - 1,
                cigar=fields[5],
                seq=fields[9],
            )
        except (IndexError, ValueError) as exc:
            if on_bad is None:
                raise
            on_bad(line, exc)
            return None

    if first_line and first_line[0] != "@":
        rec = make(first_line)
        if rec is not None:
            yield rec
    for line in handle:
        if line[0] != "@":
            rec = make(line)
            if rec is not None:
                yield rec


def read_sam(filename: str) -> Tuple[List[Contig], Iterator[SamRecord]]:
    """Open ``filename`` and return (contigs, lazy record iterator)."""
    handle = opener(filename)
    contigs, _n_header, first = read_header(handle)
    return contigs, iter_records(handle, first)


class ReadStream:
    """Single-pass source of SAM body content, as records OR text blocks.

    Backends consume whichever form fits: the CPU oracle and the Python
    encoder pull parsed ``records()``; the native decoder pulls raw
    ``blocks()`` (whole lines) and parses in C++.  Both report consumed body
    lines through ``add_lines`` so the CLI's progress accounting
    (``sam2consensus.py:224-225``: every body line counts, including
    unmapped and stray header lines) is identical either way.
    """

    def __init__(self, handle: TextIO, first_line: str = "", on_lines=None):
        self.handle = handle
        self.first = first_line
        self.on_lines = on_lines
        self.n_lines = 0
        #: bytes of body content consumed so far (consumers report via
        #: add_bytes / the counted iterators below; ascii input makes
        #: str-length == byte-length on text handles)
        self.n_bytes = 0
        # absolute offset of the body start, when the handle can report it.
        # Binary handles (incl. GzipFile, in uncompressed offsets) keep
        # tell() accurate through read_header's line iteration; a
        # TextIOWrapper raises here ("telling position disabled") and the
        # stream falls back to line-skipping resume.
        try:
            self._body_start = self.handle.tell() - len(first_line)
        except (AttributeError, OSError, ValueError):
            self._body_start = None
        #: absolute input offset of the most recent ``blocks()`` block
        #: (None when the handle cannot locate itself) — the strict-
        #: error / quarantine offset base
        self.block_offset: Optional[int] = None

    def byte_offset(self) -> int:
        """Absolute input offset matching ``n_lines``; -1 if unknown."""
        if self._body_start is None:
            return -1
        return self._body_start + self.n_bytes

    def skip_to(self, byte_offset: int, k: int) -> str:
        """Position after ``k`` body lines: seek straight to the recorded
        byte offset when both sides can (O(1) resume), else re-read and
        discard ``k`` lines.  Returns the mode used ("seek" or "lines")."""
        if k <= 0:
            return "none"
        if byte_offset >= 0 and self._body_start is not None:
            try:
                self.handle.seek(byte_offset)
            except (AttributeError, OSError, ValueError):
                pass
            else:
                self.first = ""
                self.n_lines = k
                self.n_bytes = byte_offset - self._body_start
                return "seek"
        self.skip_lines(k)
        return "lines"

    def skip_lines(self, k: int) -> None:
        """Skip ``k`` body lines (checkpoint resume); they still count."""
        if k <= 0:
            return
        n = k
        if self.first:
            self.n_bytes += len(self.first)
            self.first = ""
            n -= 1
        for _ in range(n):
            self.n_bytes += len(self.handle.readline())
        self.n_lines = k

    def add_lines(self, k: int) -> None:
        if k:
            self.n_lines += k
            if self.on_lines is not None:
                self.on_lines(self.n_lines)

    def add_bytes(self, k: int) -> None:
        if k:
            self.n_bytes += k

    def shard_plan(self, n_shards: int, min_bytes: Optional[int] = None):
        """Byte-range shard plan over the remaining body, or None.

        Plain uncompressed binary files mmap and split into line-snapped
        ranges (``ingest.plan_byte_shards``) that the sharded decoder's
        workers own outright — the zero-feed-thread ingest path.  Gzip
        streams (compressed bytes are not splittable), BGZF readers
        (parallel at the inflate layer already) and text/in-memory
        handles return None: the caller degrades to the streaming rung.

        A successful plan CONSUMES the stream (the handle seeks to EOF
        and any buffered first line is dropped — its bytes are re-read
        from the map), so plan exactly once and only when committing to
        the shard rung.  Line/byte accounting still arrives through
        ``add_lines`` / ``add_bytes`` from the decoder, as on every
        other path.
        """
        if n_shards <= 1:
            return None
        mm = self._mmap_body()
        if mm is None:
            return None
        from .. import ingest

        if self.first:
            if self._body_start is None:
                return None       # cannot locate the buffered line
            start = self._body_start
            self.first = ""
        else:
            start = self.handle.tell()
        kwargs = {} if min_bytes is None else {"min_bytes": min_bytes}
        ranges = ingest.plan_byte_shards(mm, start, len(mm), n_shards,
                                         **kwargs)
        # leave the handle where the content ended, as read() would
        self.handle.seek(len(mm))
        return ingest.ShardPlan(data=mm, ranges=ranges, start=start,
                                end=len(mm))

    def records(self, on_bad=None) -> Iterator[SamRecord]:
        """Parsed mapped records, counting every body line.  ``on_bad``
        is :func:`iter_records`' tolerant-decode hook (the pure-python
        rung's seam for ``--on-bad-record``)."""
        def counted() -> Iterator[str]:
            for line in self.handle:
                self.add_lines(1)
                self.add_bytes(len(line))
                yield line.decode("ascii") if isinstance(line, bytes) \
                    else line

        first = self.first
        if isinstance(first, bytes):
            first = first.decode("ascii")
        if first:
            self.add_lines(1)
            self.add_bytes(len(first))
        yield from iter_records(counted(), first, on_bad=on_bad)

    def blocks(self, max_bytes: int = 1 << 23):
        """Raw blocks of whole lines, str or bytes per the handle's mode
        (line counting is the consumer's job via ``add_lines`` — the native
        decoder counts in C++).

        Plain binary files take a zero-copy path: the file is mmapped and
        line-aligned ``memoryview`` windows are yielded straight off the
        page cache — no per-block ``read()`` memcpy or bytes allocation
        (~tens of ms on the 241 MB north-star input).  Consumers already
        accept anything ``np.frombuffer`` does.  Gzip and text handles
        keep the buffered-read path.

        ``block_offset`` is set before each yield to the absolute input
        offset of the block's first byte (uncompressed offsets on gzip/
        BGZF handles — the SAME number a plain copy of the file would
        give, which is what makes strict-error offsets comparable
        across containers), or ``None`` when the handle cannot locate
        itself.  Consumers that attach offsets to strict decode errors
        (``ingest/badrecords.mark_offset``) read it per block.
        """
        pending = self.first
        self.first = ""
        mm = self._mmap_body()
        if mm is not None:
            if pending:
                self.block_offset = self._body_start
                yield pending.encode("ascii") \
                    if isinstance(pending, str) else pending
            pos = self.handle.tell()
            size = len(mm)
            mv = memoryview(mm)
            while pos < size:
                end = min(pos + max_bytes, size)
                if end < size:
                    nl = mm.rfind(b"\n", pos, end)
                    if nl < pos:
                        # one line longer than the window: extend to its
                        # terminating newline (or EOF)
                        nl = mm.find(b"\n", end)
                        end = size if nl < 0 else nl + 1
                    else:
                        end = nl + 1
                self.block_offset = pos
                yield mv[pos:end]
                pos = end
            # leave the handle where the content ended, as read() would
            self.handle.seek(size)
            return
        off = None if self._body_start is None \
            else self._body_start + self.n_bytes
        while True:
            chunk = self.handle.read(max_bytes)
            if not chunk:
                if pending:
                    self.block_offset = off
                    yield pending
                return
            if not isinstance(pending, type(chunk)):  # str first body line
                pending = pending.encode("ascii") if isinstance(pending, str) \
                    else pending.decode("ascii")
            newline = "\n" if isinstance(chunk, str) else b"\n"
            if not chunk.endswith(newline):
                chunk += self.handle.readline()
            block, pending = pending + chunk, chunk[:0]
            self.block_offset = off
            if off is not None:
                off += len(block)
            yield block

    def _is_plain_file(self) -> bool:
        """ONE definition of "plain uncompressed binary file handle" —
        shared by the mmap shard planner and the decode-pricing ledger
        so they can never disagree on what is byte-addressable (a gzip
        handle's fileno()/fstat see COMPRESSED bytes)."""
        import io as _io

        h = self.handle
        return (isinstance(h, _io.BufferedReader)
                and isinstance(getattr(h, "raw", None), _io.FileIO))

    def body_bytes_total(self) -> Optional[int]:
        """Body size in bytes (header excluded) for plain uncompressed
        file handles; None for compressed/in-memory handles or when the
        body start could not be located."""
        import os as _os

        if not self._is_plain_file() or self._body_start is None:
            return None
        try:
            st = _os.fstat(self.handle.fileno())
        except (OSError, ValueError):
            return None
        return max(0, st.st_size - self._body_start)

    def _mmap_body(self):
        """An ACCESS_READ mmap of the whole file when the handle is a
        plain uncompressed binary file; None otherwise (gzip handles
        would map COMPRESSED bytes — their fileno() is the raw file)."""
        import mmap as _mmap

        if not self._is_plain_file():
            return None
        try:
            return _mmap.mmap(self.handle.fileno(), 0,
                              access=_mmap.ACCESS_READ)
        except (ValueError, OSError):
            return None                    # empty file, pipe, ...
