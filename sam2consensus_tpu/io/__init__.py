from .sam import Contig, SamRecord, opener, read_header, iter_records, read_sam  # noqa: F401
from .fasta import FastaRecord, render_file, write_outputs  # noqa: F401
