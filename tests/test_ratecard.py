"""Rate cards (observability/ratecard.py): per-worker learned
throughput constants with confidence gating, crash-safe persistence,
decision-site consultation provenance, and the evidence-only fleet
scale hint computed from them.

Covers (ISSUE 19):
* EWMA convergence + spread tracking;
* min-sample and staleness confidence gates (consult falls back to
  the caller's default, with an auditable provenance stamp);
* atomic persistence across restarts — age stamps intact, restarts
  (the exposition's restart-epoch) bumped per reload, corrupt files
  read as absent-with-counter;
* the job-snapshot fold at the ``_finalize_job`` choke point;
* ``compute_scale_hint`` verdicts (refuse-to-guess, drain-over-target,
  tenant-paging, headroom, in-band);
* the link-constant aging unification with utils/linkprobe.py;
* the exposition: s2c_rate_* families, restart_epoch label rules and
  the process start-time gauge.
"""

import json
import os

import pytest

from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.observability import ratecard as rc
from sam2consensus_tpu.observability import telemetry as T
from sam2consensus_tpu.observability.metrics import MetricsRegistry
from sam2consensus_tpu.utils.simulate import SimSpec, simulate


@pytest.fixture(autouse=True)
def _no_persistent_cache(monkeypatch):
    monkeypatch.setenv("S2C_JIT_CACHE", "")


# =========================================================================
# units: estimator
# =========================================================================
def test_ewma_converges_to_constant():
    est = rc.RateEstimator()
    for _ in range(50):
        est.observe(120.0, now=1000.0)
    assert est.mean == 120.0
    assert est.stddev() == 0.0
    assert est.n == 50


def test_ewma_tracks_level_shift():
    est = rc.RateEstimator()
    for _ in range(20):
        est.observe(100.0, now=1000.0)
    for _ in range(20):
        est.observe(200.0, now=1001.0)
    # alpha=0.3: twenty samples at the new level all but complete the
    # transition
    assert 195.0 < est.mean <= 200.0
    assert est.stddev() > 0.0            # spread reflects the shift


def test_estimator_rejects_junk():
    est = rc.RateEstimator()
    for bad in (0.0, -5.0, float("nan"), float("inf")):
        est.observe(bad)
    assert est.n == 0
    est.observe(3.0, now=50.0)
    assert est.n == 1 and est.mean == 3.0


# =========================================================================
# confidence gates: min samples + staleness
# =========================================================================
def test_consult_min_sample_gate():
    card = rc.RateCard(worker="w0")
    now = 1000.0
    v, prov = card.consult("decode_mbps_per_core", 330.0, now=now)
    assert (v, prov["source"]) == (330.0, "default")
    card.observe("decode_mbps_per_core", 100.0, now=now)
    card.observe("decode_mbps_per_core", 100.0, now=now)
    v, prov = card.consult("decode_mbps_per_core", 330.0, now=now)
    assert prov["source"] == "default" and prov["n"] == 2   # gated
    assert v == 330.0
    card.observe("decode_mbps_per_core", 100.0, now=now)
    v, prov = card.consult("decode_mbps_per_core", 330.0, now=now)
    assert prov["source"] == "learned"
    assert v == 100.0
    assert prov["n"] == 3 and prov["default"] == 330.0


def test_consult_staleness_gate(monkeypatch):
    monkeypatch.setenv("S2C_LINK_CACHE_MAX_AGE", "100")
    card = rc.RateCard(worker="w0")
    for _ in range(5):
        card.observe("wire_bps", 8e6, now=1000.0)
    v, prov = card.consult("wire_bps", 1e6, now=1050.0)
    assert prov["source"] == "learned" and v == 8e6
    v, prov = card.consult("wire_bps", 1e6, now=1200.0)   # 200 s old
    assert prov["source"] == "default" and v == 1e6
    assert prov["age_sec"] == 200.0      # the audit trail survives


def test_module_consult_without_card_serves_default():
    rc.install(None)
    v, prov = rc.consult("decode_mbps_per_core", 330.0)
    assert v == 330.0 and prov == {"source": "default",
                                   "key": "decode_mbps_per_core"}


# =========================================================================
# persistence: restart survival, age stamps, corruption
# =========================================================================
def test_save_load_roundtrip_preserves_age_and_bumps_restarts(tmp_path):
    path = str(tmp_path / "ratecard-w0.json")
    card = rc.RateCard(worker="w0", path=path)
    for _ in range(4):
        card.observe("warm_jobs_per_sec", 0.5, now=5000.0)
    card.save(now=5010.0)

    loaded = rc.RateCard.load(path, worker="w0")
    assert loaded.restarts == 1          # second life of the card
    v, prov = loaded.consult("warm_jobs_per_sec", 9.9, now=5020.0)
    assert prov["source"] == "learned" and v == 0.5
    # the age stamp is the PERSISTED observation time, not load time
    assert prov["age_sec"] == 20.0

    loaded.save(now=5030.0)
    third = rc.RateCard.load(path, worker="w0")
    assert third.restarts == 2


def test_corrupt_card_reads_as_absent_with_counter(tmp_path):
    path = str(tmp_path / "ratecard-w0.json")
    with open(path, "w") as fh:
        fh.write('{"schema": "s2c-ratecard/1", "rates": {tr')
    reg = MetricsRegistry()
    card = rc.RateCard.load(path, worker="w0", registry=reg)
    assert card.restarts == 0
    assert card.snapshot()["rates"] == {}
    assert reg.value("rate/card_corrupt") == 1
    # schema mismatch is the same verdict
    with open(path, "w") as fh:
        json.dump({"schema": "bogus/9", "rates": {}}, fh)
    card = rc.RateCard.load(path, worker="w0", registry=reg)
    assert card.snapshot()["rates"] == {}
    assert reg.value("rate/card_corrupt") == 2


def test_missing_card_is_fresh_not_corrupt(tmp_path):
    reg = MetricsRegistry()
    card = rc.RateCard.load(str(tmp_path / "nope.json"),
                            worker="w0", registry=reg)
    assert card.restarts == 0
    assert reg.value("rate/card_corrupt") == 0


# =========================================================================
# the _finalize_job fold
# =========================================================================
def _snap(**counters):
    return {"counters": counters, "gauges": {}}


def test_observe_job_folds_expected_rates():
    card = rc.RateCard(worker="w0")
    seen = card.observe_job(
        _snap(**{"phase/decode_sec": 2.0,
                 "phase/pileup_dispatch_sec": 1.0,
                 "phase/accumulate_sec": 0.5,
                 "phase/stage_sec": 0.5,
                 "phase/vote_sec": 0.4,
                 "pileup/cells": 2e6,
                 "wire/bytes": 3e6}),
        elapsed_sec=5.0, input_bytes=100_000_000, decode_cores=4,
        packed=False,
        lifecycle={"steal_latency_sec": 2.5}, now=100.0)
    assert seen["decode_mbps_per_core"] == 100 / 2.0 / 4
    assert seen["dispatch_cells_per_sec"] == 2e6 / 2.0
    assert seen["vote_sec_per_mcell"] == 0.4 / 2.0
    assert seen["wire_bps"] == 3e6 / 1.5
    assert seen["warm_jobs_per_sec"] == 1 / 5.0
    assert seen["steal_sec"] == 2.5
    assert seen["recovery_sec"] == 7.5


def test_observe_job_guards_noise_denominators():
    card = rc.RateCard(worker="w0")
    seen = card.observe_job(
        _snap(**{"phase/decode_sec": 0.001,       # sub-ms decode
                 "pileup/cells": 10.0,            # trivial pileup
                 "wire/bytes": 1000.0}),          # sub-MB wire
        elapsed_sec=0.0001, input_bytes=500)
    assert seen == {}                             # nothing learned


def test_observe_job_packed_key():
    card = rc.RateCard(worker="w0")
    seen = card.observe_job(_snap(), elapsed_sec=2.0, packed=True)
    assert seen == {"packed_jobs_per_sec": 0.5}


# =========================================================================
# scale hint
# =========================================================================
def _card_snap(jps, confident=True, key="warm_jobs_per_sec"):
    return {"worker": "w", "restarts": 0,
            "rates": {key: {"mean": jps, "stddev": 0.0, "n": 5,
                            "age_sec": 1.0, "confident": confident}}}


def test_scale_hint_refuses_to_guess_without_confident_cards():
    hint = rc.compute_scale_hint(
        [_card_snap(0.5, confident=False)], queue_depth=50, workers=1)
    assert hint["verdict"] == "hold"
    assert hint["reason"] == "no_confident_rate"
    assert hint["projected_drain_sec"] is None
    assert hint["delta"] == 0


def test_scale_hint_up_when_drain_over_target():
    # 100 jobs at 0.05 jobs/s = 2000 s projected vs a 600 s target
    hint = rc.compute_scale_hint(
        [_card_snap(0.05)], queue_depth=100, workers=1,
        target_sec=600.0)
    assert hint["verdict"] == "up" and hint["delta"] >= 1
    assert hint["reason"] == "drain_over_target"
    assert hint["projected_drain_sec"] == 2000.0


def test_scale_hint_up_when_tenant_paging():
    hint = rc.compute_scale_hint(
        [_card_snap(10.0)], queue_depth=1, workers=1,
        burn_states={"hot": "page", "cold": "ok"}, target_sec=600.0)
    assert hint["verdict"] == "up" and hint["delta"] >= 1
    assert hint["reason"] == "tenant_paging"
    assert hint["paging_tenants"] == ["hot"]


def test_scale_hint_down_on_headroom_and_hold_in_band():
    # two workers, nearly empty queue, drain far under target
    hint = rc.compute_scale_hint(
        [_card_snap(1.0), _card_snap(1.0, key="packed_jobs_per_sec")],
        queue_depth=1, workers=2, target_sec=600.0)
    assert hint["verdict"] == "down" and hint["delta"] < 0
    assert hint["reason"] == "headroom"
    hint = rc.compute_scale_hint(
        [_card_snap(0.02)], queue_depth=10, workers=1,
        target_sec=600.0)
    assert hint["verdict"] == "hold" and hint["reason"] == "in_band"


# =========================================================================
# link-constant aging unification (utils/linkprobe.py satellite)
# =========================================================================
def test_link_cache_age_is_the_ratecard_knob(monkeypatch):
    from sam2consensus_tpu.utils import linkprobe

    monkeypatch.delenv("S2C_LINK_CACHE_MAX_AGE", raising=False)
    assert linkprobe.cache_max_age() == rc.max_age_sec() == 7 * 86400
    monkeypatch.setenv("S2C_LINK_CACHE_MAX_AGE", "123")
    assert linkprobe.cache_max_age() == 123.0
    assert rc.max_age_sec() == 123.0


def test_record_link_feeds_installed_card():
    from sam2consensus_tpu.utils import linkprobe

    card = rc.RateCard(worker="w0")
    rc.install(card)
    try:
        linkprobe._record_link((0.2, 42e6))
    finally:
        rc.install(None)
    snap = card.snapshot()
    assert snap["rates"]["link_bps"]["mean"] == 42e6
    assert snap["rates"]["link_rt_sec"]["mean"] == 0.2


# =========================================================================
# exposition: rate families, restart epoch, start-time gauge
# =========================================================================
def test_rate_families_render_and_lint(tmp_path):
    reg = MetricsRegistry()
    card = rc.RateCard(worker="w0",
                       path=str(tmp_path / "ratecard-w0.json"))
    for _ in range(4):
        card.observe("decode_mbps_per_core", 80.0, now=100.0)
    card.publish(reg, now=110.0)
    reg.gauge("process/start_time_seconds").set(12345.0)
    text = T.render_openmetrics(reg.snapshot(), worker="w0",
                                restart_epoch=card.restarts)
    assert 's2c_rate{key="decode_mbps_per_core"' in text
    assert 's2c_rate_samples{key="decode_mbps_per_core"' in text
    assert 's2c_rate_age_seconds{key="decode_mbps_per_core"' in text
    assert 's2c_process_start_time_seconds' in text
    assert 'restart_epoch="0"' in text
    assert T.lint_openmetrics(text) == []


def test_lint_rejects_restart_epoch_without_start_time():
    reg = MetricsRegistry()
    reg.add("serve/jobs", 1)
    text = T.render_openmetrics(reg.snapshot(), worker="w0",
                                restart_epoch=2)
    errs = T.lint_openmetrics(text)
    assert any("process_start_time" in e for e in errs)


def test_lint_rejects_non_integer_restart_epoch():
    reg = MetricsRegistry()
    reg.gauge("process/start_time_seconds").set(1.0)
    text = T.render_openmetrics(reg.snapshot(), worker="w0",
                                restart_epoch=1)
    bad = text.replace('restart_epoch="1"', 'restart_epoch="-1"')
    assert any("restart_epoch" in e for e in T.lint_openmetrics(bad))
    assert T.lint_openmetrics(text) == []


def _sim(tmp, name, seed):
    spec = SimSpec(n_contigs=1, contig_len=3000, n_reads=1000,
                   read_len=100, contig_len_jitter=0.0, seed=seed,
                   contig_prefix="rcrd")
    path = os.path.join(str(tmp), name)
    with open(path, "w") as fh:
        fh.write(simulate(spec))
    return path


def test_serve_card_survives_restart_with_ages(tmp_path):
    """A journaled server persists its card at job boundaries; the
    next life loads it (restarts bumped, sample counts and age stamps
    intact) and the health snapshot carries the card + scale hint."""
    from sam2consensus_tpu.serve import JobSpec, ServeRunner

    jdir = str(tmp_path / "journal")
    outdir = tmp_path / "out"
    outdir.mkdir()
    path = _sim(tmp_path, "a.sam", seed=7)

    def spec(jid):
        return JobSpec(
            filename=path, job_id=jid, tenant="ta",
            config=RunConfig(backend="jax", pileup="scatter",
                             shards=1, outfolder=str(outdir) + "/",
                             prefix=jid))

    r1 = ServeRunner(prewarm="off", persistent_cache=False,
                     journal_dir=jdir, slo="e2e=60s")
    try:
        res = r1.submit_jobs([spec("j0"), spec("j1")])
        assert all(r.ok for r in res)
        card_file = rc.card_path(jdir, "serve")
        assert os.path.exists(card_file)
        blob = json.load(open(card_file))
        assert blob["schema"] == rc.SCHEMA
        n1 = blob["rates"]["warm_jobs_per_sec"]["n"]
        assert n1 >= 2
        h = r1.health_snapshot()
        assert h["ratecard"]["restarts"] == 0
        assert "warm_jobs_per_sec" in h["ratecard"]["rates"]
        assert "scale_hint" in h           # tick ran at job end
    finally:
        r1.close()

    r2 = ServeRunner(prewarm="off", persistent_cache=False,
                     journal_dir=jdir, slo="e2e=60s")
    try:
        assert r2.ratecard.restarts == 1   # second life
        snap = r2.ratecard.snapshot()
        assert snap["rates"]["warm_jobs_per_sec"]["n"] == n1
        # the age stamp survived the restart (measured-at, not loaded-at)
        assert snap["rates"]["warm_jobs_per_sec"]["age_sec"] is not None
        v, prov = r2.ratecard.consult("warm_jobs_per_sec", 0.0) \
            if n1 >= rc.min_samples() else (None, {"source": "default"})
        if n1 >= rc.min_samples():
            assert prov["source"] == "learned" and v > 0
    finally:
        r2.close()


def test_restart_epoch_label_change_does_not_trip_monotonicity():
    reg = MetricsRegistry()
    reg.add("serve/jobs", 5)
    reg.gauge("process/start_time_seconds").set(1.0)
    prev = T.render_openmetrics(reg.snapshot(), worker="w0",
                                restart_epoch=0)
    reg2 = MetricsRegistry()                      # restarted: reset
    reg2.add("serve/jobs", 1)
    reg2.gauge("process/start_time_seconds").set(2.0)
    cur = T.render_openmetrics(reg2.snapshot(), worker="w0",
                               restart_epoch=1)
    # same worker, fewer jobs — but the epoch label makes it a NEW
    # series, so the cross-scrape monotonicity check cannot false-fire
    assert T.lint_openmetrics(cur, prev=prev) == []
