"""Multi-host mesh scale-up (ISSUE 18): the partition-rule table, the
shard/gather rungs it drives, typed ``--shards`` capacity validation,
and the capacity-planned ``mesh_shards`` admission verdict.

* every canonical array name matches EXACTLY one rule (both mesh-axis
  orderings), an uncovered non-scalar raises — placement must never be
  accidental;
* shard→gather round-trips are byte-identical on the 8-virtual-device
  mesh, including the per-device assembly path a real process-spanning
  mesh takes (``force_assemble``), which bills this host's shard bytes;
* impossible ``--shards`` requests fail up front with
  ``MeshCapacityError`` at every entry point (helper, backend, CLI);
* the memory plane picks the minimal host count K that fits the
  budget, records the ``mesh_shards`` ledger decision, and the
  admission controller turns it into an admit-with-K verdict instead
  of a capacity shed.
"""

import io
import re

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from sam2consensus_tpu import observability as obs
from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.observability import memplane
from sam2consensus_tpu.observability import telemetry as T
from sam2consensus_tpu.observability.metrics import (MetricsRegistry,
                                                     pop_run, push_run)
from sam2consensus_tpu.parallel import partition
from sam2consensus_tpu.parallel.mesh import (MeshCapacityError, make_mesh,
                                             validate_shards)
from sam2consensus_tpu.serve.admission import (REASON_CAPACITY,
                                               AdmissionController)


@pytest.fixture(autouse=True)
def _fresh_plane():
    memplane._reset_for_tests()
    yield
    memplane._reset_for_tests()


#: every array name the accumulators ship through the shard path
CANONICAL_NAMES = (
    "counts", "row_starts", "row_codes", "kernel_rank", "kernel_aux",
    "wire_lane", "wire_lane_lo", "wire_lane_hi", "vote_syms",
    "insertion_bank", "insertion_bank_rows", "thresholds",
    "contig_offsets", "site_keys", "contig_sums", "site_cov",
)


# =========================================================================
# The rule table
# =========================================================================
class TestRuleTable:
    @pytest.mark.parametrize("pos_axes", [("dp", "sp"), ("sp", "dp")])
    def test_each_canonical_name_matches_exactly_one_rule(self, pos_axes):
        rules = partition.partition_rules(pos_axes)
        for name in CANONICAL_NAMES:
            hits = partition.matching_rules(rules, name)
            assert len(hits) == 1, \
                f"{name!r} matched {len(hits)} rules: {hits}"

    def test_expected_specs(self):
        named = {
            "counts": jax.ShapeDtypeStruct((64, 6), np.int32),
            "vote_syms": jax.ShapeDtypeStruct((2, 64), np.uint8),
            "wire_lane_lo": jax.ShapeDtypeStruct((128,), np.uint8),
            "row_codes": jax.ShapeDtypeStruct((128, 4), np.uint8),
            "thresholds": jax.ShapeDtypeStruct((3,), np.float32),
        }
        specs = partition.match_partition_rules(
            partition.PARTITION_RULES, named)
        assert specs["counts"] == P(("dp", "sp"), None)
        assert specs["vote_syms"] == P(None, ("dp", "sp"))
        assert specs["wire_lane_lo"] == P(("dp", "sp"))
        assert specs["row_codes"] == P(("dp", "sp"), None)
        assert specs["thresholds"] == P()
        # dpsp's product ordering threads straight through to the spec
        flipped = partition.match_partition_rules(
            partition.partition_rules(("sp", "dp")), named)
        assert flipped["counts"] == P(("sp", "dp"), None)
        assert flipped["vote_syms"] == P(None, ("sp", "dp"))
        # the row ring is ordering-independent (always the flat ring)
        assert flipped["row_codes"] == P(("dp", "sp"), None)

    def test_uncovered_name_raises(self):
        with pytest.raises(ValueError,
                           match="partition rules don't cover"):
            partition.match_partition_rules(
                partition.PARTITION_RULES,
                {"mystery_plane": np.zeros((4, 4), np.int32)})

    def test_scalars_replicate_without_a_rule(self):
        specs = partition.match_partition_rules(
            partition.PARTITION_RULES,
            {"n_reads": 3, "zero_d": np.float32(1.5)})
        assert specs == {"n_reads": P(), "zero_d": P()}

    def test_rule_dim_overflow_raises(self):
        # canonical rules never over-ask; a custom table that wants
        # more sharded dims than the array has must fail loudly
        rules = ((r"^x$", P("dp", "sp")),)
        with pytest.raises(ValueError, match="wants"):
            partition.match_partition_rules(
                rules, {"x": np.zeros(8, np.int32)})


# =========================================================================
# shard -> gather round-trips on the virtual 8-device mesh
# =========================================================================
class TestShardGather:
    @pytest.fixture()
    def mesh(self):
        with make_mesh(8) as m:
            yield m

    def test_round_trip_byte_identity(self, mesh):
        rng = np.random.default_rng(7)
        named = {
            "counts": rng.integers(0, 2 ** 20, (64, 6)).astype(np.int32),
            "row_starts": rng.integers(0, 2 ** 16, 128).astype(np.int32),
            "row_codes": rng.integers(0, 255, (128, 4)).astype(np.uint8),
            "vote_syms": rng.integers(0, 6, (2, 64)).astype(np.uint8),
            "thresholds": np.asarray([0.25, 0.5, 0.75], np.float32),
        }
        specs = partition.match_partition_rules(
            partition.PARTITION_RULES, named)
        shard_fns, gather_fns = partition.make_shard_and_gather_fns(
            mesh, specs)
        assert set(shard_fns) == set(named) == set(gather_fns)
        for name, arr in named.items():
            placed = shard_fns[name](arr)
            assert placed.sharding.spec == specs[name]
            back = gather_fns[name](placed)
            assert back.dtype == arr.dtype
            assert np.array_equal(back, arr), name

    def test_force_assemble_round_trips_and_bills_local_bytes(self, mesh):
        # the per-device assembly path is exactly what a DCN-spanning
        # mesh runs; on one controller the "local window" is the whole
        # array, so the billed shard bytes equal arr.nbytes
        arr = np.arange(64 * 6, dtype=np.int32).reshape(64, 6)
        sharding = NamedSharding(mesh, P(("dp", "sp"), None))
        reg = push_run()
        try:
            placed = partition.shard_to_mesh(arr, sharding,
                                             force_assemble=True)
            billed = reg.value("mesh/shard_bytes/0")
        finally:
            pop_run(reg)
        assert billed == arr.nbytes
        assert np.array_equal(partition.gather_from_mesh(placed), arr)

    def test_mesh_gauges(self, mesh):
        assert partition.mesh_process_count(mesh) == 1
        reg = push_run()
        try:
            partition.publish_mesh_gauges(mesh)
            assert reg.value("mesh/hosts") == 1
            assert reg.value("mesh/shards") == 8
        finally:
            pop_run(reg)


# =========================================================================
# typed --shards validation (helper, backend, CLI)
# =========================================================================
class TestShardValidation:
    def test_noop_below_two(self):
        validate_shards(None)
        validate_shards(0)
        validate_shards(1)
        validate_shards(1, pileup="host")  # single shard composes fine

    def test_host_pileup_conflict(self):
        with pytest.raises(MeshCapacityError, match="does not compose"):
            validate_shards(4, pileup="host")

    def test_over_device_request(self):
        with pytest.raises(MeshCapacityError,
                           match="exceeds the 8 available"):
            validate_shards(64, n_available=8)
        # remedy is in the message, not just the verdict
        with pytest.raises(MeshCapacityError, match="widen the mesh"):
            validate_shards(64, n_available=8)
        validate_shards(8, n_available=8)  # exact fit is legal

    def test_default_pool_is_the_runtime(self):
        n = len(jax.devices())  # conftest forces 8 virtual devices
        validate_shards(n)
        with pytest.raises(MeshCapacityError, match="exceeds"):
            validate_shards(n + 1)

    def test_typed_as_value_error(self):
        # every existing reject-with-reason path keeps working
        assert issubclass(MeshCapacityError, ValueError)

    def test_make_mesh_over_request(self):
        with pytest.raises(MeshCapacityError, match="requested 99"):
            make_mesh(99)

    def test_backend_rejects_before_decode(self):
        from sam2consensus_tpu.backends.jax_backend import JaxBackend
        from sam2consensus_tpu.io.sam import iter_records, read_header
        from sam2consensus_tpu.utils.simulate import sam_text

        handle = io.StringIO(
            sam_text([("g", 8)], [("g", 1, "4M", "ACGT")]))
        contigs, _n, first = read_header(handle)
        cfg = RunConfig(thresholds=[0.25], backend="jax", shards=64)
        with pytest.raises(MeshCapacityError, match="exceeds"):
            JaxBackend().run(contigs, iter_records(handle, first), cfg)

    def test_cli_rejects_up_front(self, tmp_path):
        from sam2consensus_tpu.cli import main
        from sam2consensus_tpu.utils.simulate import sam_text, write_sam

        sam = write_sam(sam_text([("g", 8)], [("g", 1, "4M", "ACGT")]),
                        str(tmp_path / "t.sam"))
        out = str(tmp_path / "o")
        with pytest.raises(SystemExit, match="exceeds"):
            main(["-i", sam, "-o", out, "--backend", "jax",
                  "--shards", "64", "--quiet"])
        with pytest.raises(SystemExit,
                           match="does not compose with --shards"):
            main(["-i", sam, "-o", out, "--backend", "jax",
                  "--shards", "4", "--pileup", "host", "--quiet"])


# =========================================================================
# capacity-planned admission: the mesh_shards verdict
# =========================================================================
def _two_host_budget(total_len=200_000, max_hosts=4):
    """A budget strictly between the 1-host and 2-host per-host peaks:
    single-host runs are over budget, two hosts fit."""
    probe = memplane.plan_mesh_shards(total_len, None, budget_bytes=0,
                                      max_hosts=max_hosts, record=False)
    alt = probe["alternatives"]
    return int((alt["1"] + alt["2"]) / 2)


class TestMeshAdmission:
    def test_plan_picks_minimal_k(self):
        budget = _two_host_budget()
        plan = memplane.plan_mesh_shards(200_000, None,
                                         budget_bytes=budget,
                                         max_hosts=4, record=False)
        assert plan["fits"] is True
        assert plan["hosts"] == 2
        assert plan["per_host_bytes"] <= budget < plan["single_host_bytes"]
        # alternatives are keyed by STRING host counts (JSON-stable)
        assert set(plan["alternatives"]) == {"1", "2", "3", "4"}

    def test_plan_over_capacity(self):
        plan = memplane.plan_mesh_shards(200_000, None, budget_bytes=1,
                                         max_hosts=4, record=False)
        assert plan["fits"] is False
        assert plan["hosts"] == 4  # best effort: the cap, still over

    def test_plan_within_budget_stays_single_host(self):
        plan = memplane.plan_mesh_shards(200_000, None,
                                         budget_bytes=2 ** 40,
                                         max_hosts=4, record=False)
        assert plan["fits"] is True and plan["hosts"] == 1

    def test_plan_records_ledger_decision(self):
        budget = _two_host_budget()
        robs = obs.start_run()
        try:
            memplane.plan_mesh_shards(200_000, None, budget_bytes=budget,
                                      max_hosts=4)
            memplane.track("counts", 50_000)
            recs = obs.finalize_decisions()
        finally:
            obs.finish_run(robs)
        rec = next(r for r in recs if r.decision == "mesh_shards")
        assert rec.chosen == "hosts_2"
        assert rec.predicted["per_host_bytes"] > 0
        assert rec.measured["per_host_bytes"] == 50_000
        # band=0: the model is an upper bound, headroom must not alarm
        assert rec.drift is False

    def test_admission_verdict_matrix(self):
        fits2 = {"fits": True, "hosts": 2}
        adm = AdmissionController(mem_budget=100, mesh_hosts=4)
        d = adm.admit("t", predicted_bytes=50)
        assert d.admitted and d.mesh_shards is None
        d = adm.admit("t", predicted_bytes=500)
        assert not d.admitted and d.reason == REASON_CAPACITY
        d = adm.admit("t", predicted_bytes=500, shard_plan=fits2)
        assert d.admitted and d.mesh_shards == 2
        d = adm.admit("t", predicted_bytes=500,
                      shard_plan={"fits": False, "hosts": 4})
        assert not d.admitted and d.reason == REASON_CAPACITY
        d = adm.admit("t", predicted_bytes=500,
                      shard_plan={"fits": True, "hosts": 1})
        assert not d.admitted and d.reason == REASON_CAPACITY
        # no budget -> no capacity gate, plan or not
        assert AdmissionController().admit(
            "t", predicted_bytes=500).admitted

    def test_mesh_hosts_env(self, monkeypatch):
        from sam2consensus_tpu.serve import ServeRunner

        monkeypatch.setenv("S2C_JIT_CACHE", "")
        monkeypatch.setenv("S2C_MESH_HOSTS", "3")
        r = ServeRunner(prewarm="off", persistent_cache=False)
        assert r.admission.mesh_hosts == 3
        monkeypatch.setenv("S2C_MESH_HOSTS", "lots")
        with pytest.raises(ValueError,
                           match="S2C_MESH_HOSTS must be an integer"):
            ServeRunner(prewarm="off", persistent_cache=False)


# =========================================================================
# the s2c_mesh_* OpenMetrics family
# =========================================================================
def test_mesh_openmetrics_family():
    r = MetricsRegistry()
    r.add("mesh/shard_bytes/0", 1024)
    r.add("mesh/shard_bytes/1", 2048)
    r.add("mesh/gather_bytes", 4096)
    r.add("serve/admission_mesh", 1)
    r.gauge("mesh/hosts").set(2)
    r.gauge("mesh/shards").set(8)
    r.gauge("mesh/planned_hosts").set(2)
    text = T.render_openmetrics(r.snapshot())
    assert re.search(r's2c_mesh_shard_bytes_total\{host="0"\} 1024',
                     text)
    assert re.search(r's2c_mesh_shard_bytes_total\{host="1"\} 2048',
                     text)
    assert re.search(r"s2c_mesh_gather_bytes_total 4096", text)
    assert re.search(r"s2c_mesh_hosts 2(\.0)?\b", text)
    assert re.search(r"s2c_mesh_shards 8(\.0)?\b", text)
    assert re.search(r"s2c_mesh_planned_hosts 2(\.0)?\b", text)
    assert re.search(r"s2c_serve_admission_mesh_total 1\b", text)
    assert T.lint_openmetrics(text) == []
