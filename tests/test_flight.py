"""Fleet flight recorder (ISSUE 16): journal -> distributed trace.

The acceptance pins live here:

* the clock assumptions the assembler leans on — per-key journal
  event timestamps are non-decreasing across claim/steal/commit
  lineages, and ``lease_expired`` arbitration is exactly
  ``rec.t >= expires_unix`` (a renewal that published first voids the
  reap) — in both serve/journal._apply and flight.assemble's mirror;
* a mid-queue SIGKILL lineage assembles into a GAP-FREE per-job track
  (segments tile submit -> terminal, zero negative durations) whose
  measured steal latency sits within the fleet_soak 2x-lease-TTL
  bound;
* Chrome assembly validates (per-job tracks, worker occupancy lanes,
  flow arrows, no orphans) and per-worker ``--trace-out`` blobs merge
  re-anchored onto the journal wall clock, joined by trace_id;
* the runner stamps trace context end-to-end: manifest ``lifecycle``
  section, ``s2c_sched_*`` exposition (lint-clean, worker-labeled),
  health ``sched`` section — with the journal-measured queue wait
  agreeing with the window-epoch measure on a clean queue;
* recording is passive: outputs are byte-identical with the flight
  recorder on vs off;
* the riding tools: trace_summary multi-file merge (``worker;`` flame
  root), s2c_top --fleet staleness flag, check_perf_claims
  flight-artifact lints.
"""

import json
import os
import sys
import time

import pytest

from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.observability import flight
from sam2consensus_tpu.observability.metrics import MetricsRegistry
from sam2consensus_tpu.serve import journal as sjournal
from sam2consensus_tpu.serve.fleet import FleetCoordinator
from sam2consensus_tpu.utils.simulate import SimSpec, simulate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_persistent_cache(monkeypatch):
    monkeypatch.setenv("S2C_JIT_CACHE", "")


def _journal(tmp_path, name="j", **kw):
    kw.setdefault("checkpoint_every", 0)
    return sjournal.JobJournal(str(tmp_path / name), **kw)


def _sim(tmp, name, seed, n_reads=400, prefix="fl"):
    spec = SimSpec(n_contigs=1, contig_len=2500, n_reads=n_reads,
                   read_len=100, contig_len_jitter=0.0, seed=seed,
                   contig_prefix=prefix)
    path = os.path.join(str(tmp), name)
    with open(path, "w") as fh:
        fh.write(simulate(spec))
    return path


def _ev(seq, ev, key, t, **kw):
    return {"schema": "s2c-journal/1", "seq": seq, "ev": ev,
            "key": key, "t": t, **kw}


def _sigkill_lineage(ttl=2.5):
    """The canonical mid-queue SIGKILL story: w1 claims, renews once,
    dies; w2 reaps at expiry, steals, commits."""
    return [
        _ev(1, "submitted", "k", 100.0, job="x", tenant="ta"),
        _ev(2, "claimed", "k", 100.1, worker="w1",
            expires_unix=100.1 + ttl),
        _ev(3, "started", "k", 100.15, job="x", worker="w1"),
        _ev(4, "lease_renewed", "k", 101.0, worker="w1",
            expires_unix=101.0 + ttl),
        # SIGKILL lands here; silence until the reap
        _ev(5, "lease_expired", "k", 103.6, worker="w1", reaper="w2"),
        _ev(6, "claimed", "k", 103.7, worker="w2",
            expires_unix=103.7 + ttl),
        _ev(7, "started", "k", 103.8, job="x", worker="w2"),
        _ev(8, "committed", "k", 104.9, job="x", worker="w2",
            claim_seq=6, outputs={}),
    ]


# =========================================================================
# clock assumptions (journal side)
# =========================================================================
class TestJournalClockAssumptions:
    def test_timestamps_non_decreasing_per_key_across_steal(self,
                                                            tmp_path):
        """A real claim/steal/commit lineage through the journal keeps
        per-key ``t`` non-decreasing in seq order — the ordering the
        assembler's segment derivation (and commit fencing) leans
        on."""
        j = _journal(tmp_path)
        a = FleetCoordinator(j, "wa", 0.05, MetricsRegistry())
        b = FleetCoordinator(
            sjournal.JobJournal(j.root, checkpoint_every=0), "wb",
            5.0, MetricsRegistry())
        j.append("submitted", key="k", job="x")
        assert a.try_claim("k", "x")
        time.sleep(0.08)
        assert b.try_claim("k", "x")       # reap + steal
        j.append("started", key="k", job="x", worker="wb")
        j.append("committed", key="k", job="x", outputs={},
                 worker="wb")
        evs = j.events()
        by_key = {}
        for e in evs:
            if e.get("key"):
                by_key.setdefault(e["key"], []).append(e)
        for key, kevs in by_key.items():
            ts = [float(e["t"]) for e in kevs]
            assert ts == sorted(ts), (key, kevs)
        # the steal is visible and measurable
        assert b.steal_gaps.get("k", -1) >= 0.0

    def test_reap_effective_only_at_or_after_expiry(self, tmp_path):
        """``lease_expired`` arbitration is ``rec.t >= expires_unix``:
        a reap racing a live (future-expiry) lease is void, in both
        the journal replay and the assembler's mirror."""
        j = _journal(tmp_path)
        now = time.time()
        j.append("claimed", key="k", worker="wa",
                 expires_unix=now + 60)
        j.append("lease_expired", key="k", worker="wa", reaper="wb")
        st = j.replay()
        assert st.claims["k"]["worker"] == "wa"      # reap voided
        jobs = flight.assemble(j.events())
        names = [n for n, _t, _a in jobs["k"].instants]
        assert "lease_reap_void" in names
        assert "lease_reaped" not in names
        # expired lease: the same reap is effective
        j2 = _journal(tmp_path, "j2")
        j2.append("claimed", key="k", worker="wa",
                  expires_unix=time.time() - 1)
        j2.append("lease_expired", key="k", worker="wa", reaper="wb")
        assert "k" not in j2.replay().claims
        jobs2 = flight.assemble(j2.events())
        assert "lease_reaped" in [n for n, _t, _a
                                  in jobs2["k"].instants]


# =========================================================================
# assembler (synthetic lineages)
# =========================================================================
class TestAssemble:
    def test_sigkill_track_is_gap_free_with_bounded_steal(self):
        ttl = 2.5
        jobs = flight.assemble(_sigkill_lineage(ttl))
        assert list(jobs) == ["k"]
        jl = jobs["k"]
        assert jl.tenant == "ta"
        assert jl.terminal_ev == "committed"
        assert jl.committed_worker == "w2"
        segs = jl.segments
        assert segs, "no segments derived"
        # gap-free tiling submit -> terminal, no negative durations
        assert segs[0].t0 == jl.submitted_t == 100.0
        assert segs[-1].t1 == jl.terminal_t == 104.9
        for prev, nxt in zip(segs, segs[1:]):
            assert prev.t1 == nxt.t0, (prev, nxt)
        assert all(s.dur > 0 for s in segs)
        kinds = [s.kind for s in segs]
        assert kinds == ["queue_wait", "claim_latency", "run",
                         "steal_gap", "claim_latency", "run"]
        # the steal: victim's last sign of life (renewal at 101.0) ->
        # winning re-claim at 103.7, within the fleet_soak bound
        assert jl.steals == 1
        assert jl.steal_latency_sec == pytest.approx(2.7)
        assert jl.steal_latency_sec <= 2 * ttl
        gap = [s for s in segs if s.kind == "steal_gap"][0]
        assert gap.args["victim_last_t"] == 101.0
        # journal-measured scheduler numbers
        assert jl.queue_wait_sec == pytest.approx(0.15)
        assert jl.claim_latency_sec == pytest.approx(0.1)
        assert jl.lease_churn == 1                   # the reap
        assert jl.renewals == 1

    def test_zombie_commit_is_fenced_to_instant(self):
        evs = _sigkill_lineage()
        # the woken victim's commit lands between the steal and the
        # thief's real commit — the lease fence voids it
        evs.insert(7, _ev(9, "committed", "k", 104.0, job="x",
                          worker="w1", claim_seq=2, outputs={}))
        jobs = flight.assemble(evs)
        jl = jobs["k"]
        assert jl.terminal_ev == "committed"
        assert jl.committed_worker == "w2"
        assert jl.terminal_t == 104.9
        assert ("stale_commit", 104.0, {"worker": "w1"}) \
            in jl.instants
        # the thief's run segment is NOT truncated at the zombie's t
        run2 = [s for s in jl.segments if s.kind == "run"][-1]
        assert (run2.t0, run2.t1) == (103.8, 104.9)

    def test_claim_race_loser_counts_churn_not_ownership(self):
        evs = [
            _ev(1, "submitted", "k", 10.0, job="x"),
            _ev(2, "claimed", "k", 10.1, worker="wa",
                expires_unix=70.0),
            _ev(3, "claimed", "k", 10.1, worker="wb",
                expires_unix=70.0),
            _ev(4, "started", "k", 10.2, job="x", worker="wa"),
            _ev(5, "committed", "k", 11.0, job="x", worker="wa",
                claim_seq=2, outputs={}),
        ]
        jl = flight.assemble(evs)["k"]
        assert jl.lease_churn == 1
        names = [n for n, _t, _a in jl.instants]
        assert names.count("claim_won") == 1
        assert names.count("claim_lost") == 1
        assert jl.steals == 0
        run = [s for s in jl.segments if s.kind == "run"][0]
        assert run.worker == "wa"

    def test_serial_journal_without_claims_still_tracks(self):
        evs = [
            _ev(1, "submitted", "k", 5.0, job="x"),
            _ev(2, "started", "k", 5.4, job="x"),
            _ev(3, "committed", "k", 6.0, job="x", outputs={}),
        ]
        jl = flight.assemble(evs)["k"]
        assert [s.kind for s in jl.segments] == ["queue_wait", "run"]
        assert jl.queue_wait_sec == pytest.approx(0.4)
        assert jl.claim_latency_sec is None
        assert jl.steal_latency_sec is None


# =========================================================================
# sched metrics + critical path
# =========================================================================
class TestSchedMetrics:
    def test_fleet_aggregates_from_lineage(self):
        jobs = flight.assemble(_sigkill_lineage())
        sched = flight.sched_metrics(jobs)
        ta = sched["per_tenant"]["ta"]
        assert ta["queue_wait_sec"] == [pytest.approx(0.15)]
        assert ta["claim_latency_sec"] == [pytest.approx(0.1)]
        assert ta["steal_latency_sec"] == [pytest.approx(2.7)]
        assert sched["lease_churn"] == 1
        assert sched["wall_sec"] == pytest.approx(4.9)
        # w1 ran 100.15 -> 103.6 (reap closes it), w2 103.8 -> 104.9
        assert sched["workers"]["w1"]["busy_sec"] == pytest.approx(
            3.45)
        assert sched["workers"]["w2"]["busy_sec"] == pytest.approx(
            1.1)
        assert sched["workers"]["w1"]["occupancy"] == pytest.approx(
            3.45 / 4.9, abs=1e-3)

    def test_critical_path_splits_run_and_caps_overshoot(self):
        jl = flight.assemble(_sigkill_lineage())["k"]
        phases = {"phase/decode_sec": 0.5, "phase/accumulate_sec": 1.0,
                  "phase/vote_sec": 0.25}
        d = flight.critical_path(jl, phases)
        run_total = 3.45 + 1.1
        # queue = submit -> first claim; claim = both attempts' claim
        # -> started gaps; steal = the gap's visible (post-reap) tail
        assert d["queue"] == pytest.approx(0.1)
        assert d["claim"] == pytest.approx(0.15)
        assert d["steal"] == pytest.approx(0.1)
        assert d["decode"] == pytest.approx(0.5)
        assert d["dispatch"] == pytest.approx(1.0)
        assert d["tail"] == pytest.approx(0.25)
        assert d["run_other"] == pytest.approx(run_total - 1.75)
        # a counter overshoot can never exceed the measured run wall
        d2 = flight.critical_path(jl, {"phase/decode_sec": 99.0})
        assert d2["decode"] == pytest.approx(run_total)
        assert d2["run_other"] == 0.0
        report = flight.wall_report({"k": jl})
        assert report["total_sec"] > 0
        assert set(report["totals_sec"]) == set(flight.PATH_BUCKETS)


# =========================================================================
# Chrome assembly + validation
# =========================================================================
class TestChromeAssembly:
    def test_lineage_validates_with_lanes_and_flows(self):
        jobs = flight.assemble(_sigkill_lineage())
        events = flight.chrome_events(jobs)
        assert flight.validate(events) == []
        names = {e["args"]["name"] for e in events
                 if e.get("ph") == "M"
                 and e.get("name") == "thread_name"}
        assert any(n.startswith("job x [k]") for n in names)
        assert {"worker w1", "worker w2"} <= names
        # flow arrows tie job track to worker lane (the steal hop)
        assert any(e.get("ph") == "s" for e in events)
        assert any(e.get("ph") == "f" for e in events)
        # every X span is non-negative and on the journal-relative
        # microsecond clock
        for e in events:
            if e.get("ph") == "X":
                assert e["dur"] >= 0
                assert e["ts"] >= 0

    def test_worker_trace_merges_reanchored_by_trace_id(self):
        jobs = flight.assemble(_sigkill_lineage())
        blob = {"traceEvents": [
            {"ph": "X", "tid": 0, "ts": 1000.0, "dur": 10.0,
             "name": "decode"}],
            "s2c": {"epoch_unix": 100.15, "trace_id": "k",
                    "worker": "w1"}}
        no_anchor = {"traceEvents": [
            {"ph": "X", "tid": 0, "ts": 0.0, "dur": 1.0,
             "name": "x"}], "s2c": {}}
        events = flight.chrome_events(jobs, [blob, no_anchor])
        assert flight.validate(events) == []
        merged = [e for e in events
                  if e.get("pid") == flight.PID_WORKER_TRACE0
                  and e.get("ph") == "X"]
        assert len(merged) == 1
        # (epoch_unix - journal t0) * 1e6 + perf_counter_us
        assert merged[0]["ts"] == pytest.approx(151000.0)
        assert merged[0]["args"]["trace_id"] == "k"
        # the anchorless blob was skipped, not mis-anchored
        assert not any(e.get("pid") == flight.PID_WORKER_TRACE0 + 1
                       for e in events)

    def test_validate_flags_breakage(self):
        assert flight.validate([]) != []             # no job track
        bad = [{"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
                "args": {"name": "job j"}},
               {"ph": "X", "pid": 1, "tid": 1, "name": "s",
                "ts": 0.0, "dur": -5.0},
               {"ph": "X", "pid": 1, "tid": 9, "name": "o",
                "ts": 0.0, "dur": 1.0}]
        errs = flight.validate(bad)
        assert any("negative" in e for e in errs)
        assert any("orphaned" in e for e in errs)


# =========================================================================
# runner integration: trace context + sched telemetry end-to-end
# =========================================================================
class TestRunnerLifecycle:
    def test_lifecycle_stamped_and_sched_exposed(self, tmp_path):
        from sam2consensus_tpu.observability.telemetry import \
            lint_openmetrics
        from sam2consensus_tpu.serve import JobSpec, ServeRunner

        path = _sim(tmp_path, "a.sam", 91, prefix="lc_")
        out = str(tmp_path / "out")
        os.makedirs(out)
        r = ServeRunner(prewarm="off", persistent_cache=False,
                        journal_dir=str(tmp_path / "j"),
                        worker_id="w0", lease_ttl=30.0)
        try:
            res = r.submit_jobs([JobSpec(
                filename=path,
                config=RunConfig(backend="jax", outfolder=out,
                                 prefix="pl"),
                tenant="ta")])[0]
            assert res.ok
            lc = res.manifest["lifecycle"]
            st = r.journal.read_state()
            (key,) = st.submitted
            assert lc["trace_id"] == flight.trace_id(key)
            assert lc["key"] == key
            assert lc["worker"] == "w0"
            # journal-measured queue wait is present and agrees with
            # the window-epoch measure on a clean queue
            jqw = lc["queue_wait_sec"]
            wqw = lc["window_queue_wait_sec"]
            assert jqw >= 0.0
            assert abs(jqw - wqw) <= max(0.1 * max(jqw, wqw), 0.25)
            assert lc["claim_latency_sec"] >= 0.0
            assert "steal_latency_sec" not in lc     # nothing stolen
            # live histograms observed per tenant
            hist = r.registry.snapshot()["histograms"]
            assert hist["sched/ta/queue_wait"]["count"] == 1
            assert hist["sched/ta/claim_latency"]["count"] == 1
            # exposition: s2c_sched_* family, worker-labeled,
            # lint-clean
            tel = r.render_telemetry()
            assert lint_openmetrics(tel) == []
            sched_lines = [ln for ln in tel.splitlines()
                           if ln.startswith("s2c_sched_seconds")]
            assert sched_lines
            assert all('tenant="ta"' in ln and 'worker="w0"' in ln
                       for ln in sched_lines)
            assert any('kind="queue_wait"' in ln
                       for ln in sched_lines)
            # health snapshot sched section
            snap = r.health_snapshot()
            assert snap["sched"]["queue_wait"]["ta"]["count"] == 1
            assert snap["sched"]["occupancy_ratio"] >= 0.0
        finally:
            r.close()

    def test_outputs_byte_identical_flight_on_vs_off(self, tmp_path,
                                                     monkeypatch):
        """Recording is passive: a journaled worker with per-job
        trace artifacts + trace-context stamping produces
        byte-identical consensus outputs to the same worker run with
        recording off."""
        from sam2consensus_tpu.serve import JobSpec, ServeRunner

        path = _sim(tmp_path, "b.sam", 92, prefix="bi_")

        def run(tag, **kw):
            out = str(tmp_path / f"out_{tag}") + os.sep
            os.makedirs(out)
            r = ServeRunner(prewarm="off", persistent_cache=False,
                            journal_dir=str(tmp_path / f"j_{tag}"),
                            worker_id="w0", lease_ttl=30.0, **kw)
            try:
                res = r.submit_jobs([JobSpec(
                    filename=path,
                    config=RunConfig(backend="jax", outfolder=out,
                                     prefix="pb"))])[0]
                assert res.ok and res.output_paths
                return {os.path.basename(p): open(p, "rb").read()
                        for p in res.output_paths}
            finally:
                r.close()

        monkeypatch.setenv("S2C_TRACE_OUT",
                           str(tmp_path / "trace_on"))
        on = run("on")
        monkeypatch.delenv("S2C_TRACE_OUT")
        off = run("off")
        assert on == off
        # the recorder side really was on: a per-job trace exists and
        # carries the trace context the assembler joins on
        traces = [n for n in os.listdir(tmp_path)
                  if n.startswith("trace_on")]
        assert traces
        blob = json.load(open(tmp_path / traces[0]))
        assert blob["s2c"]["worker"] == "w0"
        assert blob["s2c"]["trace_id"]
        assert blob["s2c"]["epoch_unix"] > 0


# =========================================================================
# the assembler tool over a real journal
# =========================================================================
class TestFleetTraceTool:
    def test_assembles_real_steal_journal_within_bound(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import fleet_trace

        ttl = 0.05
        j = _journal(tmp_path)
        a = FleetCoordinator(j, "wa", ttl, MetricsRegistry())
        b = FleetCoordinator(
            sjournal.JobJournal(j.root, checkpoint_every=0), "wb",
            5.0, MetricsRegistry())
        j.append("submitted", key="k", job="x", tenant="tt")
        assert a.try_claim("k", "x")
        j.append("started", key="k", job="x", worker="wa")
        time.sleep(0.08)
        assert b.try_claim("k", "x")
        j.append("started", key="k", job="x", worker="wb")
        j.append("committed", key="k", job="x", outputs={},
                 worker="wb")
        jobs, events, sched, report = fleet_trace.assemble_journal(
            j.root)
        assert flight.validate(events) == []
        jl = jobs["k"]
        assert jl.steals == 1
        assert jl.steal_latency_sec is not None
        # generous wall bound: claims stamp second-resolution t's
        assert jl.steal_latency_sec <= 2 * ttl + 2.0
        assert sched["per_tenant"]["tt"]["steal_latency_sec"]
        # trace round-trips through write_trace as valid JSON
        out = str(tmp_path / "t.json")
        fleet_trace.write_trace(out, events, sched)
        blob = json.load(open(out))
        assert blob["s2c"]["kind"] == "fleet_trace"
        assert flight.validate(blob["traceEvents"]) == []


# =========================================================================
# riding tools: trace_summary merge, s2c_top staleness, claim lints
# =========================================================================
class TestTools:
    def _trace(self, tmp_path, name, worker, span_name, dur):
        blob = {"traceEvents": [
            {"ph": "X", "tid": 0, "ts": 0.0, "dur": dur,
             "name": span_name}],
            "s2c": {"worker": worker, "epoch_unix": 100.0}}
        p = str(tmp_path / name)
        with open(p, "w") as fh:
            json.dump(blob, fh)
        return p

    def test_trace_summary_merges_with_worker_flame_root(
            self, tmp_path, capsys):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import trace_summary

        p1 = self._trace(tmp_path, "t1.json", "wa", "decode", 100.0)
        p2 = self._trace(tmp_path, "t2.json", "wb", "vote", 200.0)
        assert trace_summary.main([p1, p2, "--flame"]) == 0
        lines = capsys.readouterr().out.strip().splitlines()
        assert "wa;decode 100" in lines
        assert "wb;vote 200" in lines
        # single-file mode: unchanged, no worker root
        assert trace_summary.main([p1, "--flame"]) == 0
        assert capsys.readouterr().out.strip() == "decode 100"
        # glob expansion merges into ONE ranking
        assert trace_summary.main(
            [str(tmp_path / "t*.json"), "-n", "5"]) == 0
        out = capsys.readouterr().out
        assert "2 spans / 2 names" in out

    def test_s2c_top_fleet_flags_stale_snapshots(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import s2c_top

        fresh = str(tmp_path / "h_fresh.json")
        stale = str(tmp_path / "h_stale.json")
        for p, wid in ((fresh, "w0"), (stale, "w1")):
            with open(p, "w") as fh:
                json.dump({"worker_id": wid, "uptime_sec": 10.0,
                           "jobs": {"run": 1},
                           "sched": {"telemetry_interval_sec": 2.0}},
                          fh)
        old = time.time() - 60
        os.utime(stale, (old, old))
        healths = [(fresh, s2c_top.read_health(fresh)),
                   (stale, s2c_top.read_health(stale))]
        flagged = s2c_top.stale_workers(healths)
        assert stale in flagged and fresh not in flagged
        assert flagged[stale] > 3 * 2.0
        frame = s2c_top.render_fleet(healths, None, stale=flagged)
        assert any("1 stale" in ln for ln in frame)
        w1_row = [ln for ln in frame if ln.startswith("w1")][0]
        assert "stale" in w1_row
        w0_row = [ln for ln in frame if ln.startswith("w0")][0]
        assert "stale" not in w0_row
        # 2-arg call stays valid (pinned fleet-frame contract)
        assert s2c_top.render_fleet(healths, None)

    def test_check_perf_claims_lints_flight_artifacts(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import check_perf_claims
        import fleet_trace

        jobs = flight.assemble(_sigkill_lineage())
        events = flight.chrome_events(jobs)
        good = str(tmp_path / "fleet_trace_ok.json")
        fleet_trace.write_trace(good, events,
                                flight.sched_metrics(jobs))
        assert check_perf_claims.lint_flight_trace_artifact(good) == []
        bad = str(tmp_path / "fleet_trace_bad.json")
        with open(bad, "w") as fh:
            json.dump({"traceEvents": [
                {"ph": "X", "pid": 1, "tid": 1, "name": "s",
                 "ts": 0.0, "dur": -1.0}]}, fh)
        assert check_perf_claims.lint_flight_trace_artifact(bad)
        notjson = str(tmp_path / "fleet_trace_nj.json")
        with open(notjson, "w") as fh:
            fh.write("{nope")
        assert check_perf_claims.lint_flight_trace_artifact(notjson)
        # leg JSONL: clean summary passes, any failure is flagged
        okrow = {"mode": "summary", "failures": 0, "lost_total": 0,
                 "duplicated_total": 0, "identical_all": True,
                 "per_job_tracks": 3, "validation_errors": 0}
        leg = str(tmp_path / "fleet_trace_leg.jsonl")
        with open(leg, "w") as fh:
            fh.write(json.dumps(okrow) + "\n")
        assert check_perf_claims.lint_fleet_trace_leg_artifact(
            leg) == []
        badrow = dict(okrow, validation_errors=2, per_job_tracks=0)
        with open(leg, "w") as fh:
            fh.write(json.dumps(badrow) + "\n")
        errs = check_perf_claims.lint_fleet_trace_leg_artifact(leg)
        assert any("validation_errors" in e for e in errs)
        assert any("per-job" in e for e in errs)

    def test_committed_leg_artifact_is_lint_clean(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import check_perf_claims

        art = os.path.join(REPO, "campaign",
                           "fleet_trace_r06_cpufallback.jsonl")
        assert os.path.exists(art), \
            "campaign/fleet_trace_r06_cpufallback.jsonl missing"
        assert check_perf_claims.lint_fleet_trace_leg_artifact(
            art) == []
