"""Device-side exact threshold cutoffs vs the host float64 oracle.

``ops.cutoff.exact_cutoff`` must reproduce ``ceil(fl64(t) * cov)`` —
including the float64 rounding of the product — for every threshold double
and int32 coverage, because the reference's greedy vote compares integer
running totals against that float product
(/root/reference/sam2consensus.py:359-367).  ``threshold_luts`` (numpy
float64) is the independent oracle.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sam2consensus_tpu.ops.cutoff import encode_thresholds, exact_cutoff
from sam2consensus_tpu.ops.vote import threshold_luts

_f = jax.jit(exact_cutoff)


def _oracle(t, cov):
    return np.minimum(np.ceil(np.float64(t) * cov.astype(np.float64)),
                      2 ** 31 - 1).astype(np.int64)


def _check(thresholds, cov):
    enc = encode_thresholds(thresholds)
    for i, t in enumerate(thresholds):
        got = np.asarray(_f(jnp.asarray(cov), jnp.asarray(enc[i])))
        want = _oracle(t, cov)
        bad = np.nonzero(got.astype(np.int64) != want)[0]
        assert len(bad) == 0, (
            f"t={t!r}: first mismatches at cov={cov[bad[:5]]}: "
            f"got {got[bad[:5]]}, want {want[bad[:5]]}")


BENCH_THRESHOLDS = [0.25, 0.5, 0.75, 1 / 3, 2 / 3, 0.1, 0.9, 0.999999, 1.0]


def test_exhaustive_small_cov():
    _check(BENCH_THRESHOLDS, np.arange(0, 100000, dtype=np.int32))


def test_random_doubles_exhaustive():
    rng = np.random.default_rng(7)
    _check(list(rng.random(20)), np.arange(0, 20000, dtype=np.int32))


def test_large_cov_random():
    rng = np.random.default_rng(8)
    cov = rng.integers(0, 2 ** 31, 100000, dtype=np.int64).astype(np.int32)
    _check(BENCH_THRESHOLDS + list(rng.random(10)), cov)


def test_pow2_boundaries():
    cov = []
    for b in range(1, 31):
        cov += [(1 << b) - 2, (1 << b) - 1, 1 << b, (1 << b) + 1]
    cov += [2 ** 31 - 1, 2 ** 31 - 2, 0, 1, 2, 3]
    _check(BENCH_THRESHOLDS, np.asarray(cov, dtype=np.int32))


def test_extreme_thresholds():
    """Sub/near-denormal, tiny and huge thresholds stay exact or clamp."""
    cov = np.asarray([0, 1, 2, 3, 1000, 2 ** 20, 2 ** 31 - 1],
                     dtype=np.int32)
    _check([1e-9, 1e-300, 5e-324, 2.5, 1000.0, 1e9], cov)


def test_rne_tie_cases():
    """Thresholds whose products hit exact .5 ulp ties (RNE must match)."""
    # t = (2^53-1)/2^54 * 2: mantissa all-ones patterns provoke ties
    ts = [np.nextafter(0.5, 1.0), np.nextafter(0.5, 0.0),
          np.nextafter(0.25, 1.0), float.fromhex("0x1.fffffffffffffp-2")]
    _check([float(t) for t in ts], np.arange(0, 50000, dtype=np.int32))


def test_rejects_bad_thresholds():
    for bad in (0.0, -0.25, float("nan"), float("inf")):
        with pytest.raises(ValueError):
            encode_thresholds([bad])


def test_matches_threshold_luts_matrix():
    """Row-for-row against the LUT builder (the round-2 production path)."""
    ts = [0.25, 0.5, 0.75]
    luts = threshold_luts(ts, 4096)
    enc = encode_thresholds(ts)
    cov = np.arange(0, 4097, dtype=np.int32)
    for i in range(len(ts)):
        got = np.asarray(_f(jnp.asarray(cov), jnp.asarray(enc[i])))
        np.testing.assert_array_equal(got, luts[i])
