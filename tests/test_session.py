"""Streaming consensus sessions: crash-safe live ingest (ISSUE 17).

The acceptance pins live here:

* waves are journaled (``wave_received``) BEFORE the ACK and absorbed
  exactly once — the journal audit proves 0 lost / 0 duplicated at
  wave granularity, and compacted replay equals full replay;
* a torn spool (sha mismatch vs the journaled intent) is re-requested,
  never absorbed; a declared-sha mismatch is rejected 422 at receive;
* the early-stability verdict fires when the consensus digest is
  unchanged for N waves, and ``revote`` re-votes without new ingest;
* the HTTP front door answers the full status taxonomy (404/405/413/
  422/429) without dying, and backpressure carries Retry-After;
* a SIGKILLed worker's session is stolen by a peer and replayed from
  the journal, byte-identical to the one-shot run (subprocess smoke
  here, the rotating soak is the slow test + the committed campaign
  artifact campaign/session_soak_r06_cpufallback.jsonl);
* session counters ride the lint-clean OpenMetrics exposition and the
  health snapshot's ``sessions`` section;
* the serve CLI rejects incoherent session flag combinations at parse
  time;
* review hardening: rejections consume their wave number (a stale
  rejection can never void a later ACKed wave — in the journal fence
  and end to end through a steal), status/health/other-tenant ingest
  never queue behind one session's absorb, early HTTP errors close the
  keep-alive connection instead of desyncing it, a restarted worker
  re-adopts its own orphans, and the orphan scan runs on a lease-TTL
  cadence rather than every drain tick.
"""

import hashlib
import http.client
import json
import os
import sys
import time

import pytest

from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.serve import journal as sjournal
from sam2consensus_tpu.serve.session import (
    SessionError, SessionManager, consensus_digest, sha256_hex)
from sam2consensus_tpu.utils.simulate import SimSpec, simulate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_persistent_cache(monkeypatch):
    monkeypatch.setenv("S2C_JIT_CACHE", "")


# =========================================================================
# corpus helpers: one simulated SAM split into header + read waves
# =========================================================================
def _corpus(tmp_path, n_waves=3, n_reads=900, contig_len=2200, seed=411,
            prefix="ts_"):
    spec = SimSpec(n_contigs=1, contig_len=contig_len, n_reads=n_reads,
                   read_len=100, contig_len_jitter=0.0, seed=seed,
                   contig_prefix=prefix)
    text = simulate(spec)
    header = [ln for ln in text.splitlines() if ln.startswith("@")]
    reads = [ln for ln in text.splitlines()
             if ln and not ln.startswith("@")]
    per = max(1, len(reads) // n_waves)
    waves = [reads[i:i + per] for i in range(0, len(reads), per)]
    if len(waves) > n_waves:                    # fold the remainder
        waves[n_waves - 1].extend(
            ln for w in waves[n_waves:] for ln in w)
        waves = waves[:n_waves]
    concat = str(tmp_path / "concat.sam")
    with open(concat, "w") as fh:
        fh.write(text)
    header_text = "\n".join(header) + "\n"
    bodies = [("\n".join(w) + "\n").encode("utf-8") for w in waves]
    return header_text, bodies, concat


def _runner(tmp_path, worker="w0", ttl=30.0):
    from sam2consensus_tpu.serve import ServeRunner

    return ServeRunner(prewarm="off", persistent_cache=False,
                       journal_dir=str(tmp_path / "j"),
                       worker_id=worker, lease_ttl=ttl)


def _cfg(tmp_path):
    out = str(tmp_path / "oneshot_out")
    os.makedirs(out, exist_ok=True)
    return RunConfig(backend="jax", outfolder=out + os.sep, prefix="")


def _content_shas(paths):
    """Per-reference FASTA content, keyed on the reference stem (the
    filename prefix differs between one-shot and session mode)."""
    shas = {}
    for p in paths:
        ref = os.path.basename(p).split("__")[0]
        with open(p, "rb") as fh:
            shas[ref] = hashlib.sha256(fh.read()).hexdigest()
    return shas


# =========================================================================
# journal: session events replay, audit, compaction equivalence
# =========================================================================
class TestSessionJournal:
    def test_session_audit_counts_waves_not_reads(self, tmp_path):
        j = sjournal.JobJournal(str(tmp_path / "j"),
                                checkpoint_every=0)
        j.append("session_open", key="s-ab", tenant="t0",
                 header_sha="x", refs=1)
        for n in range(3):
            j.append("wave_received", key="s-ab", wave=n,
                     sha=f"h{n}", reads=100, bytes=999)
        j.append("wave_absorbed", key="s-ab", wave=0, sha="h0",
                 reads_total=100, digest="d0")
        j.append("wave_absorbed", key="s-ab", wave=1, sha="h1",
                 reads_total=200, digest="d1")
        j.append("wave_rejected", key="s-ab", wave=2, reason="torn")
        aud = j.audit(full=True)["sessions"]["s-ab"]
        assert aud["waves"] == 3
        assert aud["absorbed"] == 2
        assert aud["lost_waves"] == []          # rejected != lost
        assert aud["duplicated_waves"] == []
        assert aud["rejected_waves"] != []
        assert aud["reads_total"] == 200

    def test_double_absorb_is_flagged_duplicated(self, tmp_path):
        j = sjournal.JobJournal(str(tmp_path / "j"),
                                checkpoint_every=0)
        j.append("session_open", key="s-cd", tenant="", header_sha="x",
                 refs=1)
        j.append("wave_received", key="s-cd", wave=0, sha="h0",
                 reads=10, bytes=99)
        j.append("wave_absorbed", key="s-cd", wave=0, sha="h0",
                 reads_total=10, digest="d")
        j.append("wave_absorbed", key="s-cd", wave=0, sha="h0",
                 reads_total=20, digest="d")
        aud = j.audit(full=True)["sessions"]["s-cd"]
        assert aud["duplicated_waves"] != []

    def test_compacted_replay_equals_full(self, tmp_path):
        j = sjournal.JobJournal(str(tmp_path / "j"),
                                checkpoint_every=2)
        j.append("session_open", key="s-ef", tenant="t",
                 header_sha="x", refs=2)
        for n in range(4):
            j.append("wave_received", key="s-ef", wave=n, sha=f"h{n}",
                     reads=50, bytes=100)
            j.append("wave_absorbed", key="s-ef", wave=n, sha=f"h{n}",
                     reads_total=50 * (n + 1), digest=f"d{n}")
        j.append("session_stable", key="s-ef", wave=3, digest="d3",
                 waves_stable=3)
        j.append("session_closed", key="s-ef", digest="d3",
                 outputs={}, reads_total=200)
        j2 = sjournal.JobJournal(str(tmp_path / "j"))
        assert j2.audit() == j2.audit(full=True)
        aud = j2.audit(full=True)["sessions"]["s-ef"]
        assert aud["status"] == "closed"
        assert aud["stable"] is True
        assert aud["lost_waves"] == [] and aud["duplicated_waves"] == []

    def test_stale_rejection_does_not_launder_a_reused_number(
            self, tmp_path):
        """Journals written before the no-reuse rule could reject wave
        N pre-receive and later journal a valid intent under the same
        N.  The seq fence (effective_rejections) must keep that ACKed
        wave in the replay set instead of laundering it as rejected —
        the HIGH-severity lost-reads hole."""
        j = sjournal.JobJournal(str(tmp_path / "j"),
                                checkpoint_every=0)
        j.append("session_open", key="s-gh", tenant="",
                 header_sha="x", refs=1)
        j.append("wave_rejected", key="s-gh", wave=1,
                 reason="sha_mismatch")           # pre-receive reject
        j.append("wave_received", key="s-gh", wave=1, sha="h1",
                 reads=5, bytes=9)                # number reused later
        view = j.read_state().sessions["s-gh"]
        assert sjournal.effective_rejections(view) == set()
        aud = j.audit(full=True)["sessions"]["s-gh"]
        assert aud["lost_waves"] == ["1"]       # still needs replay
        assert aud["rejected_waves"] == ["1"]   # but stays accounted
        # a rejection journaled AFTER the intent (torn spool) gates
        j.append("wave_rejected", key="s-gh", wave=1, reason="torn")
        view = j.read_state().sessions["s-gh"]
        assert sjournal.effective_rejections(view) == {"1"}
        assert j.audit(
            full=True)["sessions"]["s-gh"]["lost_waves"] == []
        # a rejection of a number never received at all is effective
        # (there is nothing to replay)
        j.append("wave_rejected", key="s-gh", wave=2,
                 reason="malformed_wave")
        view = j.read_state().sessions["s-gh"]
        assert sjournal.effective_rejections(view) == {"1", "2"}


# =========================================================================
# absorb engine: exactly-once, byte-identity, torn waves, stability
# =========================================================================
class TestSessionAbsorb:
    def test_stream_byte_identical_to_one_shot(self, tmp_path):
        """The tentpole oracle: a session fed the corpus wave by wave
        writes per-reference FASTA content byte-identical to the
        one-shot run over the concatenated SAM."""
        from sam2consensus_tpu.serve import JobSpec, ServeRunner

        header, bodies, concat = _corpus(tmp_path, n_waves=3)
        r = _runner(tmp_path)
        mgr = SessionManager(r, _cfg(tmp_path), stability_waves=99,
                             revote_debounce=0.0)
        r.sessions = mgr
        try:
            sid = mgr.open_session(header, tenant="t0")["sid"]
            total = 0
            for body in bodies:
                ack = mgr.receive_wave(
                    sid, body, declared_sha="sha256:" +
                    sha256_hex(body))
                assert ack["status"] == "absorbed"
                total = ack["reads_total"]
            res = mgr.close_session(sid)
            assert res["outputs"], "session wrote no FASTA outputs"
            assert res["reads_total"] == total
            aud = r.journal.audit(full=True)["sessions"][sid]
            assert aud["lost_waves"] == []
            assert aud["duplicated_waves"] == []
            assert aud["absorbed"] == len(bodies)

            # health snapshot carries the sessions section (absorbed
            # counters survive the close)
            snap = r.health_snapshot()
            assert snap["sessions"]["waves_absorbed"] == len(bodies)

            # exposition: session counters ride the worker-labeled,
            # lint-clean OpenMetrics text
            from sam2consensus_tpu.observability.telemetry import \
                lint_openmetrics

            tel = r.render_telemetry()
            assert lint_openmetrics(tel) == []
            assert "s2c_session_waves_absorbed_total" in tel
            assert "s2c_session_opened_total" in tel
        finally:
            r.close()

        rb = ServeRunner(prewarm="off", persistent_cache=False)
        try:
            one = rb.submit_jobs([JobSpec(filename=concat,
                                          config=_cfg(tmp_path))])[0]
            assert one.error is None, one.error
        finally:
            rb.close()
        from sam2consensus_tpu.io.fasta import write_outputs

        oneshot_dir = str(tmp_path / "oneshot_fasta")
        os.makedirs(oneshot_dir)
        paths = write_outputs(one.fastas, oneshot_dir + os.sep, "", 0,
                              [0.25], echo=lambda *a, **k: None)
        assert _content_shas(res["outputs"]) == _content_shas(paths)
        assert res["digest"] == consensus_digest(one.fastas)

    def test_declared_sha_mismatch_rejected_never_absorbed(
            self, tmp_path):
        header, bodies, _ = _corpus(tmp_path, n_waves=2)
        r = _runner(tmp_path)
        mgr = SessionManager(r, _cfg(tmp_path), stability_waves=99,
                             revote_debounce=0.0)
        try:
            sid = mgr.open_session(header)["sid"]
            with pytest.raises(SessionError) as ei:
                mgr.receive_wave(sid, bodies[0],
                                 declared_sha="sha256:" + "0" * 64)
            assert ei.value.status == 422
            assert ei.value.reason == "sha_mismatch"
            # the session survives: the same bytes with the right sha
            # absorb cleanly afterwards
            ack = mgr.receive_wave(
                sid, bodies[0],
                declared_sha="sha256:" + sha256_hex(bodies[0]))
            assert ack["status"] == "absorbed"
            aud = r.journal.audit(full=True)["sessions"][sid]
            assert aud["rejected_waves"] != []
            assert aud["lost_waves"] == []
        finally:
            r.close()

    def test_malformed_and_empty_waves_are_data_class(self, tmp_path):
        header, _, _ = _corpus(tmp_path, n_waves=1)
        r = _runner(tmp_path)
        mgr = SessionManager(r, _cfg(tmp_path), revote_debounce=0.0)
        try:
            sid = mgr.open_session(header)["sid"]
            with pytest.raises(SessionError) as ei:
                mgr.receive_wave(sid, b"not\ta\tsam\trecord\n")
            assert ei.value.status == 422
            assert ei.value.reason == "malformed_wave"
            with pytest.raises(SessionError) as ei:
                mgr.receive_wave(sid, b"@CO just header noise\n")
            assert ei.value.status == 422
            assert ei.value.reason == "empty_wave"
        finally:
            r.close()

    def test_torn_spool_re_requested_then_resent(self, tmp_path):
        """Crash-torn spool: the journaled intent's sha no longer
        matches the file — the wave lands on the resend list, is never
        absorbed, and a client re-post of the same bytes recovers."""
        header, bodies, _ = _corpus(tmp_path, n_waves=2)
        r = _runner(tmp_path)
        mgr = SessionManager(r, _cfg(tmp_path), stability_waves=99,
                             revote_debounce=0.2)     # hold pending
        try:
            sid = mgr.open_session(header)["sid"]
            ack = mgr.receive_wave(sid, bodies[0])
            assert ack["status"] == "pending"
            n = ack["wave"]
            sess = mgr.sessions[sid]
            with open(sess.body_path(n), "wb") as fh:
                fh.write(bodies[0][: len(bodies[0]) // 2])   # tear it
            time.sleep(0.25)            # let the debounce expire
            mgr.tick()
            st = mgr.status(sid)
            assert st["absorbed"] == 0
            assert st["resend"] == [n]
            assert r.registry.value("session/torn_waves") == 1
            # resend: same bytes arrive as a fresh wave and absorb
            mgr.receive_wave(sid, bodies[0])
            time.sleep(0.25)
            mgr.tick()
            st = mgr.status(sid)
            assert st["absorbed"] == 1 and st["reads_total"] > 0
            aud = r.journal.audit(full=True)["sessions"][sid]
            assert aud["lost_waves"] == []
            assert aud["duplicated_waves"] == []
            assert aud["rejected_waves"] != []     # the torn wave
        finally:
            r.close()

    def test_stability_verdict_and_revote_without_ingest(
            self, tmp_path):
        """Identical wave content only deepens coverage — the digest
        holds still, the read-until verdict fires at the configured
        streak, and revote() re-votes with zero new ingest."""
        header, bodies, _ = _corpus(tmp_path, n_waves=1)
        body = bodies[0]
        r = _runner(tmp_path)
        mgr = SessionManager(r, _cfg(tmp_path), stability_waves=2,
                             revote_debounce=0.0)
        try:
            sid = mgr.open_session(header)["sid"]
            a0 = mgr.receive_wave(sid, body)
            assert a0["stable"] is False
            a1 = mgr.receive_wave(sid, body)
            assert a1["stable"] is True
            assert a1["stable_wave"] == a1["wave"]
            assert a1["digest"] == a0["digest"] != ""
            evs = [e for e in r.journal.events()
                   if e.get("ev") == "session_stable"]
            assert len(evs) == 1 and evs[0]["key"] == sid
            before = mgr.status(sid)
            rv = mgr.revote(sid)
            assert rv["digest"] == a1["digest"]
            assert mgr.status(sid)["waves"] == before["waves"]
            assert r.registry.value("session/revotes") == 1
        finally:
            r.close()

    def test_backpressure_sheds_with_retry_after(self, tmp_path):
        header, bodies, _ = _corpus(tmp_path, n_waves=2)
        r = _runner(tmp_path)
        mgr = SessionManager(r, _cfg(tmp_path), revote_debounce=60.0,
                             max_pending=1)
        try:
            sid = mgr.open_session(header)["sid"]
            assert mgr.receive_wave(
                sid, bodies[0])["status"] == "pending"
            with pytest.raises(SessionError) as ei:
                mgr.receive_wave(sid, bodies[1])
            assert ei.value.status == 429
            assert ei.value.retry_after and ei.value.retry_after > 0
            assert r.registry.value("session/waves_shed") == 1
        finally:
            r.close()

    def test_unknown_session_is_404(self, tmp_path):
        r = _runner(tmp_path)
        mgr = SessionManager(r, _cfg(tmp_path))
        try:
            with pytest.raises(SessionError) as ei:
                mgr.status("s-nope")
            assert ei.value.status == 404
            with pytest.raises(SessionError) as ei:
                mgr.receive_wave("s-nope", b"x\t" * 10 + b"x\n")
            assert ei.value.status == 404
        finally:
            r.close()


# =========================================================================
# HTTP front door: the full status taxonomy against a live server
# =========================================================================
class TestIngestHTTP:
    def _request(self, port, method, path, body=b"", headers=None):
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=15)
        try:
            hdrs = dict(headers or {})
            if method == "POST":
                hdrs.setdefault("Content-Length", str(len(body)))
            conn.request(method, path, body=body or None,
                         headers=hdrs)
            resp = conn.getresponse()
            payload = resp.read()
            try:
                doc = json.loads(payload.decode("utf-8"))
            except Exception:
                doc = {}
            return resp.status, doc, dict(resp.getheaders())
        finally:
            conn.close()

    def test_status_taxonomy_end_to_end(self, tmp_path):
        from sam2consensus_tpu.serve.stream_server import IngestServer

        header, bodies, _ = _corpus(tmp_path, n_waves=2)
        r = _runner(tmp_path)
        mgr = SessionManager(r, _cfg(tmp_path), stability_waves=99,
                             revote_debounce=0.0)
        srv = IngestServer(mgr, port=0,
                           max_body=max(len(b) for b in bodies) + 512,
                           timeout=10.0)
        port = srv.port
        try:
            # routing + method taxonomy
            assert self._request(port, "GET", "/nope")[0] == 404
            assert self._request(port, "PUT", "/session/open")[0] == 405
            assert self._request(
                port, "POST", "/session/x/frob")[0] == 404
            assert self._request(
                port, "GET", "/session/s-missing")[0] == 404

            # DATA-class open: header with no usable @SQ
            st, doc, _ = self._request(port, "POST", "/session/open",
                                       b"@CO\tnothing here\n")
            assert st == 422 and doc["error"] == "bad_header"

            st, doc, _ = self._request(
                port, "POST", "/session/open",
                header.encode("utf-8"), {"X-Tenant": "net0"})
            assert st == 200
            sid = doc["sid"]

            # torn upload: declared sha disagrees with the bytes
            st, doc, _ = self._request(
                port, "POST", f"/session/{sid}/wave", bodies[0],
                {"X-Wave-Sha256": "sha256:" + "f" * 64})
            assert st == 422 and doc["error"] == "sha_mismatch"

            # oversize wave: refused by declared length, 413
            big = b"x" * (srv.max_body + 1)
            st, _, _ = self._request(
                port, "POST", f"/session/{sid}/wave", big)
            assert st == 413

            # POST without a length is 400, not a hang
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=15)
            try:
                conn.putrequest("POST", f"/session/{sid}/wave")
                conn.endheaders()
                assert conn.getresponse().status == 400
            finally:
                conn.close()

            # the happy path still works after every rejection above
            st, doc, _ = self._request(
                port, "POST", f"/session/{sid}/wave", bodies[0],
                {"X-Wave-Sha256": "sha256:" + sha256_hex(bodies[0])})
            assert st == 200 and doc["status"] == "absorbed"
            assert doc["digest"]

            # revote on no new ingest answers 200 with the digest
            st, doc2, _ = self._request(
                port, "POST", f"/session/{sid}/revote")
            assert st == 200 and doc2["digest"] == doc["digest"]

            st, doc, _ = self._request(port, "GET", f"/session/{sid}")
            assert st == 200 and doc["absorbed"] == 1

            st, doc, _ = self._request(port, "GET", "/sessions")
            assert st == 200 and doc["open"] == 1
            assert doc["waves_rejected"] >= 1

            st, doc, _ = self._request(
                port, "POST", f"/session/{sid}/close")
            assert st == 200 and doc["outputs"]

            # a closed session is gone: the wave answers 404
            st, _, _ = self._request(
                port, "POST", f"/session/{sid}/wave", bodies[1])
            assert st == 404
        finally:
            srv.close()
            r.close()

    def test_backpressure_answers_429_with_retry_after(self, tmp_path):
        from sam2consensus_tpu.serve.stream_server import IngestServer

        header, bodies, _ = _corpus(tmp_path, n_waves=2)
        r = _runner(tmp_path)
        mgr = SessionManager(r, _cfg(tmp_path), revote_debounce=60.0,
                             max_pending=1)
        srv = IngestServer(mgr, port=0, max_body=1 << 20, timeout=10.0)
        try:
            st, doc, _ = self._request(
                srv.port, "POST", "/session/open",
                header.encode("utf-8"))
            sid = doc["sid"]
            st, doc, _ = self._request(
                srv.port, "POST", f"/session/{sid}/wave", bodies[0])
            assert st == 202 and doc["status"] == "pending"
            st, doc, hdrs = self._request(
                srv.port, "POST", f"/session/{sid}/wave", bodies[1])
            assert st == 429
            assert float(hdrs.get("Retry-After", "0")) > 0
        finally:
            srv.close()
            r.close()


# =========================================================================
# crash recovery: orphaned sessions are adopted and replayed
# =========================================================================
class TestSessionRecovery:
    def test_peer_adopts_orphan_and_replays_uncovered_wave(
            self, tmp_path):
        """In-process model of the SIGKILL story: worker w0 absorbs
        two waves, ACKs a third (journaled intent + spool) and dies
        before absorbing it.  Peer w1 adopts the session once the
        lease expires, replays exactly the uncovered wave, and closes
        with all reads counted once."""
        from sam2consensus_tpu.serve.session import _count_reads

        header, bodies, _ = _corpus(tmp_path, n_waves=3)
        cfg = _cfg(tmp_path)
        ra = _runner(tmp_path, worker="w0", ttl=0.6)
        ma = SessionManager(ra, cfg, stability_waves=99,
                            revote_debounce=0.0)
        sid = ma.open_session(header, tenant="tr")["sid"]
        for body in bodies[:2]:
            assert ma.receive_wave(sid, body)["status"] == "absorbed"
        # the crash site: the next wave was ACKed (spool + journal
        # intent) but the worker died before the absorb
        sess = ma.sessions[sid]
        n = sess.wave_next
        with open(sess.body_path(n), "wb") as fh:
            fh.write(bodies[2])
        ra.journal.append("wave_received", key=sid, wave=n,
                          sha=sha256_hex(bodies[2]),
                          reads=_count_reads(bodies[2]),
                          bytes=len(bodies[2]))
        expected_reads = sum(_count_reads(b) for b in bodies)
        ra.close()          # w0 is gone; its lease will expire

        rb = _runner(tmp_path, worker="w1", ttl=0.6)
        mb = SessionManager(rb, cfg, stability_waves=99,
                            revote_debounce=0.0)
        try:
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                mb.tick()
                if sid in mb.sessions:
                    break
                time.sleep(0.2)
            assert sid in mb.sessions, "peer never adopted the orphan"
            st = mb.status(sid)
            assert st["stolen_from"] == "w0"
            assert st["absorbed"] == 3
            assert st["reads_total"] == expected_reads
            res = mb.close_session(sid)
            assert res["outputs"]
            aud = rb.journal.audit(full=True)["sessions"][sid]
            assert aud["lost_waves"] == []
            assert aud["duplicated_waves"] == []
            assert rb.registry.value("session/steals") == 1
        finally:
            rb.close()

    def test_sigkill_steal_subprocess_smoke(self, tmp_path):
        """One kill cycle of the real thing: two CLI server processes,
        SIGKILL mid-stream, client retargets, byte-identity + audit.
        (The rotating multi-mode soak is the slow test below.)"""
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import session_soak

        out = str(tmp_path / "soak.jsonl")
        rc = session_soak.main([
            "--cycles", "1", "--waves", "4", "--reads", "3000",
            "--contig-len", "2500", "--lease-ttl", "1.5",
            "--out", out, "--workdir", str(tmp_path / "wk")])
        assert rc == 0
        rows = [json.loads(ln) for ln in open(out) if ln.strip()]
        summary = rows[-1]
        assert summary["kind"] == "summary"
        assert summary["schema"] == "s2c-session-soak/1"
        assert summary["failures"] == 0
        assert summary["identical_all"] is True
        assert summary["lost_total"] == 0
        assert summary["duplicated_total"] == 0
        assert summary["max_steal_sec"] is not None
        assert summary["max_steal_sec"] <= summary["steal_bound_sec"]

    @pytest.mark.slow
    def test_session_soak_all_modes(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import session_soak

        out = str(tmp_path / "soak.jsonl")
        rc = session_soak.main([
            "--cycles", "3", "--waves", "5", "--reads", "4000",
            "--contig-len", "3000", "--lease-ttl", "2.0",
            "--out", out, "--workdir", str(tmp_path / "wk")])
        assert rc == 0
        rows = [json.loads(ln) for ln in open(out) if ln.strip()]
        summary = rows[-1]
        assert summary["failures"] == 0
        assert summary["identical_all"] is True
        assert {r["mode"] for r in rows if r.get("kind") == "cycle"} \
            == {"kill", "wedge", "fault"}


# =========================================================================
# serve CLI: incoherent session flags fail at parse time
# =========================================================================
class TestSessionCLI:
    def test_session_flag_cross_checks(self, tmp_path):
        from sam2consensus_tpu.cli import serve_main

        j = str(tmp_path / "j")
        with pytest.raises(SystemExit,
                           match="--ingest-port requires --journal"):
            serve_main(["--ingest-port", "0"])
        with pytest.raises(SystemExit,
                           match="does not compose with -i/--input"):
            serve_main(["--ingest-port", "0", "--journal", j,
                        "-i", "x.sam"])
        with pytest.raises(SystemExit, match="--batch"):
            serve_main(["--ingest-port", "0", "--journal", j,
                        "--batch", "4"])
        with pytest.raises(SystemExit, match="--incremental"):
            serve_main(["--ingest-port", "0", "--journal", j,
                        "--incremental"])
        with pytest.raises(SystemExit, match="--count-cache"):
            serve_main(["--ingest-port", "0", "--journal", j,
                        "--count-cache", "64M"])
        with pytest.raises(SystemExit,
                           match="--stability-waves must be >= 1"):
            serve_main(["--ingest-port", "0", "--journal", j,
                        "--stability-waves", "0"])
        with pytest.raises(SystemExit,
                           match="--revote-debounce must be >= 0"):
            serve_main(["--ingest-port", "0", "--journal", j,
                        "--revote-debounce", "-1"])
        with pytest.raises(SystemExit,
                           match="--ingest-max-body must be > 0"):
            serve_main(["--ingest-port", "0", "--journal", j,
                        "--ingest-max-body", "0"])
        with pytest.raises(SystemExit,
                           match="--ingest-timeout must be > 0"):
            serve_main(["--ingest-port", "0", "--journal", j,
                        "--ingest-timeout", "0"])
        with pytest.raises(SystemExit,
                           match="--ingest-max-pending must be >= 1"):
            serve_main(["--ingest-port", "0", "--journal", j,
                        "--ingest-max-pending", "0"])
        with pytest.raises(SystemExit,
                           match="at least one -i/--input"):
            serve_main([])


# =========================================================================
# review hardening: wave-number consumption, lock planes, keep-alive
# framing, own-orphan re-adoption, orphan-scan throttle
# =========================================================================
class TestReviewHardening:
    def test_rejection_never_voids_a_later_acked_wave(self, tmp_path):
        """The review's lost-reads sequence, end to end: a torn upload
        is 422-rejected, the client re-sends and gets a 202 ACK, the
        worker dies before absorbing — the thief must replay the ACKed
        wave (the rejection consumed its own wave number and must not
        gate the resend)."""
        from sam2consensus_tpu.serve.session import _count_reads

        header, bodies, _ = _corpus(tmp_path, n_waves=2)
        cfg = _cfg(tmp_path)
        ra = _runner(tmp_path, worker="w0", ttl=0.6)
        ma = SessionManager(ra, cfg, stability_waves=99,
                            revote_debounce=60.0)    # hold pending
        sid = ma.open_session(header)["sid"]
        assert ma.receive_wave(sid, bodies[0])["status"] == "pending"
        with pytest.raises(SessionError) as ei:
            ma.receive_wave(sid, bodies[1],
                            declared_sha="sha256:" + "0" * 64)
        assert ei.value.reason == "sha_mismatch"
        ack = ma.receive_wave(
            sid, bodies[1],
            declared_sha="sha256:" + sha256_hex(bodies[1]))
        assert ack["status"] == "pending"
        # the rejection consumed its number: no journaled wave shares
        # a number with a journaled rejection
        view = ra.journal.read_state().sessions[sid]
        assert set(view["rejected"]).isdisjoint(set(view["waves"]))
        expected = sum(_count_reads(b) for b in bodies)
        ra.close()      # crash before any absorb; the lease expires

        rb = _runner(tmp_path, worker="w1", ttl=0.6)
        mb = SessionManager(rb, cfg, stability_waves=99,
                            revote_debounce=0.0)
        try:
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                mb.tick()
                if sid in mb.sessions:
                    break
                time.sleep(0.2)
            assert sid in mb.sessions, "thief never adopted"
            st = mb.status(sid)
            assert st["absorbed"] == 2          # BOTH valid waves
            assert st["reads_total"] == expected
            aud = rb.journal.audit(full=True)["sessions"][sid]
            assert aud["lost_waves"] == []
            assert aud["duplicated_waves"] == []
            assert aud["rejected_waves"] != []
            # fresh ingest resumes past every journaled number,
            # rejected ones included
            assert mb.sessions[sid].wave_next > max(
                int(w) for w in view["rejected"])
        finally:
            rb.close()

    def test_observability_answers_while_a_wave_lock_is_held(
            self, tmp_path):
        """status(), health_summary() and OTHER sessions' ingest must
        not queue behind one session's absorb (the review's global-
        RLock stall): hold one session's wave lock — a stand-in for a
        minutes-long backend run — and everything else still answers."""
        import threading

        header, bodies, _ = _corpus(tmp_path, n_waves=2)
        r = _runner(tmp_path)
        mgr = SessionManager(r, _cfg(tmp_path), stability_waves=99,
                             revote_debounce=60.0)   # no backend runs
        try:
            s1 = mgr.open_session(header, tenant="a")["sid"]
            s2 = mgr.open_session(header, tenant="b")["sid"]
            mgr.receive_wave(s1, bodies[0])
            held, release = threading.Event(), threading.Event()

            def long_absorb():
                with mgr.sessions[s1].lock:
                    held.set()
                    release.wait(20.0)

            t = threading.Thread(target=long_absorb, daemon=True)
            t.start()
            assert held.wait(5.0)
            t0 = time.monotonic()
            st = mgr.status(s1)                 # mid-absorb probe
            hs = mgr.health_summary()
            ack = mgr.receive_wave(s2, bodies[1])   # another tenant
            took = time.monotonic() - t0
            release.set()
            t.join(10.0)
            assert took < 5.0, \
                f"observability blocked {took:.1f}s behind a wave lock"
            assert st["waves"] == 1 and st["pending"]
            assert hs["open"] == 2
            assert ack["status"] == "pending"
        finally:
            r.close()

    def test_early_error_closes_keepalive_connection(self, tmp_path):
        """An error reply sent before the request body is consumed
        (413 on declared length) must close the connection — replying
        and then parsing the unread body bytes as the next request
        desyncs keep-alive into a 400 cascade."""
        import socket

        from sam2consensus_tpu.serve.stream_server import IngestServer

        r = _runner(tmp_path)
        mgr = SessionManager(r, _cfg(tmp_path), revote_debounce=0.0)
        srv = IngestServer(mgr, port=0, max_body=1024, timeout=5.0)
        try:
            req = ("POST /session/open HTTP/1.1\r\nHost: t\r\n"
                   f"Content-Length: {srv.max_body + 1}\r\n\r\n"
                   ).encode("ascii")
            # bytes a desynced server would parse as a second request
            trailing = b"GET /sessions HTTP/1.1\r\nHost: t\r\n\r\n"
            with socket.create_connection(
                    ("127.0.0.1", srv.port), timeout=10.0) as s:
                s.sendall(req + trailing)
                s.settimeout(10.0)
                buf = b""
                while True:
                    try:
                        chunk = s.recv(65536)
                    except socket.timeout:
                        break
                    if not chunk:
                        break
                    buf += chunk
            assert buf.startswith(b"HTTP/1.1 413")
            # exactly ONE response: the server closed instead of
            # answering the leftover bytes as a pipelined GET
            assert buf.count(b"HTTP/1.1 ") == 1
        finally:
            srv.close()
            r.close()

    def test_restarted_worker_readopts_its_own_orphans(self, tmp_path):
        """A worker restarted under the SAME --worker-id must adopt
        its own orphaned sessions from tick() — before the fix the
        scan skipped any lease bearing its own id, so in a one-worker
        fleet journaled-but-unabsorbed waves waited forever."""
        from sam2consensus_tpu.serve.session import _count_reads

        header, bodies, _ = _corpus(tmp_path, n_waves=1)
        cfg = _cfg(tmp_path)
        ra = _runner(tmp_path, worker="w0", ttl=0.6)
        ma = SessionManager(ra, cfg, revote_debounce=60.0)
        sid = ma.open_session(header)["sid"]
        assert ma.receive_wave(sid, bodies[0])["status"] == "pending"
        ra.close()      # crash: the journal lease stays under w0

        rb = _runner(tmp_path, worker="w0", ttl=0.6)  # same id
        mb = SessionManager(rb, cfg, revote_debounce=0.0)
        try:
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                mb.tick()
                if sid in mb.sessions:
                    break
                time.sleep(0.2)
            assert sid in mb.sessions, \
                "restarted worker never re-adopted its own orphan"
            st = mb.status(sid)
            assert st["absorbed"] == 1
            assert st["reads_total"] == _count_reads(bodies[0])
            # recovering one's own session is not a steal
            assert st["stolen_from"] == ""
            assert rb.registry.value("session/steals") == 0.0
        finally:
            rb.close()

    def test_orphan_scan_is_throttled_below_tick_rate(self, tmp_path):
        """tick() runs at 10 Hz in the drain loop; the orphan scan (a
        full journal tail replay from disk) must run on its own
        lease-TTL-fraction cadence, not every tick."""
        r = _runner(tmp_path, worker="w0", ttl=40.0)
        mgr = SessionManager(r, _cfg(tmp_path))
        try:
            calls = [0]
            orig = r.journal.read_state

            def counting(*a, **k):
                calls[0] += 1
                return orig(*a, **k)

            r.journal.read_state = counting
            for _ in range(30):         # ~3 s of drain-loop ticks
                mgr.tick()
                time.sleep(0.01)
            # ttl/4 = 10 s cadence: exactly the first tick scans
            assert calls[0] == 1
        finally:
            r.close()
