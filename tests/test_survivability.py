"""Serve survivability: crash-safe journal, watchdog, admission, health.

The r6 acceptance pins live here:

* a ``kill -9`` mid-queue (real SIGKILL, subprocess) costs nothing: the
  restarted server produces the full byte-identical output set with no
  job run twice (journal fingerprint audit);
* a hung dispatch (``job_hang`` fault site) costs exactly ONE job — the
  watchdog fails it (or, under fallback, retries it on the ladder's
  host rung) while the next job runs warm on the device rung;
* admission control bounds the queue and pins a degraded tenant's jobs
  to the host rung without demoting the fleet;
* the health snapshot and the manifest's ``serve`` section carry the
  recovery story.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.io.fasta import render_file
from sam2consensus_tpu.serve import journal as sjournal
from sam2consensus_tpu.utils.simulate import SimSpec, simulate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_persistent_cache(monkeypatch):
    monkeypatch.setenv("S2C_JIT_CACHE", "")


def _sim(tmp, name, seed, contig_len=3000, n_reads=1200, prefix="srv"):
    spec = SimSpec(n_contigs=1, contig_len=contig_len, n_reads=n_reads,
                   read_len=100, contig_len_jitter=0.0, seed=seed,
                   contig_prefix=prefix)
    path = os.path.join(str(tmp), name)
    with open(path, "w") as fh:
        fh.write(simulate(spec))
    return path


def _runner(**kw):
    from sam2consensus_tpu.serve import ServeRunner

    kw.setdefault("prewarm", "off")
    kw.setdefault("persistent_cache", False)
    return ServeRunner(**kw)


def _rendered(result):
    return {n: render_file(r, 0) for n, r in result.fastas.items()}


def _cold_jax(path, cfg):
    from sam2consensus_tpu.backends.jax_backend import JaxBackend
    from sam2consensus_tpu.io.sam import (ReadStream, opener,
                                          read_header)

    h = opener(path, binary=True)
    contigs, _n, first = read_header(h)
    res = JaxBackend().run(contigs, ReadStream(h, first), cfg)
    h.close()
    return {n: render_file(r, 0) for n, r in res.fastas.items()}


BASE = dict(backend="jax", pileup="scatter", shards=1)


# -- journal unit behavior -------------------------------------------------
def test_journal_append_replay_roundtrip(tmp_path):
    j = sjournal.JobJournal(str(tmp_path / "j"))
    j.append("submitted", job="a", key="k1", filename="/x/a.sam")
    j.append("started", job="a", key="k1", ckpt="")
    j.append("committed", job="a", key="k1",
             outputs={}, elapsed_sec=0.5)
    j.append("started", job="b", key="k2", ckpt="")
    st = j.replay()
    assert set(st.committed) == {"k1"}
    assert set(st.inflight) == {"k2"}
    assert st.commit_counts == {"k1": 1}
    assert st.last_seq == 4
    # a new handle over the same dir continues the sequence
    j2 = sjournal.JobJournal(str(tmp_path / "j"))
    assert j2.append("failed", job="b", key="k2", error="boom") == 5
    st2 = j2.replay()
    assert st2.inflight == {} and set(st2.failed) == {"k2"}


def test_journal_segments_are_atomic_and_corrupt_tolerant(tmp_path):
    j = sjournal.JobJournal(str(tmp_path / "j"))
    j.append("submitted", job="a", key="k1")
    j.append("committed", job="a", key="k1", outputs={})
    # no tmp droppings (atomic rename), and external damage to one
    # segment skips it without losing the rest
    names = os.listdir(j.root)
    assert not [n for n in names if n.endswith(".tmp")]
    seg = os.path.join(j.root, "ev-00000001.json")
    with open(seg, "w") as fh:
        fh.write('{"ev": "subm')            # torn by external damage
    st = j.replay()
    assert st.corrupt_segments == 1
    assert set(st.committed) == {"k1"}      # the intact event survives


def test_job_key_tracks_output_relevant_config_only(tmp_path):
    a = RunConfig(**BASE, thresholds=[0.25])
    same = RunConfig(**BASE, thresholds=[0.25], retries=9, wire="delta8")
    different = RunConfig(**BASE, thresholds=[0.5])
    assert sjournal.job_key("x.sam", a) == sjournal.job_key("x.sam", same)
    assert sjournal.job_key("x.sam", a) != sjournal.job_key("x.sam",
                                                            different)
    assert sjournal.job_key("x.sam", a) != sjournal.job_key("y.sam", a)


def test_journal_verify_outputs_detects_drift(tmp_path):
    p = tmp_path / "out.fasta"
    p.write_text(">r\nACGT\n")
    fp = {str(p): sjournal.file_sha256(str(p))}
    rec = {"outputs": fp}
    j = sjournal.JobJournal(str(tmp_path / "j"))
    assert j.verify_outputs(rec)
    p.write_text(">r\nTTTT\n")              # drifted: must re-run
    assert not j.verify_outputs(rec)
    os.unlink(p)                            # missing: must re-run
    assert not j.verify_outputs(rec)
    assert not j.verify_outputs({"outputs": {}})
    # a null recorded fingerprint (commit-time hash failure) must not
    # match a missing file's null re-hash: unknown never verifies
    assert not j.verify_outputs({"outputs": {str(p): None}})


# -- the SIGKILL acceptance test -------------------------------------------
def _serve_cmd(inputs, outdir, jdir):
    cmd = [sys.executable, "-m", "sam2consensus_tpu.cli", "serve"]
    for p in inputs:
        cmd += ["-i", p]
    cmd += ["-o", outdir, "--journal", jdir, "--pileup", "scatter",
            "--quiet"]
    return cmd


def _committed(jdir):
    n = 0
    for name in os.listdir(jdir) if os.path.isdir(jdir) else []:
        if name.startswith("ev-") and name.endswith(".json"):
            try:
                with open(os.path.join(jdir, name)) as fh:
                    if json.load(fh).get("ev") == "committed":
                        n += 1
            except Exception:
                pass
    return n


def test_sigkill_midqueue_resume_byte_identical(tmp_path):
    """THE crash-resume pin: SIGKILL a journaled serve mid-queue; the
    restarted server completes the queue byte-identically with no job
    run twice (fingerprint audit) and no job lost."""
    inputs = [_sim(tmp_path, f"k{i}.sam", 300 + i, contig_len=6000,
                   n_reads=20000, prefix=f"kk{i}_") for i in range(3)]
    env = dict(os.environ, JAX_PLATFORMS="cpu", S2C_JIT_CACHE="",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    clean = str(tmp_path / "clean")
    r = subprocess.run(_serve_cmd(inputs, clean,
                                  str(tmp_path / "jc")), env=env,
                       capture_output=True, text=True, timeout=420)
    assert r.returncode == 0, r.stderr[-2000:]
    want = {f: open(os.path.join(clean, f), "rb").read()
            for f in sorted(os.listdir(clean))}
    assert len(want) == 3

    outdir, jdir = str(tmp_path / "out"), str(tmp_path / "j")
    proc = subprocess.Popen(_serve_cmd(inputs, outdir, jdir), env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    deadline = time.monotonic() + 300
    killed = False
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            break
        if 1 <= _committed(jdir) < 3:
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
            killed = True
            break
        time.sleep(0.05)
    assert killed, "server finished before the kill window (jobs too fast)"
    assert _committed(jdir) < 3             # genuinely mid-queue

    r2 = subprocess.run(_serve_cmd(inputs, outdir, jdir), env=env,
                        capture_output=True, text=True, timeout=420)
    assert r2.returncode == 0, r2.stderr[-2000:]
    got = {f: open(os.path.join(outdir, f), "rb").read()
           for f in sorted(os.listdir(outdir))}
    assert got == want                      # byte-identical output set
    audit = sjournal.JobJournal(jdir).audit()
    assert audit["duplicated"] == []        # no job ran (committed) twice
    assert audit["lost"] == []              # no job lost
    assert len(audit["commit_counts"]) == 3
    # the journal records the restart's resume bookkeeping
    evs = [e["ev"] for e in sjournal.JobJournal(jdir).events()]
    assert "resumed" in evs


def test_restart_over_completed_journal_skips_everything(tmp_path):
    from sam2consensus_tpu.serve import JobSpec

    path = _sim(tmp_path, "s.sam", 77)
    jdir = str(tmp_path / "j")
    cfg = RunConfig(**BASE, outfolder=str(tmp_path / "o") + "/")
    os.makedirs(str(tmp_path / "o"), exist_ok=True)
    r1 = _runner(journal_dir=jdir)
    [a] = r1.submit_jobs([JobSpec(filename=path, config=cfg)])
    assert a.ok and a.output_paths and not a.resumed
    r2 = _runner(journal_dir=jdir)
    [b] = r2.submit_jobs([JobSpec(filename=path, config=cfg)])
    assert b.ok and b.resumed and b.fastas is None
    assert r2.registry.value("serve/resume_skipped") == 1
    # drifted output re-runs instead of trusting the journal
    with open(a.output_paths[0], "a") as fh:
        fh.write("tampered\n")
    r3 = _runner(journal_dir=jdir)
    [c] = r3.submit_jobs([JobSpec(filename=path, config=cfg)])
    assert c.ok and not c.resumed           # re-ran and re-committed
    audit = sjournal.JobJournal(jdir).audit()
    assert audit["lost"] == []


# -- watchdog: deadlines + hung dispatch -----------------------------------
def test_hung_dispatch_costs_exactly_one_job(tmp_path, monkeypatch):
    """A wedged dispatch (job_hang site sleeping far past the deadline)
    fails ONLY its job; the next job runs warm on the device rung."""
    from sam2consensus_tpu.serve import JobSpec

    monkeypatch.setenv("S2C_FAULT_HANG_S", "600")
    paths = [_sim(tmp_path, f"h{i}.sam", 400 + i) for i in range(3)]
    hang = RunConfig(**BASE, fault_inject="job_hang:timeout:0:1")
    cfgs = [RunConfig(**BASE), hang, RunConfig(**BASE)]
    runner = _runner(job_timeout=3.0)
    res = runner.submit_jobs(
        [JobSpec(filename=p, config=c) for p, c in zip(paths, cfgs)])
    assert [r.ok for r in res] == [True, False, True]
    assert "JobDeadlineExceeded" in res[1].error
    assert res[1].metrics.get("serve/watchdog_timeouts") == 1
    assert runner.registry.value("serve/watchdog_timeouts") == 1
    # the NEXT job: device rung, warm, untouched by the hang
    assert res[2].rungs == {}
    assert res[2].metrics.get("compile/jit_cache_hit", 0) > 0
    assert res[2].metrics.get("resilience/demotions", 0) == 0
    for k in (0, 2):
        assert _rendered(res[k]) == _cold_jax(paths[k], RunConfig(**BASE))


def test_hung_job_retries_on_host_rung_under_fallback(tmp_path,
                                                      monkeypatch):
    """Fallback mode: the hung job is retried once on the ladder's host
    rung (job-level demotion), byte-identical; counters pin the story."""
    from sam2consensus_tpu.serve import JobSpec

    monkeypatch.setenv("S2C_FAULT_HANG_S", "600")
    path = _sim(tmp_path, "hf.sam", 410)
    hang = RunConfig(**BASE, fault_inject="job_hang:timeout:0:1",
                     on_device_error="fallback")
    runner = _runner(job_timeout=3.0)
    [r] = runner.submit_jobs([JobSpec(filename=path, config=hang)])
    assert r.ok, r.error
    assert r.rungs.get("pileup") == "host"  # job-level ladder rung
    assert r.metrics.get("serve/job_retries") == 1
    assert r.metrics.get("serve/watchdog_timeouts") == 1
    assert _rendered(r) == _cold_jax(path, RunConfig(**BASE))


def test_stall_timeout_catches_wedge_before_job_deadline(tmp_path,
                                                         monkeypatch):
    from sam2consensus_tpu.serve import JobSpec

    monkeypatch.setenv("S2C_FAULT_HANG_S", "600")
    path = _sim(tmp_path, "st.sam", 420)
    hang = RunConfig(**BASE, fault_inject="job_hang:timeout:0:1")
    t0 = time.monotonic()
    runner = _runner(job_timeout=60.0, stall_timeout=2.0)
    [r] = runner.submit_jobs([JobSpec(filename=path, config=hang)])
    elapsed = time.monotonic() - t0
    assert not r.ok and "HungDispatchError" in r.error
    assert elapsed < 30                     # the 60s deadline never ran


def test_job_timeout_env_fallback(monkeypatch):
    monkeypatch.setenv("S2C_JOB_TIMEOUT", "7.5")
    monkeypatch.setenv("S2C_STALL_TIMEOUT", "2.5")
    runner = _runner()
    assert runner.job_timeout == 7.5
    assert runner.stall_timeout == 2.5
    runner2 = _runner(job_timeout=1.0)      # explicit beats env
    assert runner2.job_timeout == 1.0


# -- admission control ------------------------------------------------------
def test_admission_queue_bound_and_tenant_quota(tmp_path):
    from sam2consensus_tpu.serve import JobSpec

    paths = [_sim(tmp_path, f"a{i}.sam", 500 + i) for i in range(4)]
    runner = _runner(max_queue=3, tenant_quota=1)
    res = runner.submit_jobs([
        JobSpec(filename=paths[0], config=RunConfig(**BASE), tenant="a"),
        JobSpec(filename=paths[1], config=RunConfig(**BASE), tenant="a"),
        JobSpec(filename=paths[2], config=RunConfig(**BASE), tenant="b"),
        JobSpec(filename=paths[3], config=RunConfig(**BASE), tenant="c"),
    ])
    assert [r.ok for r in res] == [True, False, True, True]
    assert res[1].admission == "tenant_quota"
    assert "admission rejected" in res[1].error
    reg = runner.registry
    assert reg.value("serve/admission_rejected") == 1
    assert reg.value("serve/admission_rejected/tenant_quota") == 1
    assert reg.value("serve/admission_admitted") == 3
    # order preserved, admitted jobs correct
    assert _rendered(res[0]) == _cold_jax(paths[0], RunConfig(**BASE))
    # the bound is per submission window: a new submit admits again
    res2 = runner.submit_jobs([
        JobSpec(filename=paths[1], config=RunConfig(**BASE), tenant="a")])
    assert res2[0].ok


def test_admission_queue_full_sheds_overflow(tmp_path):
    from sam2consensus_tpu.serve import JobSpec

    path = _sim(tmp_path, "qf.sam", 510)
    runner = _runner(max_queue=2)
    res = runner.submit_jobs(
        [JobSpec(filename=path, config=RunConfig(**BASE))
         for _ in range(4)])
    assert [r.ok for r in res] == [True, True, False, False]
    assert {r.admission for r in res[2:]} == {"queue_full"}
    assert runner.registry.value(
        "serve/admission_rejected/queue_full") == 2


def test_degraded_tenant_pinned_to_host_rung_fleet_unharmed(tmp_path):
    """A tenant whose job demoted runs its NEXT job pinned to the host
    rung (byte-identical), other tenants stay on the device path, and
    one clean pinned job clears the tenant (probation)."""
    from sam2consensus_tpu.serve import JobSpec

    paths = [_sim(tmp_path, f"t{i}.sam", 520 + i) for i in range(4)]
    faulty = RunConfig(**BASE, fault_inject="pileup_dispatch:rpc:0:inf",
                       on_device_error="fallback", retries=1,
                       retry_backoff=0.01)
    runner = _runner()
    res = runner.submit_jobs([
        JobSpec(filename=paths[0], config=faulty, tenant="t"),
        JobSpec(filename=paths[1], config=RunConfig(**BASE), tenant="t"),
        JobSpec(filename=paths[2], config=RunConfig(**BASE), tenant="u"),
        JobSpec(filename=paths[3], config=RunConfig(**BASE), tenant="t"),
    ])
    assert all(r.ok for r in res)
    assert res[0].rungs.get("pileup") == "host"   # in-run demotion
    assert res[1].admission == "pinned:host"      # tenant isolation
    assert res[2].admission is None               # fleet unharmed
    # probation: job 1 (pinned) completed clean -> job 3 back on device
    assert res[3].admission is None
    assert runner.registry.value("serve/admission_pinned") == 1
    for k, p in enumerate(paths):
        assert _rendered(res[k]) == _cold_jax(p, RunConfig(**BASE)), k


# -- health + manifest ------------------------------------------------------
def test_health_snapshot_written_atomically(tmp_path):
    from sam2consensus_tpu.serve import JobSpec

    path = _sim(tmp_path, "he.sam", 530)
    hout = str(tmp_path / "health.json")
    runner = _runner(health_out=hout)
    res = runner.submit_jobs(
        [JobSpec(filename=path, config=RunConfig(**BASE))])
    assert res[0].ok
    h = json.load(open(hout))
    assert h["schema"] == "s2c-health/1"
    assert h["queue_depth"] == 0 and h["in_flight"] is None
    assert h["jobs"]["run"] == 1 and h["jobs"]["failed"] == 0
    assert h["last_heartbeat_age_sec"] >= 0
    assert not [n for n in os.listdir(tmp_path)
                if n.startswith("health.json.tmp")]
    # API snapshot agrees
    snap = runner.health_snapshot()
    assert snap["jobs"]["run"] == 1


def test_manifest_serve_section_carries_health_and_recovery(tmp_path):
    from sam2consensus_tpu.serve import JobSpec

    path = _sim(tmp_path, "mr.sam", 540)
    jdir = str(tmp_path / "j")
    cfg = RunConfig(**BASE, outfolder=str(tmp_path / "o") + "/",
                    metrics_out=str(tmp_path / "m.jsonl"))
    os.makedirs(str(tmp_path / "o"), exist_ok=True)
    r1 = _runner(journal_dir=jdir)
    [a] = r1.submit_jobs([JobSpec(filename=path, config=cfg)])
    assert a.ok
    man = json.load(open(str(tmp_path / "m.jsonl.manifest.json")))
    assert man["serve"]["serve/health"]["in_flight"].endswith("mr.sam")
    assert "serve/recovery" not in man["serve"]   # first run: no resume
    # crash simulation: drop the committed event so the job reads as
    # in-flight, then restart — the manifest records the recovery
    j = sjournal.JobJournal(jdir)
    for name in os.listdir(j.root):
        p = os.path.join(j.root, name)
        if name.endswith(".json"):
            with open(p) as fh:
                if json.load(fh).get("ev") == "committed":
                    os.unlink(p)
    cfg2 = RunConfig(**BASE, outfolder=str(tmp_path / "o") + "/",
                     metrics_out=str(tmp_path / "m2.jsonl"))
    r2 = _runner(journal_dir=jdir)
    [b] = r2.submit_jobs([JobSpec(filename=path, config=cfg2)])
    assert b.ok and not b.resumed
    man2 = json.load(open(str(tmp_path / "m2.jsonl.manifest.json")))
    rec = man2["serve"]["serve/recovery"]
    assert rec["resumed"] is True
    assert rec["inflight_resumed"]
    assert man2["serve"]["serve/health"]["journal_last_seq"] >= 1


# -- runner-scope fault sites ----------------------------------------------
def test_journal_write_fault_degrades_durability_not_correctness(
        tmp_path):
    from sam2consensus_tpu.serve import JobSpec

    path = _sim(tmp_path, "jw.sam", 550)
    cfg = RunConfig(**BASE, outfolder=str(tmp_path / "o") + "/")
    os.makedirs(str(tmp_path / "o"), exist_ok=True)
    runner = _runner(journal_dir=str(tmp_path / "j"),
                     fault_inject="journal_write:rpc:0:1")
    [r] = runner.submit_jobs([JobSpec(filename=path, config=cfg)])
    assert r.ok                              # the JOB survived
    assert runner.registry.value("serve/journal_write_failed") == 1
    assert r.output_paths                    # outputs still committed


def test_decode_ahead_fault_fails_only_its_job(tmp_path):
    from sam2consensus_tpu.serve import JobSpec

    paths = [_sim(tmp_path, f"d{i}.sam", 560 + i) for i in range(3)]
    runner = _runner(fault_inject="serve_decode_ahead:rpc:0:1")
    res = runner.submit_jobs(
        [JobSpec(filename=p, config=RunConfig(**BASE)) for p in paths])
    # job 1 is the first decode-ahead target; its poisoned decode fails
    # it alone, jobs 0 and 2 complete
    assert [r.ok for r in res] == [True, False, True]
    assert "InjectedRpcError" in res[1].error
    assert _rendered(res[2]) == _cold_jax(paths[2], RunConfig(**BASE))


def test_new_fault_sites_accepted_by_spec_grammar():
    from sam2consensus_tpu.resilience.faultinject import parse_spec

    rules = parse_spec("serve_decode_ahead:rpc:0:1,journal_write:fatal:2,"
                       "job_hang:timeout:0:1")
    assert [r.site for r in rules] == ["serve_decode_ahead",
                                      "journal_write", "job_hang"]
    with pytest.raises(ValueError):
        parse_spec("job_hangg:timeout:0")


def test_serve_cli_survivability_flags(tmp_path):
    """The serve CLI accepts the new flags end-to-end (journal +
    health + timeouts), writes per-job outputs at commit time, and a
    rerun resumes."""
    from sam2consensus_tpu import cli

    a = _sim(tmp_path, "cli_a.sam", 570)
    out = tmp_path / "out"
    jdir = str(tmp_path / "j")
    hout = str(tmp_path / "health.json")
    argv = ["serve", "-i", a, "-o", str(out), "--pileup", "scatter",
            "--quiet", "--journal", jdir, "--health-out", hout,
            "--job-timeout", "300"]
    assert cli.main(argv) == 0
    files = sorted(os.listdir(out))
    assert files
    before = {f: open(out / f, "rb").read() for f in files}
    assert json.load(open(hout))["journal"]["committed"] == 1
    assert cli.main(argv) == 0               # resume: all skipped
    after = {f: open(out / f, "rb").read() for f in sorted(
        os.listdir(out))}
    assert after == before
    audit = sjournal.JobJournal(jdir).audit()
    assert audit["duplicated"] == [] and audit["lost"] == []
