"""Position-sharded (long-context) accumulation vs the unsharded oracle.

``parallel.sp.PositionShardedConsensus`` must produce exactly the
unsharded counts for any read set — including rows that overhang device
block boundaries (the ppermute halo path), rows at the very edges of the
genome, PAD rows, and streaming over multiple chunks.  Runs on the 8
virtual CPU devices from tests/conftest.py (SURVEY.md §4 "multi-device
without a cluster").
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from sam2consensus_tpu.encoder.events import SegmentBatch  # noqa: E402
from sam2consensus_tpu.ops.pileup import PileupAccumulator  # noqa: E402
from sam2consensus_tpu.ops.cutoff import encode_thresholds  # noqa: E402
from sam2consensus_tpu.parallel.mesh import make_mesh  # noqa: E402
from sam2consensus_tpu.parallel.sp import PositionShardedConsensus  # noqa: E402

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")


def _batch(starts, codes):
    return SegmentBatch(buckets={codes.shape[1]: (starts, codes)},
                        n_reads=len(starts),
                        n_events=int((codes < 6).sum()))


def _ref_counts(total_len, starts, codes):
    acc = PileupAccumulator(total_len, strategy="scatter")
    acc.add(_batch(starts, codes))
    return acc.counts_host()


def test_sp_equals_unsharded_random():
    rng = np.random.default_rng(0)
    total_len = 9000
    w = 64
    starts = rng.integers(0, total_len - w, 700).astype(np.int32)
    codes = rng.integers(0, 6, (700, w)).astype(np.uint8)
    codes[rng.random(codes.shape) < 0.2] = 255

    sp = PositionShardedConsensus(make_mesh(8), total_len, halo=128)
    sp.add(_batch(starts, codes))
    assert np.array_equal(sp.counts_host(),
                          _ref_counts(total_len, starts, codes))


def test_sp_halo_boundary_rows():
    """Rows starting exactly at / just before block boundaries."""
    total_len = 8 * 1024 - 1
    w = 32
    sp = PositionShardedConsensus(make_mesh(8), total_len, halo=64)
    block = sp.block
    edge_starts = []
    for d in range(7):
        edge_starts += [d * block + block - 1,       # full overhang
                        d * block + block - w // 2,  # partial overhang
                        d * block]                   # block start
    edge_starts.append(total_len - w)                # genome end
    starts = np.asarray(edge_starts, dtype=np.int32)
    codes = np.tile(np.arange(w) % 6, (len(starts), 1)).astype(np.uint8)

    sp.add(_batch(starts, codes))
    assert np.array_equal(sp.counts_host(),
                          _ref_counts(total_len, starts, codes))


def test_sp_streaming_chunks_accumulate():
    rng = np.random.default_rng(5)
    total_len = 4096
    w = 32
    sp = PositionShardedConsensus(make_mesh(8), total_len, halo=w)
    all_s, all_c = [], []
    for chunk in range(3):
        starts = rng.integers(0, total_len - w, 100).astype(np.int32)
        codes = rng.integers(0, 6, (100, w)).astype(np.uint8)
        sp.add(_batch(starts, codes))
        all_s.append(starts)
        all_c.append(codes)
    ref = _ref_counts(total_len, np.concatenate(all_s),
                      np.concatenate(all_c))
    assert np.array_equal(sp.counts_host(), ref)


def test_sp_vote_matches_dp_vote():
    from sam2consensus_tpu.parallel.dp import ShardedConsensus

    rng = np.random.default_rng(9)
    total_len = 6000
    w = 64
    starts = rng.integers(0, total_len - w, 400).astype(np.int32)
    codes = rng.integers(0, 6, (400, w)).astype(np.uint8)

    sp = PositionShardedConsensus(make_mesh(8), total_len, halo=w)
    sp.add(_batch(starts, codes))
    dp = ShardedConsensus(make_mesh(8), total_len)
    dp.add(_batch(starts, codes))
    assert np.array_equal(sp.counts_host(), dp.counts_host())

    thr_enc = encode_thresholds([0.25, 0.75])
    syms_sp = sp.vote(thr_enc, 1)
    syms_dp = dp.vote(thr_enc, 1)
    assert np.array_equal(syms_sp, syms_dp)
    offs = np.asarray([0, total_len], dtype=np.int32)
    sums_sp, _ = sp.tail_stats(offs, np.zeros(0, dtype=np.int32))
    sums_dp, _ = dp.tail_stats(offs, np.zeros(0, dtype=np.int32))
    assert np.array_equal(sums_sp, sums_dp)


def test_sp_restore_roundtrip():
    total_len = 4096
    sp = PositionShardedConsensus(make_mesh(8), total_len, halo=32)
    rng = np.random.default_rng(2)
    counts = rng.integers(0, 50, (total_len, 6)).astype(np.int32)
    sp.restore(counts)
    assert np.array_equal(sp.counts_host(), counts)


def test_sp_memory_o_block_at_250mbp():
    """Per-device memory of the sp accumulate stays O(L/n + H) at true
    chromosome scale (250 Mbp), vs the dp path's O(L) transient — the
    scenario where the reference's per-position dict allocation dies
    (/root/reference/sam2consensus.py:167).  Compiled via ShapeDtypeStruct
    so nothing is materialized; XLA's static memory analysis reports
    per-device buffer sizes (VERDICT r2 #6)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sam2consensus_tpu.parallel.base import ALL
    from sam2consensus_tpu.parallel.dp import ShardedConsensus

    mesh = make_mesh(8)
    total_len = 250_000_000
    halo = 1 << 16
    rows, w = 8192, 128
    sp = PositionShardedConsensus(mesh, total_len, halo=halo)
    dp = ShardedConsensus(mesh, total_len, pileup="scatter")

    row_s = NamedSharding(mesh, P(ALL))
    mat_s = NamedSharding(mesh, P(ALL, None))
    cts = jax.ShapeDtypeStruct((sp.padded_len, 6), jnp.int32,
                               sharding=mat_s)
    sts = jax.ShapeDtypeStruct((rows,), jnp.int32, sharding=row_s)
    pk = jax.ShapeDtypeStruct((rows, w // 2), jnp.uint8, sharding=mat_s)

    sp_mem = sp._accumulate.lower(cts, sts, pk).compile().memory_analysis()
    dp_mem = dp._accumulate.lower(cts, sts, pk).compile().memory_analysis()

    block_bytes = (sp.block + halo + 1) * 6 * 4
    # sp temporaries: the [block+halo+1, 6] local tensor + slab expansion
    # + halo shift buffers — all O(block + H), nothing O(L) beyond the
    # resident counts argument itself
    slab_bytes = rows * w * 8 // 8          # expanded pos+code per device
    assert sp_mem.temp_size_in_bytes <= 2 * block_bytes + 8 * slab_bytes, (
        sp_mem.temp_size_in_bytes, block_bytes)
    # dp's transient full-length local tensor is O(L) per device — the
    # contrast that motivates sp for long genomes
    full_bytes = dp.padded_len * 6 * 4
    assert dp_mem.temp_size_in_bytes >= full_bytes
    assert sp_mem.temp_size_in_bytes * 4 < dp_mem.temp_size_in_bytes


def test_sp_rejects_tiny_blocks():
    with pytest.raises(ValueError, match="smaller than halo"):
        PositionShardedConsensus(make_mesh(8), 100, halo=1 << 16)


@pytest.mark.parametrize("shards", [2, 8])
def test_sp_backend_byte_identical(shards):
    """Full backend with --shard-mode sp == CPU oracle, byte for byte."""
    import io

    from sam2consensus_tpu.backends.cpu import CpuBackend
    from sam2consensus_tpu.backends.jax_backend import JaxBackend
    from sam2consensus_tpu.config import RunConfig
    from sam2consensus_tpu.io.fasta import render_file
    from sam2consensus_tpu.io.sam import iter_records, read_header
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate

    text = simulate(SimSpec(n_contigs=2, contig_len=64 * shards,
                            n_reads=60 * shards, read_len=16,
                            ins_read_rate=0.2, max_indel=3, seed=21))

    def run(backend, cfg):
        handle = io.StringIO(text)
        contigs, _n, first = read_header(handle)
        res = backend.run(contigs, iter_records(handle, first), cfg)
        return {n: render_file(r, 0) for n, r in res.fastas.items()}

    out_cpu = run(CpuBackend(), RunConfig(prefix="p", thresholds=[0.25, 0.75]))
    out_sp = run(JaxBackend(), RunConfig(prefix="p", thresholds=[0.25, 0.75],
                                         backend="jax", shards=shards,
                                         shard_mode="sp"))
    assert out_sp == out_cpu


def test_sp_splits_rows_wider_than_halo():
    """Width-256 rows against a small halo: exact via piece splitting."""
    rng = np.random.default_rng(17)
    total_len = 4096
    w = 256
    starts = rng.integers(0, total_len - w, 150).astype(np.int32)
    codes = rng.integers(0, 6, (150, w)).astype(np.uint8)
    codes[rng.random(codes.shape) < 0.2] = 255
    sp = PositionShardedConsensus(make_mesh(8), total_len, halo=64)
    sp.add(_batch(starts, codes))
    assert np.array_equal(sp.counts_host(),
                          _ref_counts(total_len, starts, codes))


def test_sp_sorted_input_ships_near_minimal_rows():
    """Coordinate-sorted input (the real-world common case): the window
    strategy must ship ~the real row count, not n x max_per_device
    (the round-1 ~8x transfer blowup)."""
    rng = np.random.default_rng(33)
    total_len = 1 << 20
    w = 64
    n_rows = 4096
    # coordinate-sorted: every chunk's rows land in one narrow window
    sp = PositionShardedConsensus(make_mesh(8), total_len, halo=256)
    all_s, all_c = [], []
    for chunk in range(4):
        base = chunk * 2000
        starts = (base + np.sort(rng.integers(0, 1500, n_rows))).astype(
            np.int32)
        codes = rng.integers(0, 6, (n_rows, w)).astype(np.uint8)
        sp.add(_batch(starts, codes))
        all_s.append(starts)
        all_c.append(codes)

    assert any(k.startswith("window") for k in sp.strategy_used), \
        sp.strategy_used
    assert sp.rows_shipped <= 1.5 * sp.rows_real, (
        sp.rows_shipped, sp.rows_real, sp.strategy_used)
    ref = _ref_counts(total_len, np.concatenate(all_s),
                      np.concatenate(all_c))
    assert np.array_equal(sp.counts_host(), ref)


def test_sp_scattered_input_uses_routed_path():
    """Whole-genome-scattered rows exceed the window cap relative to the
    genome and fall back to routing (which is balanced for this case)."""
    rng = np.random.default_rng(34)
    total_len = 9000
    w = 32
    starts = rng.integers(0, total_len - w, 800).astype(np.int32)
    codes = rng.integers(0, 6, (800, w)).astype(np.uint8)
    sp = PositionShardedConsensus(make_mesh(8), total_len, halo=64)
    sp.add(_batch(starts, codes))
    assert any(k.startswith("routed") for k in sp.strategy_used), \
        sp.strategy_used
    assert np.array_equal(sp.counts_host(),
                          _ref_counts(total_len, starts, codes))


def test_sp_window_spanning_block_boundaries():
    """A sorted window that straddles several device blocks folds each
    device's overlap exactly (the masked-slice path)."""
    total_len = 1 << 16
    sp = PositionShardedConsensus(make_mesh(8), total_len, halo=128)
    block = sp.block
    w = 64
    # rows packed around the 3rd/4th block boundary
    starts = np.arange(3 * block - 200, 3 * block + 200,
                       dtype=np.int32)
    codes = np.tile(np.arange(w) % 6, (len(starts), 1)).astype(np.uint8)
    sp.add(_batch(starts, codes))
    assert any(k.startswith("window") for k in sp.strategy_used)
    assert np.array_equal(sp.counts_host(),
                          _ref_counts(total_len, starts, codes))


def test_sp_odd_halo_from_odd_block_byte_exact():
    """An odd position block (total_len 967 over 8 devices -> block 121)
    makes halo = min(block, cap) odd; split_wide_rows then produces
    odd-width pieces and pack_nibbles must pad the odd column (one extra
    PAD column that self-redirects) instead of crashing on the nibble
    fold.  Regression: found driving the CLI sp mode on a jittered
    3-contig fixture."""
    total_len = 967
    rng = np.random.default_rng(5)
    sp = PositionShardedConsensus(make_mesh(8), total_len,
                                  halo=min(121, 1 << 16))
    assert sp.block == 121 and sp.halo % 2 == 1
    w = 128                       # bucket wider than the odd halo
    starts = rng.integers(0, total_len - w, 600).astype(np.int32)
    codes = rng.integers(0, 6, (600, w)).astype(np.uint8)
    sp.add(_batch(starts, codes))
    assert np.array_equal(sp.counts_host(),
                          _ref_counts(total_len, starts, codes))
