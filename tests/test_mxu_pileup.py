"""MXU pileup (one-hot matmul + overlap-add) vs the scatter oracle.

The scatter path is the semantics oracle for the MXU formulation
(ops/mxu_pileup.py); both must produce identical integer counts for any
row set, including PAD cells, tile-boundary overhangs, empty tiles, and
skewed coverage.  Runs on CPU (the formulation is platform-independent
math; the speedup is TPU-specific).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from sam2consensus_tpu.encoder.events import SegmentBatch  # noqa: E402
from sam2consensus_tpu.ops import mxu_pileup  # noqa: E402
from sam2consensus_tpu.ops.pileup import PileupAccumulator  # noqa: E402


def _ref_counts(starts, codes, padded_len):
    ref = np.zeros((padded_len, 6), np.int64)
    w = codes.shape[1]
    pos = (starts[:, None] + np.arange(w)[None, :]).ravel()
    code = codes.ravel()
    m = code < 6
    np.add.at(ref, (pos[m], code[m].astype(np.int64)), 1)
    return ref


def _random_rows(rng, n, width, span):
    starts = rng.integers(0, max(1, span - width), n).astype(np.int32)
    codes = rng.integers(0, 6, (n, width)).astype(np.uint8)
    codes[rng.random((n, width)) < 0.3] = 255   # PAD cells
    return starts, codes


@pytest.mark.parametrize("tile,n,width", [(512, 300, 64), (256, 50, 32),
                                          (1024, 1000, 128)])
def test_mxu_equals_reference(tile, n, width):
    rng = np.random.default_rng(tile + n)
    span = 4 * tile + 100             # non-multiple of tile
    padded_len = -(-span // tile) * tile
    starts, codes = _random_rows(rng, n, width, span)
    plan = mxu_pileup.plan_tiles(starts, codes, padded_len, tile,
                                 max_blowup=float("inf"))
    out = mxu_pileup.pileup_mxu(
        jnp.zeros((padded_len, 6), jnp.int32), jnp.asarray(plan.loc),
        jnp.asarray(plan.codes), tile=tile, n_tiles=plan.n_tiles,
        rows_per_tile=plan.rows_per_tile, width=plan.width)
    assert np.array_equal(np.asarray(out, dtype=np.int64),
                          _ref_counts(starts, codes, padded_len))


def test_mxu_boundary_overhangs():
    """Rows ending exactly at / crossing tile boundaries overlap-add."""
    tile = 256
    padded_len = 4 * tile
    width = 64
    starts = np.array([tile - 1, tile - width + 1, 2 * tile - 32, 0,
                       3 * tile - 1], dtype=np.int32)
    codes = np.tile(np.arange(width) % 6, (5, 1)).astype(np.uint8)
    plan = mxu_pileup.plan_tiles(starts, codes, padded_len, tile,
                                 max_blowup=float("inf"))
    out = mxu_pileup.pileup_mxu(
        jnp.zeros((padded_len, 6), jnp.int32), jnp.asarray(plan.loc),
        jnp.asarray(plan.codes), tile=tile, n_tiles=plan.n_tiles,
        rows_per_tile=plan.rows_per_tile, width=plan.width)
    assert np.array_equal(np.asarray(out, dtype=np.int64),
                          _ref_counts(starts, codes, padded_len))


def test_mxu_accumulates_across_calls():
    tile = 256
    padded_len = 2 * tile
    rng = np.random.default_rng(7)
    starts, codes = _random_rows(rng, 40, 32, padded_len - 32)
    plan = mxu_pileup.plan_tiles(starts, codes, padded_len, tile,
                                 max_blowup=float("inf"))
    args = (jnp.asarray(plan.loc), jnp.asarray(plan.codes))
    kw = dict(tile=tile, n_tiles=plan.n_tiles,
              rows_per_tile=plan.rows_per_tile, width=plan.width)
    out = mxu_pileup.pileup_mxu(jnp.zeros((padded_len, 6), jnp.int32),
                                *args, **kw)
    out = mxu_pileup.pileup_mxu(out, *args, **kw)
    assert np.array_equal(np.asarray(out, dtype=np.int64),
                          2 * _ref_counts(starts, codes, padded_len))


def test_accumulator_strategies_agree():
    """End to end: auto/mxu/scatter accumulators produce identical counts."""
    rng = np.random.default_rng(11)
    total_len = 3000
    width = 64
    starts, codes = _random_rows(rng, 500, width, total_len - width)
    batch = SegmentBatch(buckets={width: (starts, codes)},
                         n_reads=500, n_events=int((codes < 6).sum()))
    outs = {}
    for strategy in ("mxu", "scatter"):
        acc = PileupAccumulator(total_len, strategy=strategy)
        acc.add(batch)
        outs[strategy] = acc.counts_host()
        assert any(k.startswith(strategy) for k in acc.strategy_used), \
            acc.strategy_used
    assert np.array_equal(outs["mxu"], outs["scatter"])


def test_skew_falls_back_to_scatter():
    """Every read on one tile: mxu must not pay the padding blowup."""
    total_len = 64 * mxu_pileup.TILE_POSITIONS
    width = 32
    n = 2000
    starts = np.zeros(n, dtype=np.int32)      # all on tile 0
    codes = np.full((n, width), 2, dtype=np.uint8)
    batch = SegmentBatch(buckets={width: (starts, codes)},
                         n_reads=n, n_events=n * width)
    acc = PileupAccumulator(total_len, strategy="mxu")
    acc.add(batch)
    assert any(k.startswith("scatter") for k in acc.strategy_used), \
        acc.strategy_used
    counts = acc.counts_host()
    assert counts[:width, 2].tolist() == [n] * width


def test_mxu_chunked_tile_axis():
    """n_tiles > TILE_CHUNK exercises the lax.map chunked path."""
    rng = np.random.default_rng(3)
    tile = 256
    padded_len = (mxu_pileup.TILE_CHUNK + 9) * tile
    width = 32
    starts = rng.integers(0, padded_len - width, 2000).astype(np.int32)
    codes = rng.integers(0, 6, (2000, width)).astype(np.uint8)
    plan = mxu_pileup.plan_tiles(starts, codes, padded_len, tile,
                                 max_blowup=float("inf"))
    assert plan.n_tiles > mxu_pileup.TILE_CHUNK
    out = mxu_pileup.pileup_mxu(
        jnp.zeros((padded_len, 6), jnp.int32), jnp.asarray(plan.loc),
        jnp.asarray(plan.codes), tile=tile, n_tiles=plan.n_tiles,
        rows_per_tile=plan.rows_per_tile, width=plan.width)
    assert np.array_equal(np.asarray(out, dtype=np.int64),
                          _ref_counts(starts, codes, padded_len))


@pytest.mark.parametrize("tile,n,width", [(512, 300, 64), (256, 50, 32),
                                          (1024, 1000, 128)])
def test_compact_layout_equals_padded(tile, n, width):
    """pileup_mxu_compact (device-built padding) == pileup_mxu
    (host-padded transfer) == numpy reference."""
    rng = np.random.default_rng(tile * 7 + n)
    span = 4 * tile + 100
    padded_len = -(-span // tile) * tile
    starts, codes = _random_rows(rng, n, width, span)
    sp = mxu_pileup.plan_slots(starts, width, padded_len, tile,
                               max_blowup=float("inf"))
    out = mxu_pileup.pileup_mxu_compact(
        jnp.zeros((padded_len, 6), jnp.int32), jnp.asarray(starts),
        jnp.asarray(codes), jnp.asarray(sp.slot), tile=tile,
        n_tiles=sp.n_tiles, rows_per_tile=sp.rows_per_tile, width=width)
    assert np.array_equal(np.asarray(out, dtype=np.int64),
                          _ref_counts(starts, codes, padded_len))


def test_plan_slots_matches_plan_tiles_layout():
    """Scattering compact rows by plan_slots' slot reproduces plan_tiles'
    padded arrays exactly (the two layouts are the same plan)."""
    rng = np.random.default_rng(99)
    tile = 256
    padded_len = 6 * tile
    width = 32
    starts, codes = _random_rows(rng, 200, width, padded_len - width)
    tp = mxu_pileup.plan_tiles(starts, codes, padded_len, tile,
                               max_blowup=float("inf"))
    sp = mxu_pileup.plan_slots(starts, width, padded_len, tile,
                               max_blowup=float("inf"))
    assert (sp.n_tiles, sp.rows_per_tile) == (tp.n_tiles, tp.rows_per_tile)
    loc = np.zeros(sp.n_tiles * sp.rows_per_tile, np.int32)
    cod = np.full((sp.n_tiles * sp.rows_per_tile, width), 255, np.uint8)
    tile_of = sp.slot // sp.rows_per_tile
    loc[sp.slot] = starts - tile_of * tile
    cod[sp.slot] = codes
    assert np.array_equal(loc, tp.loc)
    assert np.array_equal(cod.reshape(-1), tp.codes)


def test_auto_strategy_autotunes_and_stays_exact():
    """'auto' times scatter and mxu on early steady-state slabs, locks in
    the measured winner, and every slab (trial or not) accumulates
    exactly."""
    rng = np.random.default_rng(55)
    total_len = 16000
    width = 32
    rows = 1 << 15                 # x width 32 = 1M cells: enters the trial
    acc = PileupAccumulator(total_len, strategy="auto")
    ref = np.zeros((acc.padded_len, 6), np.int64)
    for i in range(6):
        starts = rng.integers(0, total_len - width, rows).astype(np.int32)
        codes = rng.integers(0, 6, (rows, width)).astype(np.uint8)
        acc.add(SegmentBatch(buckets={width: (starts, codes)},
                             n_reads=rows, n_events=rows * width))
        ref += _ref_counts(starts, codes, acc.padded_len)
    tune = acc.strategy_used.get("autotune")
    assert tune is not None and tune["winner"] in ("scatter", "mxu"), \
        acc.strategy_used
    assert tune["scatter_sec_per_mcell"] > 0
    assert tune["mxu_sec_per_mcell"] > 0
    assert np.array_equal(acc.counts_host().astype(np.int64),
                          ref[:total_len])


def test_auto_strategy_small_slabs_skip_trials():
    """Tiny slabs never enter the trial: no autotune stats, scatter only."""
    rng = np.random.default_rng(56)
    total_len = 3000
    acc = PileupAccumulator(total_len, strategy="auto")
    for _ in range(6):
        starts = rng.integers(0, total_len - 32, 100).astype(np.int32)
        codes = rng.integers(0, 6, (100, 32)).astype(np.uint8)
        acc.add(SegmentBatch(buckets={32: (starts, codes)},
                             n_reads=100, n_events=3200))
    assert "autotune" not in acc.strategy_used
    assert all(k.startswith("scatter") for k in acc.strategy_used)


def test_auto_strategy_reswarms_on_shape_change():
    """A timing-stage slab whose shape differs from the warm slab re-warms
    instead of timing (jit compilation must never pollute the trial)."""
    rng = np.random.default_rng(57)
    total_len = 16000
    acc = PileupAccumulator(total_len, strategy="auto")
    ref = np.zeros((acc.padded_len, 6), np.int64)
    shapes = [(1 << 15, 32), (1 << 14, 64), (1 << 15, 32), (1 << 15, 32),
              (1 << 15, 32), (1 << 15, 32), (1 << 15, 32), (1 << 15, 32)]
    for rows, width in shapes:
        starts = rng.integers(0, total_len - width, rows).astype(np.int32)
        codes = rng.integers(0, 6, (rows, width)).astype(np.uint8)
        acc.add(SegmentBatch(buckets={width: (starts, codes)},
                             n_reads=rows, n_events=rows * width))
        ref += _ref_counts(starts, codes, acc.padded_len)
    assert acc.strategy_used.get("autotune", {}).get("winner") \
        in ("scatter", "mxu")
    assert np.array_equal(acc.counts_host().astype(np.int64),
                          ref[:total_len])


def test_auto_strategy_persistent_skew_locks_scatter():
    """Trial slabs that always skew (all rows on one tile of a large
    genome) stop retrying after the cap and lock in scatter."""
    total_len = 64 * mxu_pileup.TILE_POSITIONS
    width = 32
    rows = 1 << 15
    acc = PileupAccumulator(total_len, strategy="auto")
    for _ in range(8):
        starts = np.zeros(rows, dtype=np.int32)       # all on tile 0
        codes = np.full((rows, width), 3, dtype=np.uint8)
        acc.add(SegmentBatch(buckets={width: (starts, codes)},
                             n_reads=rows, n_events=rows * width))
    tune = acc.strategy_used.get("autotune")
    assert tune is not None and tune["winner"] == "scatter" \
        and tune.get("reason") == "mxu_skew", acc.strategy_used


def test_pack_nibbles_roundtrip():
    """4-bit wire pack/unpack: codes 0..5 survive, PAD (255) -> 15, both
    invalid after unpack exactly where they were before."""
    from sam2consensus_tpu.ops.pileup import pack_nibbles, unpack_nibbles

    rng = np.random.default_rng(60)
    codes = rng.integers(0, 6, (37, 64)).astype(np.uint8)
    codes[rng.random(codes.shape) < 0.3] = 255
    packed = pack_nibbles(codes)
    assert packed.shape == (37, 32)
    back = np.asarray(unpack_nibbles(jnp.asarray(packed)))
    want = np.where(codes < 6, codes, 15)
    np.testing.assert_array_equal(back, want)
    # validity semantics identical: invalid iff >= NUM_SYMBOLS
    np.testing.assert_array_equal(back < 6, codes < 6)


def test_mxu_packed_equals_compact():
    """The 4-bit-wire MXU entry point == the uint8 compact entry point."""
    from sam2consensus_tpu.ops.pileup import pack_nibbles

    rng = np.random.default_rng(61)
    tile, n, width = 512, 400, 64
    span = 4 * tile
    padded_len = 4 * tile
    starts, codes = _random_rows(rng, n, width, span - width)
    plan = mxu_pileup.plan_slots(starts, width, padded_len, tile,
                                 max_blowup=float("inf"))
    args = dict(tile=tile, n_tiles=plan.n_tiles,
                rows_per_tile=plan.rows_per_tile, width=width)
    a = mxu_pileup.pileup_mxu_compact(
        jnp.zeros((padded_len, 6), jnp.int32), jnp.asarray(starts),
        jnp.asarray(codes), jnp.asarray(plan.slot), **args)
    b = mxu_pileup.pileup_mxu_packed(
        jnp.zeros((padded_len, 6), jnp.int32), jnp.asarray(starts),
        jnp.asarray(pack_nibbles(codes)), jnp.asarray(plan.slot), **args)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_round_rows_grid_contract():
    """Pin the shared row-capacity grid (ops.pileup.round_rows_grid):
    result >= max(8, m), overshoot <= 12.5%, idempotent (always ON the
    grid, so jit caches stay O(8 log))."""
    from sam2consensus_tpu.ops.pileup import round_rows_grid

    probes = list(range(1, 1026)) + [
        (1 << k) + d for k in range(10, 25) for d in (-1, 0, 1, 137)]
    for m in probes:
        g = round_rows_grid(m)
        base = max(8, m)
        assert g >= base, (m, g)
        assert g <= base * 1.125, (m, g)
        assert round_rows_grid(g) == g, (m, g)
