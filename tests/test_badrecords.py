"""Tolerant decode (``--on-bad-record``): hostile-input hardening.

Five layers of assurance:

* policy units — budget grammar, reason taxonomy, sink partition
  merge/rollback, sidecar write + truncation, config validation;
* the tentpole guarantee, rung-invariant tolerant semantics — the
  committed fixture families with injected malformed records decode to
  the PINNED ``.expected.fasta`` bytes on every rung (serial native /
  byte-shard / streaming gzip / BAM native / BAM python / pure-python /
  cpu oracle), with identical quarantine verdicts and — among the
  raw-line native rungs — identical sidecar record sequences;
* error budgets — the N-1/N absolute boundary, the percent boundary,
  and the blown budget leaving its sidecar evidence behind;
* DATA-class resilience — a poison-input failure is never retried,
  never demotes the pileup ladder, and is distinguishable from
  infrastructure trouble (``resilience/policy.py``);
* serve isolation — a poison job injected mid-queue fails fast with
  its quarantine summary while the next job runs warm on the device
  rung: no retry storm, no tenant demotion, ``serve/admission_poison``
  counted, health snapshot carrying the verdict.
"""

import json
import os

import pytest

from sam2consensus_tpu import native
from sam2consensus_tpu.backends.cpu import CpuBackend
from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.formats import open_alignment_input
from sam2consensus_tpu.formats.bam import sam_text_to_bam
from sam2consensus_tpu.ingest.badrecords import (BadRecordBudgetExceeded,
                                                 BadRecordPolicy,
                                                 QuarantineSink,
                                                 classify_reason,
                                                 is_data_error,
                                                 parse_budget,
                                                 policy_from_config)
from sam2consensus_tpu.io.fasta import render_file

DATA = os.path.join(os.path.dirname(__file__), "data")
FAMILIES = ("formats_short", "formats_longread", "formats_adversarial")

HAVE_NATIVE = native.load() is not None


# ---------------------------------------------------------------------------
# dirty-fixture construction
# ---------------------------------------------------------------------------
def _refs(text):
    out = []
    for ln in text.splitlines():
        if ln.startswith("@SQ"):
            name = length = None
            for f in ln.split("\t"):
                if f.startswith("SN:"):
                    name = f[3:].strip()
                elif f.startswith("LN:"):
                    length = int(f[3:])
            out.append((name, length or 0))
    return out


def _dirt_lines(refs, bam_safe=False):
    """(line, reason) malformations covering the taxonomy.  With
    ``bam_safe`` only dirt that survives SAM->BAM conversion (the
    container parses on write, so text-parse garbage can't ride along —
    semantically-bad records can)."""
    name, ln = refs[0]
    oob = [
        (f"oobA\t0\t{name}\t{ln * 2 + 7}\t60\t8M\t*\t0\t0\t"
         "ACGTACGT\t*\n", "out_of_bounds_pos"),
        (f"oobB\t0\t{name}\t{ln + 1}\t60\t4M\t*\t0\t0\tACGT\t*\n",
         "out_of_bounds_pos"),
    ]
    if bam_safe:
        return oob
    return oob + [
        ("junk\tline\n", "bad_field_count"),
        (f"badpos\t0\t{name}\txx\t60\t4M\t*\t0\t0\tACGT\t*\n",
         "bad_pos"),
        (f"noref\t0\tNOSUCHREF\t5\t60\t4M\t*\t0\t0\tACGT\t*\n",
         "unknown_reference"),
        (f"badalpha\t0\t{name}\t1\t60\t4M\t*\t0\t0\tAC!T\t*\n",
         "bad_alphabet"),
    ]


def make_dirty(text, bam_safe=False):
    """Inject the taxonomy dirt at deterministic positions spread
    through the body; returns (dirty_text, [(line, reason), ...] in
    stream order)."""
    lines = text.splitlines(keepends=True)
    body = [i for i, ln in enumerate(lines) if not ln.startswith("@")]
    dirt = _dirt_lines(_refs(text), bam_safe=bam_safe)
    # insertion points spread over the body, inserted back-to-front so
    # earlier indices stay valid
    spots = [body[(k * len(body)) // len(dirt)] for k in range(len(dirt))]
    order = sorted(zip(spots, dirt), key=lambda t: t[0])
    for spot, (ln, _why) in reversed(order):
        lines.insert(spot, ln)
    return "".join(lines), [(ln.rstrip("\n"), why)
                            for _s, (ln, why) in order]


def _render_all(fastas, contigs):
    return "".join(render_file(fastas[c.name], 0)
                   for c in contigs if c.name in fastas)


def run_backend(path, backend=None, fmt="auto", **cfg_kw):
    be = backend or CpuBackend()
    ai = open_alignment_input(path, fmt, binary=(be.name == "jax"))
    cfg = RunConfig(prefix="fixture", **cfg_kw)
    try:
        res = be.run(ai.contigs, ai.stream, cfg)
    finally:
        ai.close()
    return _render_all(res.fastas, ai.contigs), res


def _jax():
    from sam2consensus_tpu.backends.jax_backend import JaxBackend

    return JaxBackend()


def _sidecar_entries(path):
    assert os.path.exists(path), f"sidecar missing: {path}"
    head, *rows = [json.loads(ln) for ln in open(path)]
    assert head == {"schema": "s2c-quarantine/1"}
    summary = rows[-1]["summary"]
    return [ (e["record"], e["reason"]) for e in rows[:-1] ], summary


def _expected(family):
    with open(os.path.join(DATA, f"{family}.expected.fasta")) as fh:
        return fh.read()


# ---------------------------------------------------------------------------
# policy units
# ---------------------------------------------------------------------------
class TestPolicyUnits:
    def test_parse_budget_grammar(self):
        assert parse_budget("") == (None, None)
        assert parse_budget("  ") == (None, None)
        assert parse_budget("7") == (7, None)
        assert parse_budget("0") == (0, None)
        assert parse_budget("2.5%") == (None, pytest.approx(0.025))
        assert parse_budget("100%") == (None, pytest.approx(1.0))
        for bad in ("-1", "101%", "-3%", "x", "5%%"):
            with pytest.raises(ValueError):
                parse_budget(bad)

    def test_policy_from_config_validation(self):
        with pytest.raises(ValueError, match="on_bad_record"):
            policy_from_config(RunConfig(on_bad_record="explode"))
        with pytest.raises(ValueError, match="tolerant mode"):
            policy_from_config(RunConfig(max_bad_records="3"))
        pol = policy_from_config(RunConfig(on_bad_record="quarantine",
                                           prefix="p", outfolder="/tmp/o"))
        assert pol.sidecar_path == "/tmp/o/p_quarantine.jsonl"
        assert policy_from_config(RunConfig()).tolerant is False

    def test_classify_reason_taxonomy(self):
        cases = [
            (IndexError("list index out of range"), "bad_field_count"),
            (ValueError("invalid literal for int() with base 10: 'xx'"),
             "bad_pos"),
            (KeyError("read mapped to unknown reference 'Z'"),
             "unknown_reference"),
            (ValueError("record refID 9 outside the reference table"),
             "unknown_reference"),
            (IndexError("read at pos 3 spans [3, 99) outside reference"),
             "out_of_bounds_pos"),
            (KeyError("read at pos 0 contains an out-of-alphabet base"),
             "bad_alphabet"),
            (ValueError("BAM record at offset 8: fields overrun the "
                        "record"), "bad_bam_record"),
            (ValueError("CIGAR op code 12 outside MIDNSHP=X"),
             "bad_cigar"),
            (RuntimeError("boom"), "malformed"),
        ]
        for exc, want in cases:
            assert classify_reason(exc) == want, exc
        try:
            "\xff".encode("ascii")
        except UnicodeEncodeError:
            pass
        assert classify_reason(UnicodeDecodeError(
            "ascii", b"\xff", 0, 1, "ordinal not in range(128)")) \
            == "non_ascii"

    def test_sink_partition_merge_and_rollback(self):
        sink = QuarantineSink(BadRecordPolicy(mode="quarantine"))
        sink.record("s2-a\tx", IndexError("i"), partition=(2,))
        sink.record("s0-a\tx", IndexError("i"), partition=(0,))
        sink.record("s2-b\tx", IndexError("i"), partition=(2,))
        sink.record("s1-a\tx", IndexError("i"), partition=(1,))
        # deterministic merge: partitions in sorted (stream) order,
        # decode order within each
        assert [e["record"] for e in sink.entries()] == \
            ["s0-a\tx", "s1-a\tx", "s2-a\tx", "s2-b\tx"]
        sink.clear_partition((2,))          # shard retry rolls back whole
        assert sink.count == 2
        sink.reset()                        # ingest demotion starts over
        assert sink.count == 0 and sink.entries() == []

    def test_sink_absolute_budget_raises(self):
        sink = QuarantineSink(BadRecordPolicy(mode="skip", max_bad=2))
        sink.record("a", IndexError("i"))
        with pytest.raises(BadRecordBudgetExceeded) as ei:
            sink.record("b", IndexError("i"))
        assert is_data_error(ei.value)
        assert ei.value.budget_exhausted
        assert ei.value.summary["bad_records"] == 2

    def test_sink_percent_budget_at_finish(self):
        sink = QuarantineSink(BadRecordPolicy(mode="skip", max_pct=0.10))
        sink.record("a", IndexError("i"))
        assert sink.finish(100)["bad_records"] == 1    # 1% <= 10%
        with pytest.raises(BadRecordBudgetExceeded):
            sink.finish(5)                             # 20% > 10%

    def test_sidecar_write_and_truncation(self, tmp_path):
        out = str(tmp_path / "q.jsonl")
        sink = QuarantineSink(BadRecordPolicy(
            mode="quarantine", sidecar_path=out, sidecar_max=2))
        for k in range(5):
            sink.record(f"bad{k}\tline", IndexError("i"), offset=10 * k)
        summary = sink.finish(50)
        entries, side_summary = _sidecar_entries(out)
        assert entries == [("bad0\tline", "bad_field_count"),
                           ("bad1\tline", "bad_field_count")]
        assert summary["truncated"] and side_summary["truncated"]
        assert summary["bad_records"] == 5
        assert summary["sidecar"] == os.path.abspath(out)


# ---------------------------------------------------------------------------
# the tentpole: rung-invariant tolerant semantics on the fixture matrix
# ---------------------------------------------------------------------------
@pytest.mark.skipif(not HAVE_NATIVE, reason="native decoder unavailable")
class TestRungInvariance:
    """Every cell must produce the PINNED clean-oracle bytes — skipping
    record k is byte-equivalent to deleting record k from the input —
    with identical quarantine verdicts across rungs."""

    def _dirty_paths(self, family, tmp_path):
        import gzip as _gzip

        text = open(os.path.join(DATA, f"{family}.sam")).read()
        dirty, entries = make_dirty(text)
        sam = str(tmp_path / f"{family}.dirty.sam")
        with open(sam, "w") as fh:
            fh.write(dirty)
        gz = str(tmp_path / f"{family}.dirty.sam.gz")
        with _gzip.open(gz, "wb") as fh:
            fh.write(dirty.encode("ascii"))
        return sam, gz, entries

    @pytest.mark.parametrize("family", FAMILIES)
    def test_text_rung_matrix_quarantine(self, family, tmp_path):
        sam, gz, entries = self._dirty_paths(family, tmp_path)
        expected = _expected(family)
        cells = [
            ("serial", sam, dict(decode_threads=1)),
            ("shard", sam, dict(decode_threads=3)),
            ("stream", gz, dict(decode_threads=2)),
        ]
        sidecars = {}
        for rung, path, extra in cells:
            side = str(tmp_path / f"{family}.{rung}.q.jsonl")
            out, res = run_backend(
                path, backend=_jax(), on_bad_record="quarantine",
                quarantine_out=side, shards=1, **extra)
            assert out == expected, f"{family}/{rung} consensus differs"
            assert res.stats.extra["bad_records"] == len(entries)
            sidecars[rung], summary = _sidecar_entries(side)
            assert summary["bad_records"] == len(entries)
        # raw-line native rungs: identical record SEQUENCES (the
        # deterministic partition merge), equal to the injected dirt
        assert sidecars["serial"] == entries
        assert sidecars["shard"] == sidecars["serial"]
        assert sidecars["stream"] == sidecars["serial"]

    @pytest.mark.parametrize("family", FAMILIES)
    def test_bam_rung_matrix_quarantine(self, family, tmp_path):
        text = open(os.path.join(DATA, f"{family}.sam")).read()
        dirty, entries = make_dirty(text, bam_safe=True)
        bam = str(tmp_path / f"{family}.dirty.bam")
        sam_text_to_bam(dirty, bam)
        expected = _expected(family)
        verdicts = {}
        for decoder in ("native", "py"):
            side = str(tmp_path / f"{family}.bam.{decoder}.q.jsonl")
            out, res = run_backend(
                bam, backend=_jax(), fmt="bam",
                on_bad_record="quarantine", quarantine_out=side,
                decoder=decoder, shards=1)
            assert out == expected, \
                f"{family}/bam-{decoder} consensus differs"
            assert res.stats.extra["bad_records"] == len(entries)
            got, summary = _sidecar_entries(side)
            verdicts[decoder] = sorted(why for _r, why in got)
            assert summary["bad_records"] == len(entries)
        assert verdicts["native"] == verdicts["py"] \
            == sorted(why for _l, why in entries)

    def test_py_rung_and_cpu_oracle(self, tmp_path):
        sam, _gz, entries = self._dirty_paths("formats_short", tmp_path)
        expected = _expected("formats_short")
        for tag, be, extra in (("py", _jax(), dict(decoder="py")),
                               ("cpu", CpuBackend(), {})):
            side = str(tmp_path / f"{tag}.q.jsonl")
            out, res = run_backend(sam, backend=be,
                                   on_bad_record="quarantine",
                                   quarantine_out=side, **extra)
            assert out == expected, f"{tag} consensus differs"
            assert res.stats.extra["bad_records"] == len(entries)
            got, _summary = _sidecar_entries(side)
            # parsed-record lanes store rendered records: reasons must
            # still match the injected taxonomy exactly
            assert sorted(why for _r, why in got) \
                == sorted(why for _l, why in entries)

    def test_skip_mode_counts_without_sidecar(self, tmp_path):
        sam, gz, entries = self._dirty_paths("formats_short", tmp_path)
        expected = _expected("formats_short")
        for path, extra in ((sam, dict(decode_threads=1)),
                            (sam, dict(decode_threads=3)),
                            (gz, dict(decode_threads=2))):
            out, res = run_backend(path, backend=_jax(),
                                   on_bad_record="skip", shards=1,
                                   **extra)
            assert out == expected
            assert res.stats.extra["bad_records"] == len(entries)
            assert "quarantine_sidecar" not in res.stats.extra
        assert not list(tmp_path.glob("*_quarantine.jsonl"))

    def test_bam_structural_overrun_never_walked(self, tmp_path):
        """A record whose fields overrun its block_size (corrupt
        n_cigar_op) is flagged at INDEX time — every python lane must
        absorb the index exception instead of walking the entry, which
        would read the NEXT record's bytes as CIGAR/SEQ and misclassify
        (or miscount).  Fuzzer-found: the py twin walked index-flagged
        entries and reported ``bad_cigar`` from the neighbour's bytes
        where the native lane said ``bad_bam_record``."""
        import io
        import struct

        from sam2consensus_tpu.formats.bam import (bam_payload,
                                                   read_bam_header,
                                                   sam_text_to_records)
        from sam2consensus_tpu.formats.bgzf import (BGZF_EOF,
                                                    compress_block)

        body = [f"r{k}\t0\tc1\t{1 + 8 * k}\t60\t8M\t*\t0\t0\t"
                "ACGTACGT\t*\n" for k in range(4)]
        text = "@SQ\tSN:c1\tLN:60\n" + "".join(body)
        payload = bytearray(bam_payload(*sam_text_to_records(text)))
        fh = io.BytesIO(bytes(payload))
        read_bam_header(fh)
        rec_offs, p = [], fh.tell()
        while p < len(payload):
            rec_offs.append(p)
            p += 4 + struct.unpack_from("<i", payload, p)[0]
        # record 2: n_cigar_op (u16 at record-relative offset 16) -> 999
        struct.pack_into("<H", payload, rec_offs[2] + 16, 999)
        bam = str(tmp_path / "overrun.bam")
        with open(bam, "wb") as out:
            out.write(compress_block(bytes(payload)) + BGZF_EOF)

        clean = str(tmp_path / "minus_r2.sam")
        with open(clean, "w") as out:
            out.write("@SQ\tSN:c1\tLN:60\n"
                      + "".join(ln for k, ln in enumerate(body)
                                if k != 2))
        expected, _res = run_backend(clean)

        # tolerant: native lane, py twin, and the cpu records() lane all
        # quarantine exactly the flagged record with the INDEX error
        sides = {}
        for tag, be, extra in (("native", _jax(), dict(decoder="native")),
                               ("py", _jax(), dict(decoder="py")),
                               ("cpu", CpuBackend(), {})):
            side = str(tmp_path / f"{tag}.q.jsonl")
            out_txt, res = run_backend(bam, backend=be, fmt="bam",
                                       on_bad_record="quarantine",
                                       quarantine_out=side, **extra)
            assert out_txt == expected, f"{tag} consensus differs"
            assert res.stats.extra["bad_records"] == 1
            with open(side) as fh2:
                rows = [json.loads(ln) for ln in fh2]
            sides[tag] = [(e["reason"], e["error"], e["offset"])
                          for e in rows if "reason" in e]
        want_off = rec_offs[2] - rec_offs[0]
        for tag, got in sides.items():
            assert got[0][0] == "bad_bam_record", (tag, got)
            assert "fields overrun" in got[0][1], (tag, got)
            assert got[0][2] == want_off, (tag, got)
        assert sides["native"] == sides["py"] == sides["cpu"]

        # strict AND legacy permissive (no sink either way): both
        # binary decode lanes die on the index error with the identical
        # type + message — permissive mode tolerates encode-level
        # contract errors only, never structural parse damage
        for strict in (True, False):
            errs = {}
            for decoder in ("native", "py"):
                with pytest.raises(ValueError) as ei:
                    run_backend(bam, backend=_jax(), fmt="bam",
                                decoder=decoder, strict=strict)
                errs[decoder] = (type(ei.value).__name__, str(ei.value))
            assert errs["native"] == errs["py"], strict
            assert "fields overrun" in errs["native"][1]

    def test_strict_default_error_parity(self, tmp_path):
        """--on-bad-record fail (the default): the FIRST bad record
        kills the job with the same typed error + absolute file offset
        on every text rung."""
        sam, gz, entries = self._dirty_paths("formats_short", tmp_path)
        first_bad = entries[0][0]
        want_off = open(sam).read().index(first_bad)
        errs = {}
        for rung, path, extra in (("serial", sam, dict(decode_threads=1)),
                                  ("shard", sam, dict(decode_threads=3)),
                                  ("stream", gz, dict(decode_threads=2))):
            with pytest.raises((ValueError, KeyError, IndexError)) as ei:
                run_backend(path, backend=_jax(), shards=1, **extra)
            errs[rung] = (type(ei.value).__name__, str(ei.value),
                          getattr(ei.value, "s2c_offset", None))
        assert errs["serial"][2] == want_off
        assert errs["shard"] == errs["serial"]
        assert errs["stream"] == errs["serial"]


# ---------------------------------------------------------------------------
# error budgets
# ---------------------------------------------------------------------------
@pytest.mark.skipif(not HAVE_NATIVE, reason="native decoder unavailable")
class TestErrorBudget:
    def _dirty(self, tmp_path):
        text = open(os.path.join(DATA, "formats_short.sam")).read()
        dirty, entries = make_dirty(text)
        path = str(tmp_path / "dirty.sam")
        with open(path, "w") as fh:
            fh.write(dirty)
        return path, entries

    def test_absolute_boundary(self, tmp_path):
        path, entries = self._dirty(tmp_path)
        n = len(entries)
        # budget n+1: all n bad records fit — the job completes
        out, res = run_backend(path, backend=_jax(),
                               on_bad_record="skip",
                               max_bad_records=str(n + 1),
                               decode_threads=1, shards=1)
        assert out == _expected("formats_short")
        assert res.stats.extra["bad_records"] == n
        # budget n: the nth bad record fails the job as a unit
        with pytest.raises(BadRecordBudgetExceeded) as ei:
            run_backend(path, backend=_jax(), on_bad_record="skip",
                        max_bad_records=str(n), decode_threads=1,
                        shards=1)
        assert ei.value.summary["bad_records"] >= n
        assert is_data_error(ei.value)

    def test_percent_boundary(self, tmp_path):
        path, entries = self._dirty(tmp_path)
        out, _res = run_backend(path, backend=_jax(),
                                on_bad_record="skip",
                                max_bad_records="50%",
                                decode_threads=2, shards=1)
        assert out == _expected("formats_short")
        with pytest.raises(BadRecordBudgetExceeded) as ei:
            run_backend(path, backend=_jax(), on_bad_record="skip",
                        max_bad_records="0.1%", decode_threads=2,
                        shards=1)
        assert "%" in str(ei.value)

    def test_blown_budget_leaves_sidecar_evidence(self, tmp_path):
        path, _entries = self._dirty(tmp_path)
        side = str(tmp_path / "evidence.jsonl")
        with pytest.raises(BadRecordBudgetExceeded) as ei:
            run_backend(path, backend=_jax(),
                        on_bad_record="quarantine", quarantine_out=side,
                        max_bad_records="2", decode_threads=1, shards=1)
        got, summary = _sidecar_entries(side)
        assert summary["bad_records"] >= 2 and len(got) >= 1
        assert ei.value.summary.get("sidecar") == os.path.abspath(side)


# ---------------------------------------------------------------------------
# DATA resilience class: poison input never retries, never demotes
# ---------------------------------------------------------------------------
class TestDataClass:
    def test_classify(self):
        from sam2consensus_tpu.resilience.policy import (DATA, TRANSIENT,
                                                         classify)

        assert classify(BadRecordBudgetExceeded("rotten")) == DATA
        # the marker protocol, not the type: any data_error-marked
        # exception classifies DATA even when its message says
        # "exhausted" (the capacity heuristics' vocabulary)
        exc = RuntimeError("resource exhausted while decoding")
        exc.data_error = True
        assert classify(exc) == DATA
        assert classify(TimeoutError("deadline")) == TRANSIENT

    def test_retry_policy_never_retries_data(self):
        from sam2consensus_tpu.resilience.policy import RetryPolicy

        calls = []

        def poison():
            calls.append(1)
            raise BadRecordBudgetExceeded("rotten input")

        pol = RetryPolicy(retries=5, backoff=0.001, on_error="fallback")
        with pytest.raises(BadRecordBudgetExceeded):
            pol.run(poison)
        assert len(calls) == 1          # zero retries

    def test_dispatcher_never_demotes_data(self):
        """A DATA-class error through ResilientDispatcher raises
        unchanged: no pileup-ladder demotion, no split, no retry — even
        under ``--on-device-error fallback`` with retries available."""
        import numpy as np

        from sam2consensus_tpu.encoder.events import SegmentBatch
        from sam2consensus_tpu.ops.pileup import PileupAccumulator
        from sam2consensus_tpu.resilience.ladder import ResilientDispatcher
        from sam2consensus_tpu.resilience.policy import RetryPolicy

        total_len = 1 << 12
        rng = np.random.default_rng(9)
        starts = rng.integers(0, total_len - 64, 32).astype(np.int32)
        codes = rng.integers(1, 6, (32, 64)).astype(np.uint8)
        batch = SegmentBatch(buckets={64: (starts, codes)})
        acc = PileupAccumulator(total_len, strategy="scatter")
        calls = []
        orig_add = acc.add

        def poison_add(unit):
            calls.append(1)
            raise BadRecordBudgetExceeded("rotten")

        acc.add = poison_add
        disp = ResilientDispatcher(
            RetryPolicy(retries=3, backoff=0.001, on_error="fallback"),
            total_len)
        with pytest.raises(BadRecordBudgetExceeded):
            disp.add(acc, batch)
        acc.add = orig_add
        assert len(calls) == 1          # zero retries, zero splits
        assert disp.demotions == 0      # no ladder step taken
        assert disp._acc is acc         # same accumulator, same rung


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------
@pytest.mark.skipif(not HAVE_NATIVE, reason="native decoder unavailable")
class TestCli:
    def _dirty(self, tmp_path):
        text = open(os.path.join(DATA, "formats_short.sam")).read()
        dirty, entries = make_dirty(text)
        path = str(tmp_path / "dirty.sam")
        with open(path, "w") as fh:
            fh.write(dirty)
        return path, entries

    def test_budget_requires_tolerant_mode(self, tmp_path):
        from sam2consensus_tpu.cli import main

        path, _ = self._dirty(tmp_path)
        with pytest.raises(SystemExit, match="tolerant"):
            main(["-i", path, "--max-bad-records", "5",
                  "-o", str(tmp_path / "out")])
        with pytest.raises(SystemExit):
            main(["-i", path, "--on-bad-record", "skip",
                  "--max-bad-records", "nonsense",
                  "-o", str(tmp_path / "out")])

    def test_quarantine_out_requires_quarantine_mode(self, tmp_path):
        # an explicit sidecar path must never be silently ignored
        from sam2consensus_tpu.cli import main

        path, _ = self._dirty(tmp_path)
        for mode_args in ([], ["--on-bad-record", "skip"]):
            with pytest.raises(SystemExit, match="quarantine-out"):
                main(["-i", path, "-o", str(tmp_path / "out"),
                      "--quarantine-out", str(tmp_path / "q.jsonl"),
                      *mode_args])

    def test_quarantine_end_to_end(self, tmp_path, capsys):
        from sam2consensus_tpu.cli import main

        path, entries = self._dirty(tmp_path)
        out = str(tmp_path / "out")
        rc = main(["-i", path, "-o", out, "-p", "cliq",
                   "--backend", "cpu", "--on-bad-record", "quarantine"])
        assert rc in (0, None)
        side = os.path.join(out, "cliq_quarantine.jsonl")
        got, summary = _sidecar_entries(side)
        assert summary["bad_records"] == len(entries)
        text = capsys.readouterr().out
        assert "malformed record(s) quarantined" in text

    def test_blown_budget_is_clean_failure(self, tmp_path, capsys):
        from sam2consensus_tpu.cli import main

        path, _ = self._dirty(tmp_path)
        with pytest.raises(SystemExit) as ei:
            main(["-i", path, "-o", str(tmp_path / "out"), "-p", "clib",
                  "--backend", "cpu", "--on-bad-record", "skip",
                  "--max-bad-records", "2"])
        msg = str(ei.value)
        assert "bad-record budget exhausted" in msg
        assert "reasons:" in msg


# ---------------------------------------------------------------------------
# serve: poison-job isolation
# ---------------------------------------------------------------------------
@pytest.mark.skipif(not HAVE_NATIVE, reason="native decoder unavailable")
class TestServePoison:
    def _runner(self, **kw):
        from sam2consensus_tpu.serve import ServeRunner

        kw.setdefault("prewarm", "off")
        kw.setdefault("persistent_cache", False)
        return ServeRunner(**kw)

    def _paths(self, tmp_path):
        text = open(os.path.join(DATA, "formats_short.sam")).read()
        dirty, entries = make_dirty(text)
        clean = str(tmp_path / "clean.sam")
        with open(clean, "w") as fh:
            fh.write(text)
        poison = str(tmp_path / "poison.sam")
        with open(poison, "w") as fh:
            fh.write(dirty)
        return clean, poison, entries

    def test_poison_job_mid_queue_fails_fast_next_job_warm(self,
                                                           tmp_path):
        from sam2consensus_tpu.serve import JobSpec
        from sam2consensus_tpu.serve.health import snapshot

        clean, poison, entries = self._paths(tmp_path)
        base = dict(backend="jax", pileup="scatter", shards=1,
                    on_device_error="fallback", retries=2,
                    retry_backoff=0.01)
        poison_cfg = RunConfig(**base, on_bad_record="skip",
                               max_bad_records="1")
        runner = self._runner()
        try:
            results = runner.submit_jobs([
                JobSpec(filename=clean, config=RunConfig(**base),
                        tenant="t1"),
                JobSpec(filename=poison, config=poison_cfg,
                        tenant="t1"),
                JobSpec(filename=clean, config=RunConfig(**base),
                        tenant="t1"),
            ])
            assert [r.ok for r in results] == [True, False, True]
            bad = results[1]
            assert bad.budget_exhausted
            assert "bad-record budget exhausted" in bad.error
            # no retry storm, no ladder demotion for the poison job
            assert bad.metrics.get("resilience/retries", 0) == 0
            assert bad.metrics.get("resilience/demotions", 0) == 0
            assert bad.rungs == {}
            # the tenant was NOT pinned off the device path: the next
            # job admitted clean and ran warm on the fast path
            nxt = results[2]
            assert nxt.admission is None
            assert nxt.rungs == {}
            assert nxt.metrics.get("compile/jit_cache_hit", 0) > 0
            assert nxt.metrics.get("compile/jit_cache_miss", 0) == 0
            # poison accounting: counted per tenant, surfaced in health
            assert runner.registry.value("serve/admission_poison") == 1
            assert runner.admission.poison_by_tenant == {"t1": 1}
            assert runner.admission.tenant_rungs == {}
            snap = snapshot(runner)
            assert snap["admission"]["poison"] == 1
            assert snap["poison_by_tenant"] == {"t1": 1}
            assert snap["last_job"]["job"].startswith("job")
        finally:
            runner.close()

    def test_tolerant_job_reports_verdict(self, tmp_path):
        from sam2consensus_tpu.serve import JobSpec
        from sam2consensus_tpu.serve.health import snapshot

        clean, poison, entries = self._paths(tmp_path)
        side = str(tmp_path / "job.q.jsonl")
        cfg = RunConfig(backend="jax", pileup="scatter", shards=1,
                        on_bad_record="quarantine", quarantine_out=side)
        runner = self._runner()
        try:
            [res] = runner.submit_jobs([JobSpec(filename=poison,
                                                config=cfg)])
            assert res.ok
            assert res.bad_records == len(entries)
            assert res.quarantined == len(entries)
            assert not res.budget_exhausted
            got, _summary = _sidecar_entries(side)
            assert len(got) == len(entries)
            # fleet aggregation + last-job verdict in the snapshot
            assert runner.registry.value("serve/bad_records") \
                == len(entries)
            snap = snapshot(runner)
            assert snap["bad_records"] == len(entries)
            assert snap["last_job"]["bad_records"] == len(entries)
            assert snap["last_job"]["budget_exhausted"] is False
        finally:
            runner.close()
