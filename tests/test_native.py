"""Native C++ decoder: differential equivalence with the Python encoder.

SURVEY.md §2b names the host decode path as the framework's one justified
native component; these tests pin it to the Python encoder (which is itself
pinned to the oracle by the differential suite): identical pileup counts,
insertion tables, read accounting, error behavior, and end-to-end FASTA
bytes over the fixture corpus, including every encoding quirk the spec
calls out.
"""

import io

import numpy as np
import pytest

from sam2consensus_tpu.backends.cpu import CpuBackend
from sam2consensus_tpu.backends.jax_backend import JaxBackend
from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.encoder import native_encoder
from sam2consensus_tpu.encoder.events import (GenomeLayout, ReadEncoder,
                                              group_insertions)
from sam2consensus_tpu.io.fasta import render_file
from sam2consensus_tpu.io.sam import ReadStream, iter_records, read_header
from sam2consensus_tpu.utils.simulate import SimSpec, sam_text, simulate

pytestmark = pytest.mark.skipif(not native_encoder.available(),
                                reason="C++ decoder unavailable (no g++?)")


def _layout(text):
    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    return GenomeLayout(contigs), handle, first


def _py_encode(text, **kw):
    layout, handle, first = _layout(text)
    enc = ReadEncoder(layout, **kw)
    batches = list(enc.encode_segments(iter_records(handle, first), 10 ** 9))
    return layout, enc, batches


def _native_encode(text, block_bytes=1 << 23, **kw):
    layout, handle, first = _layout(text)
    enc = native_encoder.NativeReadEncoder(layout, **kw)
    batches = list(enc.encode_blocks(
        ReadStream(handle, first).blocks(max_bytes=block_bytes)))
    return layout, enc, batches


def _counts(batches, total_len):
    counts = np.zeros((total_len + 1, 6), np.int64)
    for b in batches:
        for _w, (starts, codes) in b.buckets.items():
            rows, cols = np.nonzero(codes != 255)
            np.add.at(counts, (starts[rows] + cols, codes[rows, cols]), 1)
    return counts[:-1]


def _assert_equivalent(text, block_bytes=1 << 23, **kw):
    layout, py, pb = _py_encode(text, **kw)
    _l2, nat, nb = _native_encode(text, block_bytes=block_bytes, **kw)
    np.testing.assert_array_equal(_counts(pb, layout.total_len),
                                  _counts(nb, layout.total_len))
    assert py.n_reads == nat.n_reads
    assert py.n_skipped == nat.n_skipped
    assert (sum(b.n_events for b in pb) == sum(b.n_events for b in nb))
    gp = group_insertions(py.insertions, layout)
    gn = group_insertions(nat.insertions, layout)
    if gp is None:
        assert gn is None
    else:
        for k in gp:
            np.testing.assert_array_equal(gp[k], gn[k])
    return py, nat


def test_simulated_corpus_equivalence():
    text = simulate(SimSpec(n_contigs=7, contig_len=400, n_reads=3000,
                            read_len=70, ins_read_rate=0.2,
                            del_read_rate=0.2, seed=3))
    _assert_equivalent(text)


def test_tiny_blocks_and_slab_boundaries():
    # tiny text blocks force many decode calls + slab persistence across
    # block boundaries
    text = simulate(SimSpec(n_contigs=3, contig_len=200, n_reads=800,
                            read_len=40, ins_read_rate=0.3,
                            del_read_rate=0.3, seed=4))
    _assert_equivalent(text, block_bytes=1 << 12)


def test_quirk_records():
    reads = [
        ("r", 1, "4M", "ACGT"),              # plain
        ("r", 1, "*", "AAAA"),               # unmapped: skipped
        ("r", 3, "2M3D2M", "ACGT"),          # deletion
        ("r", 3, "2M3N2M", "ACGT"),          # N advances like D
        ("r", 3, "2M3P2M", "ACGT"),          # P advances (quirk 2)
        ("r", 5, "2S3M1H", "NNACG"),         # clips
        ("r", 2, "2M2I2M", "ACGTAC"),        # insertion
        ("r", 1, "3M", "A-G"),               # literal '-' in SEQ
        ("r", 39, "2M2I", "ACGT"),           # end-of-contig insertion
        ("r", 1, "2I2M", "ACGT"),            # insertion at read start
        ("r", 9, "5M", "ACGTA"),             # plain mid-contig
        ("r2", 1, "6M", "ACGTAC"),           # second contig
        ("r", 4, "10M11D5M", "ACGTACGTACGTACG"),  # long del (maxdel gate)
    ]
    text = sam_text([("r", 40), ("r2", 30)], reads)
    for maxdel in (150, 10, 0, None):
        _assert_equivalent(text, maxdel=maxdel)


def test_negative_pos_wrap():
    # POS-1 < 0 after leading deletion consumes: wraps python-style
    text = sam_text([("w", 30)], [
        ("w", 0, "4M", "ACGT"),     # pos-1 = -1: wraps to the end
        ("w", -3, "8M", "ACGTACGT"),  # deep wrap split across the boundary
        ("w", 0, "2I3M", "GGACG"),  # insertion keyed at negative local pos
    ])
    _assert_equivalent(text)


def test_stray_header_and_progress_lines():
    base = sam_text([("s", 25)], [("s", 1, "5M", "ACGTA")])
    text = base + "@CO stray comment line\n" + sam_text(
        [], [("s", 3, "5M", "TTTTT")]).split("\n", 1)[0] + "\n"
    _assert_equivalent(text)


def test_width_overflow_fallback():
    # one read spans far wider than the slab width: python fallback path
    reads = [("b", 1, "50M", "A" * 50)] * 300 + \
            [("b", 1, "10M900D10M", "ACGTACGTACGTACGTACGT")]
    text = sam_text([("b", 1000)], reads)
    py, nat = _assert_equivalent(text, maxdel=None)
    assert nat.n_reads == 301


def test_giant_insertion_grows_scratch_buffers():
    """A single line whose insertion payload overruns the per-call
    scratch buffers (chars_cap = 1 MiB) must take the grow-and-retry
    path (status==1, consumed==0 -> caps double, arrays REALLOCATE at
    the loop top) and decode exactly — regression for the hoisted
    buffers being grown by cap integer only, which let the C decoder
    write past the allocation."""
    big = ("ACGT" * 330_000)[:1_300_000]          # > 1 MiB insertion
    reads = [("g", 1, "30M", "C" * 30),
             ("g", 5, f"1M{len(big)}I1M", "A" + big + "T"),
             ("g", 11, "20M", "G" * 20)]
    text = sam_text([("g", 400)], reads)
    py, nat = _assert_equivalent(text)
    assert nat.n_reads == 3
    assert len(nat.insertions) == len(py.insertions)


def test_strict_error_parity():
    cases = [
        sam_text([("e", 10)], [("e", 1, "4M", "ACXT")]),   # bad base
        sam_text([("e", 10)], [("e", 8, "4M", "ACGT")]),   # out of bounds
        sam_text([("e", 10)], [("e", 1, "4M4I", "ACGTACZT")]),  # bad motif
        sam_text([("e", 10)], [("q", 1, "4M", "ACGT")]),   # unknown ref
    ]
    for text in cases:
        with pytest.raises(Exception) as py_exc:
            _py_encode(text, strict=True)
        with pytest.raises(Exception) as nat_exc:
            _native_encode(text, strict=True)
        assert type(py_exc.value) is type(nat_exc.value)
        assert str(py_exc.value) == str(nat_exc.value)


def test_malformed_line_errors_in_both_modes():
    good = sam_text([("m", 10)], [("m", 1, "4M", "ACGT")])
    for bad in ("too\tfew\tfields\n", "\n",
                "r\t0\tm\tnotanint\t60\t4M\t*\t0\t0\tACGT\tIIII\n"):
        text = good + bad
        for strict in (True, False):
            with pytest.raises(Exception) as py_exc:
                _py_encode(text, strict=strict)
            with pytest.raises(Exception) as nat_exc:
                _native_encode(text, strict=strict)
            assert type(py_exc.value) is type(nat_exc.value)


def test_permissive_skip_parity():
    text = sam_text([("p", 12)], [
        ("p", 1, "4M", "ACGT"),
        ("p", 1, "4M", "ACXT"),    # bad base -> skip
        ("p", 11, "4M", "ACGT"),   # bounds -> skip
        ("x", 1, "4M", "ACGT"),    # unknown ref -> skip
        ("p", 2, "4M", "TTTT"),
    ])
    py, nat = _assert_equivalent(text, strict=False)
    assert py.n_reads == 2
    assert py.n_skipped == 3


def test_star_seq_parity():
    """SEQ '*' with a real CIGAR (secondary alignments).

    The C fast path may only short-circuit these when the FIRST
    read-consuming op is M/=/X (it reads the '*' and dies on the bad
    base, matching the reference); a leading S or I consumes the '*'
    first and reaches the reference's concatenation-shift semantics —
    gap cells land LEFT of their claimed offsets, an I records an
    empty motif — which only the exact python replay reproduces
    (regression: round-3 review found the unconditioned carve-out
    committing '1S2M4D' gap cells two positions right of the oracle's).
    """
    reads = [
        ("s", 3, "4M", "*"),        # M reads '*': bad base -> skip
        ("s", 3, "1S2M4D", "*"),    # S eats '*': later D cells shift left
        ("s", 3, "2S2I4D", "*"),    # S eats '*': empty-motif I recorded
        ("s", 13, "2S1M", "*"),     # claimed span past end, emitted span 0
        ("s", 2, "6M", "ACGTAC"),   # plain neighbor
    ]
    text = sam_text([("s", 14)], reads)
    py, nat = _assert_equivalent(text, strict=False)
    assert py.n_skipped == 1       # only the M-first '*' read skips
    # strict mode: the M-first '*' read raises the same error both ways
    with pytest.raises(KeyError) as ep:
        _py_encode(text)
    with pytest.raises(KeyError) as en:
        _native_encode(text)
    assert str(ep.value) == str(en.value)


def test_native_vote_differential():
    """s2c_vote (the C++ tail vote) pinned against the device vote AND
    the independent float64 LUT oracle (ops.vote.threshold_luts) over
    adversarial count tensors: exact-integer threshold products (the
    strict-< boundary), min_depth edges, single-lane and all-tied
    positions, and max-int32-adjacent counts."""
    import jax.numpy as jnp

    from sam2consensus_tpu.constants import IUPAC_MASK_LUT
    from sam2consensus_tpu.ops.cutoff import encode_thresholds
    from sam2consensus_tpu.ops.vote import (threshold_luts, vote_positions,
                                            vote_positions_native)

    rng = np.random.default_rng(11)
    blocks = [
        rng.integers(0, 50, size=(4096, 6)),
        rng.integers(0, 3, size=(4096, 6)),           # ties + zeros
        np.zeros((64, 6), dtype=np.int64),            # all uncovered
        np.eye(6, dtype=np.int64)[rng.integers(0, 6, 256)] * 8,  # t*cov int
        np.full((32, 6), (1 << 27) // 6),             # near int32 sums
    ]
    counts = np.concatenate(blocks).astype(np.int32)
    length = counts.shape[0]
    thresholds = [0.25, 0.5, 0.75, 1.0 / 3.0, 0.9999999]
    for md in (1, 2, 9):
        got = vote_positions_native(counts, thresholds, md)
        assert got is not None, "native lib unavailable"
        syms_n, cov_n = got
        want_syms, want_cov = vote_positions(
            jnp.asarray(counts), jnp.asarray(encode_thresholds(thresholds)),
            md)
        np.testing.assert_array_equal(syms_n, np.asarray(want_syms))
        np.testing.assert_array_equal(cov_n, np.asarray(want_cov))
        # independent oracle: greedy vote via the float64 cutoff LUT
        lut = threshold_luts(thresholds, int(cov_n.max()))
        for p in rng.integers(0, length, 200):
            c = counts[p]
            cov = int(c.sum())
            for t in range(len(thresholds)):
                if cov == 0 or cov < md:
                    assert syms_n[t, p] == 0
                    continue
                cutoff = lut[t, cov]
                mask = 0
                for i in range(6):
                    s_i = int(c[c > c[i]].sum())
                    if c[i] != 0 and s_i < cutoff:
                        mask |= 1 << i
                assert syms_n[t, p] == IUPAC_MASK_LUT[mask], (p, t)


def test_native_vote_threaded_matches_serial():
    """The multi-threaded vote (position ranges across workers) must be
    bit-identical to serial at a length that actually engages the
    threaded branch (>= 2^20 positions; below that the C side stays
    serial and this test would assert nothing)."""
    from sam2consensus_tpu.ops.vote import vote_positions_native

    rng = np.random.default_rng(7)
    length = (1 << 20) + 12_345          # odd tail -> uneven last slice
    counts = rng.integers(0, 120, size=(length, 6)).astype(np.int32)
    counts[rng.random(length) < 0.2] = 0
    serial = vote_positions_native(counts, [0.25, 0.75], 1, threads=1)
    for n in (2, 3, 8):
        threaded = vote_positions_native(counts, [0.25, 0.75], 1,
                                         threads=n)
        np.testing.assert_array_equal(serial[0], threaded[0])
        np.testing.assert_array_equal(serial[1], threaded[1])


def test_fused_counts_rollback_paths():
    """Inline counting in the fused decode pass (counts incremented while
    cells are translated) must roll back exactly on its two abort paths:
    a bad base in permissive mode (whole row un-counted) and the maxdel
    gate (counted GAP cells retro-decremented when converted to PAD)."""
    reads = [
        ("f", 1, "4M", "ACGT"),                    # plain
        ("f", 2, "4M", "ACXT"),                    # bad base -> rollback
        ("f", 1, "2M6D2M", "ACGT"),                # 6 gaps > maxdel=4
        ("f", 3, "2M2D2M", "ACGT"),                # 2 gaps <= maxdel
        ("f", 1, "3M", "A-G"),                     # literal '-' counts
    ]
    text = sam_text([("f", 20)], reads)
    layout, py, pb = _py_encode(text, strict=False, maxdel=4)
    want = _counts(pb, layout.total_len)

    layout2, handle, first = _layout(text)
    acc = np.zeros((layout2.total_len, 6), np.int32)
    enc = native_encoder.NativeReadEncoder(
        layout2, strict=False, maxdel=4, accumulate_into=acc)
    from sam2consensus_tpu.io.sam import ReadStream
    for _ in enc.encode_blocks(ReadStream(handle, first).blocks()):
        pass
    np.testing.assert_array_equal(acc, want)
    assert py.n_skipped == enc.n_skipped == 1


def test_end_to_end_stream_byte_identity():
    text = simulate(SimSpec(n_contigs=4, contig_len=250, n_reads=900,
                            read_len=50, ins_read_rate=0.2,
                            del_read_rate=0.2, seed=9))
    cfg = RunConfig(prefix="nat", thresholds=[0.25, 0.75])

    def run(backend, cfg):
        handle = io.StringIO(text)
        contigs, _n, first = read_header(handle)
        res = backend.run(contigs, ReadStream(handle, first), cfg)
        return {n: render_file(r, 0) for n, r in res.fastas.items()}

    out_cpu = run(CpuBackend(), cfg)
    jcfg = RunConfig(prefix="nat", thresholds=[0.25, 0.75], backend="jax",
                     decoder="native")
    out_jax = run(JaxBackend(), jcfg)
    assert out_jax == out_cpu


def test_line_accounting_matches_python():
    text = simulate(SimSpec(n_contigs=2, contig_len=150, n_reads=300,
                            read_len=30, seed=13))

    def count(decoder):
        handle = io.StringIO(text)
        contigs, _n, first = read_header(handle)
        stream = ReadStream(handle, first)
        cfg = RunConfig(backend="jax", decoder=decoder)
        JaxBackend().run(contigs, stream, cfg)
        return stream.n_lines

    assert count("native") == count("py")


def test_fused_shadow_saturation_banked_exact():
    """Depth > 255 wraps the uint8 shadow cell and banks +256 in the
    overflow tensor (decoder.cpp u8_inc / count_row_u8 saturation
    branch); merge_shadow folds cell + bank exactly, including at a
    mid-stream checkpoint-style merge boundary.  Pins the banked-wrap
    counter (out[12]) that gates the bank fold: a counting path that
    wrote the bank without reporting a wrap would silently lose
    multiples of 256 at >255x depth and no other test would notice."""
    depth = 300
    motif = "ACGTACGTAC"
    reads = [("r", 2, "10M", motif)] * depth
    text = sam_text([("r", 40)], reads)
    layout, handle, first = _layout(text)
    acc = np.zeros((layout.total_len, 6), np.int32)
    enc = native_encoder.NativeReadEncoder(layout, accumulate_into=acc)

    body = text.split("\n", 2)[2]          # read lines only
    mid_counts = []

    def blocks():
        yield body
        # checkpoint-style mid-stream merge: the wrap path must have
        # engaged (cells wrapped at 256), and the fold must be exact
        assert enc._banked > 0
        enc.merge_shadow()
        assert enc._banked == 0
        mid_counts.append(acc.copy())
        yield body

    for _ in enc.encode_blocks(blocks()):
        pass

    want = np.zeros_like(acc)
    for col, base in enumerate(motif):
        want[1 + col, "-ACGNT".index(base)] = 2 * depth
    np.testing.assert_array_equal(acc, want)
    np.testing.assert_array_equal(mid_counts[0], want // 2)


def test_cov_sums_matches_reduceat():
    """s2c_cov_sums (SIMD segmented widen-accumulate) == the numpy
    reduction it replaced, including empty contigs and odd lengths."""
    lib = native_encoder.native.load()
    rng = np.random.default_rng(3)
    cov = rng.integers(0, 1000, 100_003).astype(np.int32)
    offs = np.array([0, 17, 17, 4099, 4099, 50_000, 100_003],
                    dtype=np.int64)
    out = np.empty(len(offs) - 1, dtype=np.int64)
    lib.s2c_cov_sums(cov, offs, len(offs) - 1, out)
    want = [cov[offs[i]:offs[i + 1]].sum(dtype=np.int64)
            for i in range(len(offs) - 1)]
    np.testing.assert_array_equal(out, want)


def test_finalize_matches_python_chain():
    """s2c_finalize (one-pass fill substitution + '-' count) == the
    python translate/count chain, across fill chars incl. '-' itself
    and lengths around the 64-byte SIMD boundary."""
    lib = native_encoder.native.load()
    rng = np.random.default_rng(4)
    for fill in (b"-", b"N", b"?"):
        for n in (0, 1, 63, 64, 65, 1000, 4096 + 17):
            syms = rng.choice(
                np.frombuffer(b"\x00-ACGTRYacgtn", dtype=np.uint8),
                size=n).astype(np.uint8)
            buf = np.empty(n, np.uint8)
            dashes = lib.s2c_finalize(
                np.ascontiguousarray(syms), n, fill[0], buf)
            raw = syms.tobytes().translate(
                bytes.maketrans(b"\x00", fill))
            assert buf.tobytes() == raw
            assert dashes == raw.count(b"-")


def test_vote_zero_block_fast_path_matches_scalar():
    """The SIMD vote's all-zero-block skip emits exactly what the
    scalar path does: cov 0 and the sentinel symbol for every
    threshold — interleaving covered and empty 16-position blocks."""
    from sam2consensus_tpu.ops.vote import vote_positions_native

    rng = np.random.default_rng(6)
    L = 4096 + 5
    counts = np.zeros((L, 6), dtype=np.int32)
    # cover scattered short runs so some 16-blocks are empty, some
    # partial, some full
    for s in rng.integers(0, L - 40, 60):
        counts[s:s + 30, rng.integers(0, 6)] += rng.integers(1, 9)
    got_syms, got_cov = vote_positions_native(
        counts, [0.25, 1.0], 2, threads=1)
    # scalar reference: force the remainder handler over the whole
    # range by voting tiny slices (each < 16 positions wide)
    parts = [vote_positions_native(counts[i:i + 7], [0.25, 1.0], 2,
                                   threads=1)
             for i in range(0, L, 7)]
    ref_syms = np.concatenate([p[0] for p in parts], axis=1)
    ref_cov = np.concatenate([p[1] for p in parts])
    np.testing.assert_array_equal(got_syms, ref_syms)
    np.testing.assert_array_equal(got_cov, ref_cov)


@pytest.mark.parametrize("n_ops", [31, 32, 33, 64])
def test_cigar_op_cache_boundary(n_ops):
    """The fast path caches up to 32 CIGAR ops and re-parses longer
    strings; pin both sides of the boundary against the python encoder
    (a regression in the cache/fallback split would otherwise pass the
    suite: simulated CIGARs carry at most ~4 ops)."""
    pairs = (n_ops - 1) // 2
    cigar = "".join(["1M1I"] * pairs)
    cigar += "2M" if (n_ops - 1) % 2 == 0 else ""
    # read length: pairs M + pairs I (+ maybe 2M tail)
    rlen = pairs * 2 + (2 if (n_ops - 1) % 2 == 0 else 0)
    reads = [("r", 3, cigar, "ACGT" * (rlen // 4 + 1))]
    reads = [(c, p, cg, seq[:rlen]) for (c, p, cg, seq) in reads]
    text = sam_text([("r", 400)], reads)
    layout, py, pb = _py_encode(text)
    want = _counts(pb, layout.total_len)

    layout2, handle, first = _layout(text)
    acc = np.zeros((layout2.total_len, 6), np.int32)
    enc = native_encoder.NativeReadEncoder(layout2, accumulate_into=acc)
    for _ in enc.encode_blocks(ReadStream(handle, first).blocks()):
        pass
    np.testing.assert_array_equal(acc, want.astype(np.int32))
    assert py.insertions.to_arrays()[2].tolist() == \
        enc.insertions.to_arrays()[2].tolist()
