"""Multi-PROCESS (DCN-topology) validation of the sharded pipeline.

The other parallel tests run every collective on a virtual mesh inside
one controller; this one shells out to ``tools/multihost_dryrun.py``,
which runs the production dp / sp / dpsp accumulators over a
``jax.distributed`` mesh spanning two OS processes (gloo cross-process
collectives — the CPU stand-in for DCN) and asserts counts, vote and
tail stats byte-equal to the single-device oracle in every process.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_multihost_two_processes_byte_equal():
    env = dict(os.environ)
    # the workers set their own JAX_PLATFORMS/XLA_FLAGS; drop the
    # conftest's 8-device forcing so each worker gets exactly 4
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "multihost_dryrun.py"),
         "--procs", "2", "--devs", "4", "--port", str(_free_port())],
        env=env, capture_output=True, text=True, timeout=560)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "MULTIHOST OK" in proc.stdout, proc.stdout + proc.stderr
