"""Test environment: force JAX onto 8 virtual CPU devices.

SURVEY.md §4 ("Multi-device without a cluster"): tests must run without TPU
hardware, so the host platform is split into 8 fake devices before any JAX
import.  The same pmap/shard_map tests then run unchanged on a real slice.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# An environment sitecustomize may pre-register a remote TPU backend and
# override jax_platforms via jax.config (trumping the env var), which would
# make the first backend use dial remote hardware from unit tests.  Re-pin
# the config to cpu before any backend is initialized.
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:  # pragma: no cover - jax is a hard dep of the jax path
    pass

import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402

from sam2consensus_tpu.config import RunConfig  # noqa: E402


@pytest.fixture
def cfg():
    return RunConfig()
