"""Cohort-scale serving (serve/cohort.py + the ISSUE-20 packing
additions): the shared-reference wave-streaming pins.

* layout dedup: equal reference fingerprints share ONE PanelGeometry;
  ``plan_wave`` reuses the cached offset table and ``extract_member``
  over the deduped plan is byte-identical to serial accumulation;
* ``merge_batches`` cell-budget regression: a wide bucket whose row
  budget sits under the 1024-row alignment stripe must still split
  under ``max_cells`` (the satellite-1 floor fix);
* manifest loading: directory scan, JSONL object-store listing, text
  lists with globs/comments, and the zero-input ValueError;
* wave sizing: hard caps (combined-length, ``--max-queue``,
  ``--mem-budget``), the floor-2 rule, explicit-wave clamping, the pow2
  occupancy snap — and the final-wave no-snap rule;
* the ConcordanceAccumulator's tally/digest semantics;
* end-to-end: a multi-wave cohort through one ServeRunner is
  byte-identical to serial, plans ONE panel geometry, prices a
  ``cohort_wave`` ledger decision per wave, reports progress through
  health/s2c_top, and resumes from the journal;
* CLI: cohort flag combinations that cannot work fail at start.
"""

import json
import os
import sys
import types

import numpy as np
import pytest

from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.constants import PAD_CODE
from sam2consensus_tpu.encoder.events import SegmentBatch
from sam2consensus_tpu.io.fasta import render_file
from sam2consensus_tpu.serve import JobSpec, packing
from sam2consensus_tpu.serve.cohort import (ConcordanceAccumulator,
                                            CohortRunner, load_manifest,
                                            size_wave, wave_cap)
from sam2consensus_tpu.utils.simulate import SimSpec, simulate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_persistent_cache(monkeypatch):
    monkeypatch.setenv("S2C_JIT_CACHE", "")


def _sim_member(tmp, k, n_reads=48, contig_len=900):
    """One cohort member: every member shares the reference LAYOUT
    (same contig name + length -> equal fingerprint) while the reads
    differ per seed — the exact sameness class cohort dedup keys on."""
    spec = SimSpec(n_contigs=1, contig_len=contig_len, n_reads=n_reads,
                   read_len=100, contig_len_jitter=0.0,
                   seed=30_000 + k, contig_prefix="cohtest")
    path = os.path.join(str(tmp), f"coh_{k:03d}.sam")
    with open(path, "w") as fh:
        fh.write(simulate(spec))
    return path


def _runner(**kw):
    from sam2consensus_tpu.serve import ServeRunner

    kw.setdefault("prewarm", "off")
    kw.setdefault("persistent_cache", False)
    kw.setdefault("echo", lambda *a, **k: None)
    return ServeRunner(**kw)


def _rendered(res):
    return {n: render_file(r, 0) for n, r in res.fastas.items()}


# -- layout dedup ----------------------------------------------------------
def test_reference_fingerprint_layout_semantics():
    fp = packing.reference_fingerprint([("chr1", 100), ("chr2", 50)])
    assert fp == packing.reference_fingerprint(
        [("chr1", 100), ("chr2", 50)])
    # order-sensitive: offsets are cumulative lengths
    assert fp != packing.reference_fingerprint(
        [("chr2", 50), ("chr1", 100)])
    assert fp != packing.reference_fingerprint(
        [("chr1", 100), ("chr2", 51)])
    # Contig objects and plain pairs fingerprint identically
    c1 = types.SimpleNamespace(name="chr1", length=100)
    c2 = types.SimpleNamespace(name="chr2", length=50)
    assert fp == packing.reference_fingerprint([c1, c2])


def test_panel_geometry_reuse_and_member_extraction():
    geom = packing.PanelGeometry(fingerprint="f" * 16, panel_len=100,
                                 max_jobs=8)
    assert geom.offsets == tuple(k * 100 for k in range(8))
    plan = geom.plan_wave(["a", "b", "c"])
    assert geom.plans_built == 1 and geom.reuses == 0
    assert plan.total_len == 300
    assert [m.offset for m in plan.members] == [0, 100, 200]
    # every later wave — any size under the cap — is a reuse
    plan2 = geom.plan_wave(["d", "e", "f", "g", "h"])
    assert geom.plans_built == 1 and geom.reuses == 1
    assert [m.offset for m in plan2.members] == [0, 100, 200, 300, 400]
    with pytest.raises(ValueError):
        geom.plan_wave([f"j{i}" for i in range(9)])
    # extract_member over the deduped plan: each member's slice of the
    # combined tensor is exactly its private partition
    combined = np.arange(300 * 6).reshape(300, 6)
    for k, m in enumerate(plan.members):
        part = packing.extract_member(combined, m)
        assert np.array_equal(part, combined[k * 100:(k + 1) * 100])
        assert part.flags["C_CONTIGUOUS"]


def test_merge_batches_wide_bucket_cell_budget():
    """Satellite-1 regression: a bucket wider than ``max_cells/1024``
    used to round its row budget DOWN to the 1024-row stripe (to zero
    rows per slab) or mint a single over-budget slab; the floor fix
    must split such buckets into slabs that each respect max_cells
    without dropping rows."""
    w, n_rows = 4096, 40
    max_cells = 8 * w            # budget_rows = 8, far under the stripe
    starts = np.arange(n_rows, dtype=np.int32)
    codes = np.ones((n_rows, w), dtype=np.uint8)
    plan = packing.plan_pack([("solo", n_rows * w)])
    batch = SegmentBatch(buckets={w: (starts, codes)},
                         n_events=n_rows * w)
    merged = packing.merge_batches(plan, [(plan.members[0], [batch])],
                                   max_cells=max_cells)
    assert merged, "wide bucket produced no slabs"
    got_rows = 0
    for sb in merged:
        (st, mat), = sb.buckets.values()
        real = int((~(mat == PAD_CODE).all(axis=1)).sum())
        got_rows += real
        assert real * w <= max_cells, \
            f"slab of {real} real rows x {w} exceeds max_cells"
    assert got_rows == n_rows        # no rows dropped by the split
    assert plan.real_rows == n_rows
    assert plan.merged_slabs == len(merged) >= 5


def test_pad_rows_contract():
    """_pad_rows is the one authoritative padding statement: pow2 with
    a floor of 8 (the module docstring defers here)."""
    assert [packing._pad_rows(n) for n in (1, 7, 8, 9, 64, 65)] == \
        [8, 8, 8, 16, 64, 128]


# -- manifest loading ------------------------------------------------------
def test_load_manifest_directory(tmp_path):
    for name in ("b.sam", "a.sam", "c.bam", "d.sam.gz", "skip.txt"):
        (tmp_path / name).write_text("")
    got = load_manifest(str(tmp_path))
    assert [os.path.basename(p) for p in got] == \
        ["a.sam", "b.sam", "c.bam", "d.sam.gz"]


def test_load_manifest_jsonl(tmp_path):
    man = tmp_path / "listing.jsonl"
    man.write_text(json.dumps({"path": "x.sam"}) + "\n"
                   + json.dumps({"path": "/abs/y.sam"}) + "\n")
    got = load_manifest(str(man))
    assert got == [str(tmp_path / "x.sam"), "/abs/y.sam"]
    man.write_text(json.dumps({"size": 3}) + "\n")
    with pytest.raises(ValueError, match="no 'path' key"):
        load_manifest(str(man))
    man.write_text("{not json\n")
    with pytest.raises(ValueError, match="not JSON"):
        load_manifest(str(man))


def test_load_manifest_text_globs_and_comments(tmp_path):
    for name in ("g1.sam", "g2.sam", "one.sam"):
        (tmp_path / name).write_text("")
    man = tmp_path / "manifest.txt"
    man.write_text("# cohort members\n\none.sam\ng*.sam\n")
    got = load_manifest(str(man))
    assert [os.path.basename(p) for p in got] == \
        ["one.sam", "g1.sam", "g2.sam"]


def test_load_manifest_empty_is_an_error(tmp_path):
    (tmp_path / "empty.txt").write_text("# nothing\n")
    with pytest.raises(ValueError, match="zero inputs"):
        load_manifest(str(tmp_path / "empty.txt"))
    os.mkdir(tmp_path / "emptydir")
    with pytest.raises(ValueError, match="zero inputs"):
        load_manifest(str(tmp_path / "emptydir"))


# -- wave sizing -----------------------------------------------------------
def _sched(max_combined_len=1_000_000):
    return types.SimpleNamespace(max_combined_len=max_combined_len)


def _admission(max_queue=0, mem_budget=0):
    return types.SimpleNamespace(max_queue=max_queue,
                                 mem_budget=mem_budget)


def test_wave_cap_combined_length_and_queue():
    cap, inputs = wave_cap(100, 100, None, _sched(1000), _admission())
    assert cap == 10 and inputs["len_cap"] == 10
    cap, inputs = wave_cap(100, 100, None, _sched(1000),
                           _admission(max_queue=4))
    assert cap == 4 and inputs["queue_cap"] == 4
    cap, _ = wave_cap(3, 100, None, _sched(1000), _admission())
    assert cap == 3                      # never beyond the remainder
    with pytest.raises(ValueError, match="cannot pack"):
        wave_cap(100, 80, None, _sched(100), _admission())


def test_wave_cap_mem_budget_binary_search(monkeypatch):
    from sam2consensus_tpu.observability import memplane

    # linear model: W members x 100 positions -> W * 1000 bytes
    monkeypatch.setattr(memplane, "predict_job_peak_bytes",
                        lambda total_len, cfg: total_len * 10)
    cap, inputs = wave_cap(100, 100, None, _sched(),
                           _admission(mem_budget=5_000))
    assert cap == 5 and inputs["mem_cap"] == 5
    with pytest.raises(ValueError, match="mem-budget"):
        wave_cap(100, 100, None, _sched(),
                 _admission(mem_budget=1_500))   # even W=2 won't fit


def test_size_wave_rate_target_and_floors():
    # rate target: jps * wave_sec, floored at 2 (a wave of 1 can't pack)
    w, inputs = size_wave(100, 100, None, _sched(), _admission(),
                          jps=5.0, wave_sec=2.0)
    assert w == 10 and inputs["rate_target"] == 10
    w, _ = size_wave(100, 100, None, _sched(), _admission(),
                     jps=0.1, wave_sec=2.0)
    assert w == 2
    # explicit --cohort-wave wins but clamps to the hard cap
    w, inputs = size_wave(100, 100, None, _sched(1000), _admission(),
                          requested=64)
    assert w == 10 and inputs["requested"] == 64
    # the remainder is the last clamp
    w, _ = size_wave(3, 100, None, _sched(), _admission(), requested=8)
    assert w == 3


def test_size_wave_pow2_snap_and_final_wave_rule():
    # rows_per_member=16 at a 10-member target: 160 rows pad to 256
    # (62% full) while 8 members' 128 rows land exactly on a pow2
    # boundary — the snap takes 8
    w, inputs = size_wave(100, 100, None, _sched(), _admission(),
                          jps=5.0, wave_sec=2.0, rows_per_member=16.0)
    assert w == 8
    assert inputs["occupancy_target_pct"] == 100.0
    # ...but NEVER for the final wave: shrinking below the remainder
    # would mint an extra wave, and wave fixed costs beat pad rows
    w, inputs = size_wave(11, 100, None, _sched(), _admission(),
                          jps=5.0, wave_sec=2.0, rows_per_member=16.0)
    assert w == 10 and "occupancy_target_pct" not in inputs


# -- concordance -----------------------------------------------------------
def test_concordance_accumulator_tally_and_digest():
    acc = ConcordanceAccumulator(3)
    a = np.zeros((3, 6), dtype=np.int64)
    a[0, 1] = 5                       # pos0: call 1
    a[1, 2] = 4                       # pos1: call 2; pos2: no depth
    b = np.zeros((3, 6), dtype=np.int64)
    b[0, 1] = 2                       # pos0 agrees
    b[1, 3] = 9                       # pos1 disagrees
    acc.add_member(a)
    acc.add_member(b)
    s = acc.summary()
    assert s["members"] == 2 and s["panel_len"] == 3
    # pos0: 2/2 agree; pos1: 1/2 modal; pos2: nobody called -> 1.0
    assert s["min_concordance"] == 0.5
    assert s["discordant_positions"] == 1
    assert s["mean_concordance"] == round((1.0 + 0.5 + 1.0) / 3, 6)
    # digest is the pin: same members -> same digest, differing
    # members -> different
    acc2 = ConcordanceAccumulator(3)
    acc2.add_member(a)
    acc2.add_member(b)
    assert acc2.summary()["digest"] == s["digest"]
    acc2.add_member(a)
    assert acc2.summary()["digest"] != s["digest"]
    with pytest.raises(ValueError, match="positions"):
        acc.add_member(np.zeros((4, 6), dtype=np.int64))


# -- end-to-end ------------------------------------------------------------
def test_cohort_multiwave_byte_identity_and_single_plan(tmp_path):
    """A 10-member cohort at --cohort-wave 4 (3 waves): outputs
    byte-identical to serial, ONE panel plan with a reuse per wave,
    a cohort_wave ledger decision per wave, occupancy accounted, and
    live progress visible through health + s2c_top."""
    paths = [_sim_member(tmp_path, k) for k in range(10)]
    cfg = RunConfig(backend="jax", prefix="",
                    outfolder=str(tmp_path / "out_c"))

    from sam2consensus_tpu.config import default_prefix

    rs = _runner(batch="off")
    serial = rs.submit_jobs(
        [JobSpec(filename=p, config=RunConfig(
            backend="jax", prefix=default_prefix(p),
            outfolder=str(tmp_path / "out_s")), job_id=f"s{k}")
         for k, p in enumerate(paths)])
    rs.close()

    rp = _runner(batch="auto")
    try:
        cohort = CohortRunner(rp, paths, cfg, wave=4)
        assert rp.cohort is cohort       # health sees live progress
        summary = cohort.run()
        health = rp.health_snapshot()
        reg = rp.registry
        real = reg.snapshot()["gauges"].get(
            "batch/real_rows", {}).get("value", 0.0)
        padded = reg.snapshot()["gauges"].get(
            "batch/padded_rows", {}).get("value", 0.0)
    finally:
        rp.close()

    assert summary["samples_ok"] == 10 and summary["failed"] == 0
    assert summary["waves"] == 3
    # layout dedup: ONE plan, every wave a prefix-slice reuse
    assert summary["panel_plans"] == 1
    assert summary["panel_reuses"] >= 3
    # one cohort_wave decision per wave, jobs priced = jobs measured
    decisions = summary["decisions"]
    assert len(decisions) == 3
    assert [d["inputs"]["wave_jobs"] for d in decisions] == [4, 4, 2]
    assert all(d["decision"] == "cohort_wave" for d in decisions)
    # multi-wave occupancy accounting: the last wave's merge gauges
    # are real and pow2-padded
    assert 0 < real <= padded
    assert cohort.last_wave["occupancy_pct"] > 0
    # byte identity member-for-member vs the serial runner
    by_file = {r.filename: r for r in cohort.results}
    for k, (p, rser) in enumerate(zip(paths, serial)):
        rc = by_file[p]
        assert rc.ok and rser.ok
        assert _rendered(rc) == _rendered(rser), f"member {k} differs"
    # concordance accumulated every member
    conc = summary["concordance"]
    assert conc["members"] == 10 and conc["digest"]
    # health + s2c_top surfacing
    coh = health["cohort"]
    assert coh["waves_done"] == 3
    assert coh["samples_done"] == 10
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import s2c_top
    finally:
        sys.path.pop(0)
    lines = s2c_top.render(health, [])
    cline = [ln for ln in lines if ln.startswith("cohort:")]
    assert cline and "wave 3/3" in cline[0] \
        and "samples 10/10" in cline[0]


def test_cohort_resumes_from_journal(tmp_path):
    """Kill-and-restart semantics without the kill: run half the
    cohort under a journal, then hand the FULL manifest to a fresh
    process-equivalent runner — the resumed cohort must skip every
    committed member and only run the remainder."""
    paths = [_sim_member(tmp_path, k, n_reads=32, contig_len=600)
             for k in range(6)]
    jdir = str(tmp_path / "journal")
    cfg = RunConfig(backend="jax", prefix="",
                    outfolder=str(tmp_path / "out"))
    r1 = _runner(batch="auto", journal_dir=jdir)
    try:
        CohortRunner(r1, paths[:3], cfg, wave=3).run()
    finally:
        r1.close()
    r2 = _runner(batch="auto", journal_dir=jdir)
    try:
        cohort = CohortRunner(r2, paths, cfg, wave=3)
        summary = cohort.run()
    finally:
        r2.close()
    assert summary["resumed"] == 3
    assert summary["samples_ok"] == 3 and summary["failed"] == 0
    assert summary["waves"] == 1      # only the pending half ran
    # the journal carries one cohort_wave marker per finished wave
    # (one ev-NNNNNNNN.json segment per event)
    events = []
    for name in sorted(os.listdir(jdir)):
        if name.startswith("ev-") and name.endswith(".json"):
            with open(os.path.join(jdir, name)) as fh:
                events.append(json.load(fh))
    waves = [e for e in events if e.get("ev") == "cohort_wave"]
    assert len(waves) == 2            # one per run
    assert all(e["fingerprint"] for e in waves)


def test_cohort_requires_batch_scheduler(tmp_path):
    p = _sim_member(tmp_path, 0)
    cfg = RunConfig(backend="jax")
    r = _runner(batch="off")
    try:
        with pytest.raises(ValueError, match="--batch"):
            CohortRunner(r, [p], cfg)
    finally:
        r.close()


# -- CLI cross-checks ------------------------------------------------------
@pytest.mark.parametrize("argv", [
    ["--cohort-manifest", "m.txt", "-i", "x.sam"],
    ["--cohort-manifest", "m.txt", "--batch", "0"],
    ["--cohort-manifest", "m.txt", "--batch", "1"],
    ["--cohort-manifest", "m.txt", "--worker-id", "w1"],
    ["--cohort-manifest", "m.txt", "--ingest-port", "0"],
    ["--cohort-manifest", "m.txt", "--cohort-wave", "1"],
    ["-i", "x.sam", "--cohort-wave", "-2"],
])
def test_serve_cli_rejects_bad_cohort_combos(argv):
    from sam2consensus_tpu.cli import serve_main

    with pytest.raises(SystemExit):
        serve_main(argv)
