"""CIGAR walker unit tests — every op, including the documented quirks.

Spec: /root/reference/sam2consensus.py:46-82 (see SURVEY.md §2 table row
"CIGAR walker").
"""

from sam2consensus_tpu.core.cigar import split_ops, walk


def test_simple_match():
    out, ins = walk("4M", "ACGT", 10)
    assert out == "ACGT"
    assert ins == []


def test_eq_and_x_behave_like_match():
    out, ins = walk("2=2X", "ACGT", 0)
    assert out == "ACGT"
    assert ins == []


def test_deletion_emits_gaps():
    out, ins = walk("2M3D2M", "ACGT", 0)
    assert out == "AC---GT"
    assert ins == []


def test_refskip_N_emits_gaps():
    out, _ = walk("1M2N1M", "AC", 0)
    assert out == "A--C"


def test_padding_P_consumes_reference():
    # Quirk 2: the reference advances the ref cursor on P (sam2consensus.py:70-72)
    # although the SAM spec says P consumes neither sequence.
    out, _ = walk("1M1P1M", "AC", 0)
    assert out == "A-C"


def test_insertion_records_next_ref_index():
    # Insertion key is the index of the *next* reference base (quirk 3).
    out, ins = walk("3M2I2M", "AAACCGG", 5)
    assert out == "AAAGG"
    assert ins == [(8, "CC")]


def test_insertion_at_read_start():
    out, ins = walk("2I3M", "CCAAA", 5)
    assert out == "AAA"
    assert ins == [(5, "CC")]


def test_softclip_skips_read_bases():
    out, ins = walk("2S3M", "TTAAA", 0)
    assert out == "AAA"
    assert ins == []


def test_hardclip_noop():
    out, _ = walk("2H3M2H", "AAA", 0)
    assert out == "AAA"


def test_combined():
    # 2S 3M 1I 2M 2D 1M: read = SS MMM I MM M
    out, ins = walk("2S3M1I2M2D1M", "TTACGTCAG", 100)
    assert out == "ACGCA--G"
    assert ins == [(103, "T")]


def test_split_ops_ignores_garbage():
    # The reference regex silently drops unmatched text.
    assert split_ops("3M*") == [(3, "M")]
    assert split_ops("*") == []
    assert split_ops("10M5I") == [(10, "M"), (5, "I")]
