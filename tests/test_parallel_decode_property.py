"""Property test: sharded ingest == serial ingest, for ANY input.

Random small SAM bodies — valid reads, indel CIGARs, unmapped lines,
out-of-bounds spans, malformed junk, optional trailing-newline-less
tails — decoded serially and through the byte-shard rung at a random
thread count with 1-byte shard floors (so raw cuts land mid-line
everywhere).  Either both paths raise the same exception (type and
message — the strict first-error parity contract) or both succeed with
bit-identical counts and identical read/skip/insertion totals.
"""

import os
import tempfile

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import HealthCheck, given, settings, strategies as st  # noqa: E402

from sam2consensus_tpu import native  # noqa: E402
from sam2consensus_tpu.encoder.events import GenomeLayout  # noqa: E402
from sam2consensus_tpu.encoder.native_encoder import \
    NativeReadEncoder  # noqa: E402
from sam2consensus_tpu.encoder.parallel_decode import \
    ParallelFusedDecoder  # noqa: E402
from sam2consensus_tpu.io.sam import ReadStream, opener, \
    read_header  # noqa: E402
from sam2consensus_tpu.ops.pileup import \
    HostPileupAccumulator  # noqa: E402

pytestmark = pytest.mark.skipif(native.load() is None,
                                reason="native decoder unavailable")

HEADER = "@SQ\tSN:c1\tLN:60\n@SQ\tSN:c2\tLN:40\n"


@st.composite
def sam_line(draw):
    kind = draw(st.sampled_from(
        ["read", "read", "read", "ins", "dele", "clip", "unmapped",
         "oob", "badref", "junk"]))
    ref = draw(st.sampled_from(["c1", "c2"]))
    reflen = 60 if ref == "c1" else 40
    pos = draw(st.integers(1, reflen))
    seq = "".join(draw(st.lists(st.sampled_from("ACGTN"), min_size=12,
                                max_size=12)))
    base = f"r\t0\t{ref}\t{pos}\t60\t{{cig}}\t*\t0\t0\t{{seq}}\t*"
    if kind == "unmapped":
        return base.format(cig="*", seq=seq)
    if kind == "junk":
        return draw(st.sampled_from(
            ["broken line", "a\tb\tc", "r\t0\tc1\tNOTANINT\t60\t5M\t*"
             "\t0\t0\tACGTA\t*"]))
    if kind == "badref":
        return base.format(cig="5M", seq=seq[:5]).replace(ref, "nope")
    if kind == "oob":
        return f"r\t0\t{ref}\t{reflen}\t60\t12M\t*\t0\t0\t{seq}\t*"
    if kind == "ins":
        return base.format(cig="4M3I5M", seq=seq)
    if kind == "dele":
        return base.format(cig="4M3D4M", seq=seq[:8])
    if kind == "clip":
        return base.format(cig="2S6M2H", seq=seq[:8])
    span = min(12, reflen - pos + 1)
    return base.format(cig=f"{span}M", seq=seq[:span])


def _run(path, n_threads):
    handle = opener(path, binary=True)
    try:
        contigs, _n, first = read_header(handle)
        layout = GenomeLayout(contigs)
        if n_threads == 0:
            counts = np.zeros((layout.total_len, 6), dtype=np.int32)
            enc = NativeReadEncoder(layout, accumulate_into=counts)
            for _ in enc.encode_blocks(ReadStream(handle, first).blocks()):
                pass
            return counts, enc.n_reads, enc.n_skipped, len(enc.insertions)
        acc = HostPileupAccumulator(layout.total_len)
        dec = ParallelFusedDecoder(layout, acc.counts_host(), n_threads)
        for _ in dec.encode_input(ReadStream(handle, first),
                                  min_shard_bytes=1):
            pass
        return (acc.counts_host(), dec.n_reads, dec.n_skipped,
                len(dec.insertions))
    finally:
        handle.close()


@settings(max_examples=25, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(lines=st.lists(sam_line(), max_size=30),
       trailing_newline=st.booleans(),
       n_threads=st.integers(2, 5))
def test_shard_rung_matches_serial(lines, trailing_newline, n_threads):
    text = HEADER + "\n".join(lines)
    if lines and trailing_newline:
        text += "\n"
    fd, path = tempfile.mkstemp(suffix=".sam")
    try:
        with os.fdopen(fd, "w") as fh:
            fh.write(text)
        try:
            want = _run(path, 0)
            serial_exc = None
        except Exception as exc:
            serial_exc = (type(exc), str(exc))
        try:
            got = _run(path, n_threads)
            par_exc = None
        except Exception as exc:
            par_exc = (type(exc), str(exc))
        assert serial_exc == par_exc
        if serial_exc is None:
            np.testing.assert_array_equal(want[0], got[0])
            assert want[1:] == got[1:]
    finally:
        os.unlink(path)
