"""Incremental consensus via the per-reference count cache (ISSUE 13).

The serving contract under test: a tenant streaming new reads against
a warm reference pays only delta decode + scatter + re-vote, and the
combined output is byte-identical to a cold run over the concatenated
inputs — the same sum-decomposition the checkpointed ``--incremental``
CLI mode already pins, promoted to the warm serve path.  Failure obeys
the count-bank rule (a seeded job that fails invalidates its entry
whole) and eviction under the LRU byte budget must never corrupt a
re-ingested reference.
"""

import os

import numpy as np
import pytest

from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.io.fasta import render_file
from sam2consensus_tpu.utils.simulate import SimSpec, simulate

jax = pytest.importorskip("jax")

from sam2consensus_tpu.serve import JobSpec, ServeRunner  # noqa: E402
from sam2consensus_tpu.serve import countcache  # noqa: E402


# -- units ----------------------------------------------------------------

def test_parse_budget_grammar():
    pb = countcache.parse_budget
    assert pb(None) == 0
    assert pb("off") == 0
    assert pb("0") == 0
    assert pb("1048576") == 1 << 20
    assert pb("512M") == 512 << 20
    assert pb("2g") == 2 << 30
    assert pb("1.5K") == 1536
    for bad in ("lots", "12Q", "-5", "3 M"):
        with pytest.raises(ValueError):
            pb(bad)


def _state(nbytes, tag="s"):
    from sam2consensus_tpu.encoder.events import InsertionEvents
    from sam2consensus_tpu.utils.checkpoint import CheckpointState

    counts = np.zeros((max(1, nbytes // 24), 6), np.int32)
    return CheckpointState(counts=counts, lines_consumed=0,
                           reads_mapped=0, reads_skipped=0,
                           aligned_bases=0,
                           insertions=InsertionEvents(),
                           source="", sources=[tag])


def test_lru_eviction_under_budget():
    cache = countcache.CountCache(10_000)
    cache.put("a", _state(4_000, "a"))
    cache.put("b", _state(4_000, "b"))
    assert cache.stats()["entries"] == 2
    assert cache.get("a") is not None        # touch: b becomes LRU
    cache.put("c", _state(4_000, "c"))       # evicts b
    assert cache.get("b") is None
    assert cache.get("a") is not None
    assert cache.get("c") is not None
    s = cache.stats()
    assert s["evictions"] == 1 and s["entries"] == 2
    # an entry larger than the whole budget is refused, nothing evicted
    cache.put("huge", _state(50_000, "huge"))
    assert cache.get("huge") is None
    assert cache.stats()["entries"] == 2
    # invalidation drops whole
    assert cache.invalidate("a") is True
    assert cache.invalidate("a") is False
    assert cache.stats()["invalidated"] == 1


def test_reference_key_sensitivity():
    from sam2consensus_tpu.io.sam import Contig

    ref = [Contig("c1", 100), Contig("c2", 200)]
    cfg = RunConfig(backend="jax")
    k0 = countcache.reference_key(ref, cfg, "")
    # vote/render knobs do NOT key (counts are pre-vote state)
    assert countcache.reference_key(
        ref, RunConfig(backend="jax", thresholds=[0.5], fill="N",
                       min_depth=9), "") == k0
    # layout, tenant, and count-relevant encode knobs DO
    assert countcache.reference_key(
        [Contig("c1", 100), Contig("c2", 201)], cfg, "") != k0
    assert countcache.reference_key(ref, cfg, "tenant_a") != k0
    assert countcache.reference_key(
        ref, RunConfig(backend="jax", maxdel=3), "") != k0


# -- serve integration ----------------------------------------------------

@pytest.fixture(scope="module")
def shard_files(tmp_path_factory):
    """Two read shards over ONE reference layout + their concatenation,
    plus a second reference's input (for eviction pressure)."""
    tmp = tmp_path_factory.mktemp("incr")
    kw = dict(n_contigs=2, contig_len=1500, read_len=60,
              contig_len_jitter=0.0, ins_read_rate=0.2,
              del_read_rate=0.2, contig_prefix="ref")
    ta = simulate(SimSpec(n_reads=2400, seed=11, **kw))
    tb = simulate(SimSpec(n_reads=240, seed=99, **kw))
    tr2 = simulate(SimSpec(n_contigs=1, contig_len=900, n_reads=800,
                           read_len=60, contig_len_jitter=0.0, seed=5,
                           contig_prefix="other"))
    paths = {}
    for name, text in (("a", ta), ("b", tb), ("r2", tr2)):
        p = tmp / f"{name}.sam"
        p.write_text(text)
        paths[name] = str(p)
    la, lb = ta.splitlines(True), tb.splitlines(True)
    hdr = [ln for ln in la if ln.startswith("@")]
    body = [ln for ln in la if not ln.startswith("@")] \
        + [ln for ln in lb if not ln.startswith("@")]
    p = tmp / "combined.sam"
    p.write_text("".join(hdr + body))
    paths["combined"] = str(p)
    return paths


def _cfg(incremental, **kw):
    return RunConfig(backend="jax", prefix="t", thresholds=[0.25, 0.5],
                     incremental=incremental, **kw)


def _render(res):
    return {n: render_file(v, 0) for n, v in res.fastas.items()}


def test_serve_incremental_warm_equals_cold(shard_files):
    """The acceptance matrix in one queue: cold absorb (miss), warm
    delta shard (hit, == cold-combined), duplicate re-submit (no-op,
    == cold-combined), with counters/decision/health/exposition/top
    all carrying the cache story."""
    r = ServeRunner(prewarm="off", persistent_cache=False,
                    count_cache="64M")
    try:
        res = r.submit_jobs([
            JobSpec(filename=shard_files["a"], config=_cfg(True),
                    job_id="A"),
            JobSpec(filename=shard_files["b"], config=_cfg(True),
                    job_id="B"),
            JobSpec(filename=shard_files["b"], config=_cfg(True),
                    job_id="Bdup"),
            JobSpec(filename=shard_files["combined"],
                    config=_cfg(False), job_id="COLD"),
        ])
        assert all(x.ok for x in res), [x.error for x in res]
        cold = _render(res[3])
        assert _render(res[1]) == cold           # warm delta == combined
        assert _render(res[2]) == cold           # duplicate adds nothing
        assert res[0].metrics.get("cache/misses") == 1
        assert res[1].metrics.get("cache/hits") == 1
        assert res[2].metrics.get("cache/hits") == 1
        assert res[2].stats.extra.get("incremental_duplicate") \
            == os.path.abspath(shard_files["b"])
        # the decision rode the warm job's manifest ledger
        recs = {d["decision"]: d for d in res[1].manifest["decisions"]}
        assert recs["count_cache"]["chosen"] == "warm"
        assert recs["count_cache"]["inputs"]["entries"] == 1
        # health + exposition + operator top line
        snap = r.health_snapshot()
        assert snap["count_cache"]["hits"] == 2
        assert snap["count_cache"]["entries"] == 1
        from sam2consensus_tpu.observability.telemetry import (
            lint_openmetrics, parse_openmetrics)

        text = r.render_telemetry()
        assert lint_openmetrics(text) == []
        samples = parse_openmetrics(text)
        by_name = {s["name"]: s["value"] for s in samples}
        assert by_name["s2c_cache_hits_total"] == 2
        assert by_name["s2c_cache_entries"] == 1
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "s2c_top", os.path.join(os.path.dirname(__file__), "..",
                                    "tools", "s2c_top.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        frame = "\n".join(mod.render(snap, samples))
        assert "count cache: 1 entry" in frame
        assert "2 hits" in frame
    finally:
        r.close()


def test_eviction_under_pressure_reingest_identity(shard_files):
    """Budget fits ONE entry: alternating references evict each other,
    and a re-ingested (evicted) reference's cold re-absorb is
    byte-identical to its original cached run."""
    # budget sized between one entry (~72K for the 2x1500-position
    # reference) and two, so the second reference must evict the first
    r = ServeRunner(prewarm="off", persistent_cache=False,
                    count_cache="80K")
    try:
        res = r.submit_jobs([
            JobSpec(filename=shard_files["a"], config=_cfg(True),
                    job_id="r1_first"),
            JobSpec(filename=shard_files["r2"], config=_cfg(True),
                    job_id="r2"),
            JobSpec(filename=shard_files["a"], config=_cfg(True),
                    job_id="r1_again"),
        ])
        assert all(x.ok for x in res), [x.error for x in res]
        s = r.count_cache.stats()
        assert s["evictions"] >= 1, s
        # r1 was evicted by r2 -> its re-ingest is a miss, absorbed
        # cold, and must render the bytes the cached run produced
        assert res[2].metrics.get("cache/misses") == 1
        assert _render(res[2]) == _render(res[0])
    finally:
        r.close()


def test_failed_incremental_invalidates_entry(shard_files, tmp_path):
    """The count-bank rule's failure edge: a poison delta shard fails
    its job AND drops the reference's warm entry whole — the next
    submission re-absorbs from scratch rather than inheriting state a
    failed job may have half-applied."""
    bad = tmp_path / "bad.sam"
    hdr = "".join(ln for ln in open(shard_files["a"])
                  if ln.startswith("@"))
    bad.write_text(hdr + "r1\t0\tref0000\t5\t60\t10M\t*\t0\t0\t"
                   "ACGTACGTAZ\t*\n")
    r = ServeRunner(prewarm="off", persistent_cache=False,
                    count_cache="64M")
    try:
        res = r.submit_jobs([
            JobSpec(filename=shard_files["a"], config=_cfg(True),
                    job_id="A"),
            JobSpec(filename=str(bad), config=_cfg(True), job_id="BAD"),
        ])
        assert res[0].ok and not res[1].ok
        s = r.count_cache.stats()
        assert s["entries"] == 0
        assert s["invalidated"] == 1
        # server survives; the reference re-absorbs clean
        res2 = r.submit_jobs([JobSpec(filename=shard_files["a"],
                                      config=_cfg(True), job_id="A2")])
        assert res2[0].ok
        assert res2[0].metrics.get("cache/misses") == 1
        assert _render(res2[0]) == _render(res[0])
    finally:
        r.close()


def test_serve_validate_rejections(shard_files, tmp_path):
    # incremental without the cache: rejected with a pointer
    r = ServeRunner(prewarm="off", persistent_cache=False)
    try:
        with pytest.raises(ValueError, match="count-cache"):
            r.submit_jobs([JobSpec(filename=shard_files["a"],
                                   config=_cfg(True))])
    finally:
        r.close()
    # incremental + journal: two sources of resumable state
    r = ServeRunner(prewarm="off", persistent_cache=False,
                    count_cache="8M",
                    journal_dir=str(tmp_path / "j"))
    try:
        with pytest.raises(ValueError, match="journal"):
            r.submit_jobs([JobSpec(filename=shard_files["a"],
                                   config=_cfg(True))])
    finally:
        r.close()
    # a typo'd budget fails the server start
    with pytest.raises(ValueError, match="count-cache"):
        ServeRunner(prewarm="off", persistent_cache=False,
                    count_cache="lots")


def test_incremental_jobs_never_pack(shard_files):
    """Continuous batching must not pack an incremental job — its
    accumulator seeds from warm state no shared tensor holds."""
    r = ServeRunner(prewarm="off", persistent_cache=False,
                    count_cache="64M", batch="4")
    try:
        entry = {"action": "run", "cfg": _cfg(True),
                 "spec": JobSpec(filename=shard_files["a"],
                                 config=_cfg(True))}
        assert not r.scheduler.eligible(entry)
        entry2 = {"action": "run", "cfg": _cfg(False),
                  "spec": JobSpec(filename=shard_files["a"],
                                  config=_cfg(False))}
        assert r.scheduler.eligible(entry2)
    finally:
        r.close()
