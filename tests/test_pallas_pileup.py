"""The Pallas tile-CSR pileup kernel vs the scatter oracle.

``ops.pallas_pileup`` replaces the retired MXU one-hot-matmul pileup as
the device-resident kernel (PERF.md round 5): rows counting-sorted by
position tile, per-row VMEM histogram accumulation, overhang carried
between tiles in scratch.  These tests pin, in interpret mode on CPU
(SURVEY.md §4), that every layer — the raw kernel, the single-device
strategy, and the sp/dpsp/dp sharded compositions (round-4 verdict #4)
— is cell-exact against the XLA scatter path.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from sam2consensus_tpu.encoder.events import SegmentBatch  # noqa: E402
from sam2consensus_tpu.ops import pallas_pileup as pp  # noqa: E402
from sam2consensus_tpu.ops.pileup import PileupAccumulator  # noqa: E402


def _batch(starts, codes):
    return SegmentBatch(buckets={codes.shape[1]: (starts, codes)},
                        n_reads=len(starts),
                        n_events=int((codes < 6).sum()))


def _ref_counts(total_len, starts, codes):
    acc = PileupAccumulator(total_len, strategy="scatter")
    acc.add(_batch(starts, codes))
    return acc.counts_host()


def _numpy_pileup(total_len, starts, codes):
    counts = np.zeros((total_len, 6), np.int64)
    for s, row in zip(starts, codes):
        for j, c in enumerate(row):
            if c < 6:
                counts[s + j, c] += 1
    return counts


@pytest.mark.parametrize("w,tile", [(32, 2048), (128, 2048), (128, 8192),
                                    (256, 4096)])
def test_kernel_vs_numpy(w, tile):
    rng = np.random.default_rng(hash((w, tile)) % 2**31)
    total_len = 3 * tile + 77            # non-tile-multiple genome
    n = 500
    starts = rng.integers(0, total_len - w, n)
    codes = rng.integers(0, 6, (n, w)).astype(np.uint8)
    codes[rng.random((n, w)) < 0.15] = 255       # PAD cells
    codes[:4] = 255                               # full PAD rows
    starts[:4] = 0
    got = pp.pileup_pallas_host(total_len, starts, codes, tile=tile,
                                interpret=True)
    assert np.array_equal(got, _numpy_pileup(total_len, starts, codes))


def test_kernel_tile_boundaries_and_carry():
    """Rows overhanging every tile boundary exercise the scratch carry."""
    tile, w = 2048, 64
    total_len = 5 * tile
    starts = []
    for t in range(4):
        starts += [(t + 1) * tile - 1,            # maximal overhang
                   (t + 1) * tile - w // 2,       # partial overhang
                   (t + 1) * tile - w,            # flush with boundary
                   (t + 1) * tile]                # next tile's start
    starts.append(total_len - w)                  # genome end
    starts = np.asarray(starts, dtype=np.int64)
    codes = np.tile(np.arange(w) % 6, (len(starts), 1)).astype(np.uint8)
    got = pp.pileup_pallas_host(total_len, starts, codes, tile=tile,
                                interpret=True)
    assert np.array_equal(got, _numpy_pileup(total_len, starts, codes))


def test_kernel_duplicate_positions():
    """Heavy duplicate accumulation (the scatter path's weak spot)."""
    tile, w = 2048, 32
    total_len = tile
    starts = np.full(300, 100, dtype=np.int64)
    codes = np.tile(np.arange(w) % 6, (300, 1)).astype(np.uint8)
    got = pp.pileup_pallas_host(total_len, starts, codes, tile=tile,
                                interpret=True)
    want = _numpy_pileup(total_len, starts, codes)
    assert got[100 + 5, 5] == want[100 + 5, 5] > 0
    assert np.array_equal(got, want)


def test_accumulator_strategy_pallas():
    """PileupAccumulator(strategy='pallas') is cell-exact vs scatter and
    records its strategy; streaming slabs accumulate."""
    rng = np.random.default_rng(11)
    total_len, w = 10_000, 64
    acc = PileupAccumulator(total_len, strategy="pallas")
    all_s, all_c = [], []
    for _ in range(2):
        starts = rng.integers(0, total_len - w, 300).astype(np.int32)
        codes = rng.integers(0, 6, (300, w)).astype(np.uint8)
        codes[rng.random(codes.shape) < 0.2] = 255
        acc.add(_batch(starts, codes))
        all_s.append(starts)
        all_c.append(codes)
    ref = _ref_counts(total_len, np.concatenate(all_s),
                      np.concatenate(all_c))
    assert np.array_equal(acc.counts_host(), ref)
    assert any(k.startswith("pallas_w") for k in acc.strategy_used)


def test_plan_rows_csr_ranges():
    """CSR invariants: rank is a permutation; block ranges cover every
    row's tile; empty tiles get zero blocks."""
    starts = np.array([0, 5000, 5001, 2047, 2048, 9999], dtype=np.int64)
    plan = pp.plan_rows(starts, 32, 10240, tile=2048)
    assert sorted(plan.rank.tolist()) == list(range(len(starts)))
    assert plan.n_tiles == 5
    # tile 1 ([2048, 4096)) holds exactly one row; tile 3 none
    assert plan.blk_n[3] == 0
    assert plan.blk_n[1] >= 1


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 (virtual) devices")
@pytest.mark.parametrize("pileup", ["pallas", "mxu"])
def test_sp_routed_kernel(pileup):
    """sp routing composes with both device kernels (verdict r4 #4)."""
    from sam2consensus_tpu.parallel.mesh import make_mesh
    from sam2consensus_tpu.parallel.sp import PositionShardedConsensus

    rng = np.random.default_rng(3)
    total_len, w = 9000, 64
    starts = rng.integers(0, total_len - w, 700).astype(np.int32)
    codes = rng.integers(0, 6, (700, w)).astype(np.uint8)
    codes[rng.random(codes.shape) < 0.2] = 255
    sp = PositionShardedConsensus(make_mesh(8), total_len, halo=128,
                                  pileup=pileup)
    sp.add(_batch(starts, codes))
    assert np.array_equal(sp.counts_host(),
                          _ref_counts(total_len, starts, codes))
    assert any(k.startswith(f"routed_{pileup}") for k in sp.strategy_used)


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 (virtual) devices")
@pytest.mark.parametrize("pileup", ["pallas", "mxu"])
def test_sp_routed_kernel_boundary_rows(pileup):
    """Block-edge rows through the kernel + halo-exchange path."""
    from sam2consensus_tpu.parallel.mesh import make_mesh
    from sam2consensus_tpu.parallel.sp import PositionShardedConsensus

    total_len, w = 8 * 1024 - 1, 32
    sp = PositionShardedConsensus(make_mesh(8), total_len, halo=64,
                                  pileup=pileup)
    block = sp.block
    edge = []
    for d in range(7):
        edge += [d * block + block - 1, d * block + block - w // 2,
                 d * block]
    edge.append(total_len - w)
    starts = np.asarray(edge, dtype=np.int32)
    codes = np.tile(np.arange(w) % 6, (len(starts), 1)).astype(np.uint8)
    sp.add(_batch(starts, codes))
    assert np.array_equal(sp.counts_host(),
                          _ref_counts(total_len, starts, codes))


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 (virtual) devices")
@pytest.mark.parametrize("pileup", ["pallas", "mxu"])
def test_dpsp_routed_kernel(pileup):
    """dpsp routing composes with both device kernels (verdict r4 #4)."""
    from sam2consensus_tpu.parallel.dpsp import ProductShardedConsensus
    from sam2consensus_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(4)
    total_len, w = 9000, 64
    starts = rng.integers(0, total_len - w, 700).astype(np.int32)
    codes = rng.integers(0, 6, (700, w)).astype(np.uint8)
    codes[rng.random(codes.shape) < 0.2] = 255
    acc = ProductShardedConsensus(make_mesh(8), total_len, halo=128,
                                  pileup=pileup)
    acc.add(_batch(starts, codes))
    assert np.array_equal(acc.counts_host(),
                          _ref_counts(total_len, starts, codes))
    assert any(k.startswith(f"dpsp_{pileup}") for k in acc.strategy_used)


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 (virtual) devices")
def test_dp_explicit_pallas():
    """dp's even-chunk layout drives the kernel over the full axis."""
    from sam2consensus_tpu.parallel.dp import ShardedConsensus
    from sam2consensus_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(5)
    total_len, w = 9000, 64
    starts = rng.integers(0, total_len - w, 600).astype(np.int32)
    codes = rng.integers(0, 6, (600, w)).astype(np.uint8)
    acc = ShardedConsensus(make_mesh(8), total_len, pileup="pallas")
    acc.add(_batch(starts, codes))
    assert np.array_equal(acc.counts_host(),
                          _ref_counts(total_len, starts, codes))
    assert any(k.startswith("pallas_w") for k in acc.strategy_used)


def test_backend_end_to_end_pallas():
    """CLI-level byte identity: --pileup pallas vs the CPU oracle."""
    from sam2consensus_tpu.backends.cpu import CpuBackend
    from sam2consensus_tpu.backends.jax_backend import JaxBackend
    from sam2consensus_tpu.config import RunConfig
    from sam2consensus_tpu.io.sam import ReadStream, opener, read_header
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate
    import io

    text = simulate(SimSpec(n_contigs=3, contig_len=400, n_reads=500,
                            read_len=60, ins_read_rate=0.1,
                            del_read_rate=0.1, seed=21))

    def run(backend, cfg):
        handle = io.StringIO(text) if cfg.backend == "cpu" \
            else io.BytesIO(text.encode())
        contigs, _n, first = read_header(handle)
        return backend.run(contigs, ReadStream(handle, first), cfg)

    cpu = run(CpuBackend(), RunConfig(prefix="t", backend="cpu"))
    jx = run(JaxBackend(), RunConfig(prefix="t", backend="jax",
                                     pileup="pallas", shards=1))
    assert jx.fastas == cpu.fastas


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 (virtual) devices")
def test_sp_mxu_skew_fallback_multi_slice_no_double_count(monkeypatch):
    """An MXU skew fallback on a LATER row slice must not leave earlier
    slices' counts committed and then rerun the whole slab via scatter
    (round-5 review finding: plan-all-before-execute)."""
    from sam2consensus_tpu.ops import pileup as pileup_mod
    from sam2consensus_tpu.parallel.mesh import make_mesh
    from sam2consensus_tpu.parallel.sp import PositionShardedConsensus

    # shrink the slice budget so the routed grid spans multiple slices
    monkeypatch.setattr(pileup_mod, "SCATTER_CELL_BUDGET", 64 * 64)
    import sam2consensus_tpu.parallel.sp as sp_mod
    import sam2consensus_tpu.parallel.dpsp as dpsp_mod
    assert sp_mod.iter_row_slices is pileup_mod.iter_row_slices
    assert dpsp_mod.iter_row_slices is pileup_mod.iter_row_slices

    total_len, w = 60_000, 64
    rng = np.random.default_rng(6)
    sp = PositionShardedConsensus(make_mesh(8), total_len, halo=128,
                                  pileup="mxu")
    block = sp.block
    # device 0 gets 128 rows all at ONE position; devices 1-7 get 64
    # scattered rows each.  Slice 1 (64 rows/device) passes the blowup
    # gate (512 real rows spread out); slice 2 holds ONLY device 0's
    # remaining 64 concentrated rows -> 8 devices x 4 tiles x E=65
    # slots / 64 real rows > 16 -> the gate trips on the LATER slice
    starts = [np.full(128, 5, dtype=np.int32)]
    for d in range(1, 8):
        starts.append(rng.integers(d * block, (d + 1) * block - w,
                                   64).astype(np.int32))
    starts = np.concatenate(starts)
    codes = np.tile(np.arange(w) % 6, (len(starts), 1)).astype(np.uint8)
    sp.add(_batch(starts, codes))
    # skew fell back: the whole slab must ride scatter EXACTLY once
    assert any(k.startswith("routed_w") for k in sp.strategy_used), \
        sp.strategy_used
    assert np.array_equal(sp.counts_host(),
                          _ref_counts(total_len, starts, codes))
