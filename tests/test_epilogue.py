"""Device-resident epilogue (ISSUE 13): fused vote→IUPAC→FASTA on
device, donated count buffers, and the d2h accounting choke point.

The tentpole's correctness contract is the byte-identity matrix: with
the epilogue device-routed (fill substituted inside the vote's emit
select, per-(T, C) dash totals packed into the tail buffer) the FASTA
output must equal the CPU oracle's across the threshold grid ×
min_depth × output encodings × fills — including fills the device
CANNOT represent (multi-char, outside the packed5 symbol space), which
must fall back to the host epilogue and still match.
"""

import io
import os

import numpy as np
import pytest

from sam2consensus_tpu.backends.cpu import CpuBackend
from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.io.fasta import render_file
from sam2consensus_tpu.io.sam import iter_records, read_header
from sam2consensus_tpu.utils.simulate import SimSpec, simulate

jax = pytest.importorskip("jax")

from sam2consensus_tpu.backends.jax_backend import JaxBackend  # noqa: E402


def _run(text, backend, cfg):
    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    res = backend.run(contigs, iter_records(handle, first), cfg)
    return {n: render_file(r, 0) for n, r in res.fastas.items()}, res.stats


@pytest.fixture(scope="module")
def ins_heavy_text():
    """Multi-contig, insertion/deletion-heavy fixture: exercises the
    splice path, lowercase IUPAC calls, and the empty-drop gates."""
    return simulate(SimSpec(n_contigs=3, contig_len=2500, n_reads=2500,
                            read_len=60, ins_read_rate=0.3,
                            del_read_rate=0.3, seed=907))


# -- units ----------------------------------------------------------------

def test_device_fill_code_resolution():
    from sam2consensus_tpu.constants import SYM32_ASCII
    from sam2consensus_tpu.ops.vote import device_fill_code

    assert device_fill_code("-", "ascii") == ord("-")
    assert device_fill_code("N", "ascii") == ord("N")
    assert device_fill_code("\xc8", "ascii") == 0xC8   # any latin-1
    assert device_fill_code("xy", "ascii") is None     # multi-char
    assert device_fill_code("ሴ", "ascii") is None  # non-latin
    # code5: only the 32-symbol vote alphabet fits the packed planes
    assert device_fill_code("-", "code5") == 1
    assert SYM32_ASCII[device_fill_code("N", "code5")] == ord("N")
    assert device_fill_code("x", "code5") is None      # not in SYM32
    assert device_fill_code("xy", "code5") is None


def test_contig_dash_counts_matches_numpy():
    import jax.numpy as jnp

    from sam2consensus_tpu.ops import fused

    rng = np.random.default_rng(3)
    syms = rng.choice(
        np.frombuffer(b"-ACGTNmrwn", np.uint8), size=(3, 1000))
    offsets = np.array([0, 120, 120, 777, 1000], dtype=np.int32)
    got = np.asarray(fused.contig_dash_counts(
        jnp.asarray(syms), jnp.asarray(offsets), ord("-")))
    want = np.stack([
        [(syms[t, offsets[c]:offsets[c + 1]] == ord("-")).sum()
         for c in range(4)] for t in range(3)])
    assert np.array_equal(got, want)


def test_donated_tail_invalidates_cached_upload():
    """Donating the HostPileupAccumulator's cached device copy must
    drop the cache (the buffer is dead), and the re-upload on the next
    call must produce identical bytes — the retry-soundness contract."""
    import jax.numpy as jnp

    from sam2consensus_tpu.backends.jax_backend import _fused_tail_call
    from sam2consensus_tpu.ops import fused
    from sam2consensus_tpu.ops.cutoff import encode_thresholds
    from sam2consensus_tpu.ops.pileup import HostPileupAccumulator

    acc = HostPileupAccumulator(64)
    counts = np.zeros((64, 6), np.int32)
    counts[:32, 1] = 5
    counts[5, 0] = 9
    acc.set_counts(counts)
    thr = jnp.asarray(encode_thresholds([0.25]))
    offs = jnp.asarray(np.array([0, 64], np.int32))
    _ = acc.counts
    assert acc._device_counts is not None
    out1 = np.asarray(_fused_tail_call(
        fused.vote_packed_simple, fused.vote_packed_simple_donated,
        True, acc, acc.counts, thr, offs, 1, None, ord("-"), True))
    assert acc._device_counts is None        # invalidated post-donation
    out2 = np.asarray(_fused_tail_call(
        fused.vote_packed_simple, fused.vote_packed_simple_donated,
        True, acc, acc.counts, thr, offs, 1, None, ord("-"), True))
    assert np.array_equal(out1, out2)


def test_d2h_choke_point_bills_fetches(monkeypatch):
    """Every d2h route bills wire/d2h_bytes at the one choke point —
    including the count-tensor pull (counts_host) that previously
    escaped the accounting — and link-free fetches bill nothing."""
    from sam2consensus_tpu import observability as obs
    from sam2consensus_tpu import wire

    robs = obs.start_run()
    try:
        reg = obs.metrics()
        arr = np.arange(1000, dtype=np.int32)
        wire.account_d2h(123, link_free=True)
        assert reg.value("wire/d2h_bytes") == 0
        got = wire.fetch_d2h(arr, link_free=False)
        assert np.array_equal(got, arr)
        assert reg.value("wire/d2h_bytes") == arr.nbytes
        # the device accumulator's counts_host pull (checkpoint /
        # demotion / paranoid route) bills through the same point;
        # pretend the default backend has a real link
        monkeypatch.setattr(wire, "link_free_default", lambda: False)
        from sam2consensus_tpu.ops.pileup import PileupAccumulator

        acc = PileupAccumulator(100, strategy="scatter")
        before = reg.value("wire/d2h_bytes")
        _ = acc.counts_host()
        assert reg.value("wire/d2h_bytes") >= before + 100 * 6 * 4
    finally:
        obs.finish_run(robs)


# -- the byte-identity matrix --------------------------------------------

@pytest.mark.parametrize("enc", ["dense", "sparse", "packed5"])
@pytest.mark.parametrize("thresholds", [[0.25], [0.25, 0.5, 0.75]])
def test_epilogue_matrix_encodings(ins_heavy_text, monkeypatch, enc,
                                   thresholds):
    monkeypatch.setenv("S2C_TAIL_ENCODING", enc)
    cfg = RunConfig(prefix="t", thresholds=thresholds, min_depth=2,
                    shards=1)
    out_cpu, _ = _run(ins_heavy_text, CpuBackend(), cfg)
    out_jax, st = _run(ins_heavy_text, JaxBackend(), cfg)
    assert out_jax == out_cpu
    # the epilogue must actually have run on the (XLA) device side
    assert st.extra.get("epilogue/device_tails") == 1, st.extra


@pytest.mark.parametrize("fill,enc,expect_device", [
    ("N", "packed5", True),    # in the 32-symbol space: device
    ("x", "packed5", False),   # outside SYM32: host fallback
    ("x", "dense", True),      # dense ships raw bytes: device
    ("xy", "dense", False),    # multi-char: host fallback
])
def test_epilogue_matrix_fills(ins_heavy_text, monkeypatch, fill, enc,
                               expect_device):
    monkeypatch.setenv("S2C_TAIL_ENCODING", enc)
    cfg = RunConfig(prefix="t", thresholds=[0.25, 0.5], fill=fill,
                    min_depth=3, shards=1)
    out_cpu, _ = _run(ins_heavy_text, CpuBackend(), cfg)
    out_jax, st = _run(ins_heavy_text, JaxBackend(), cfg)
    assert out_jax == out_cpu
    key = "epilogue/device_tails" if expect_device \
        else "epilogue/host_tails"
    assert st.extra.get(key) == 1, st.extra


def test_epilogue_forced_host_identical(ins_heavy_text, monkeypatch):
    """S2C_EPILOGUE=host pins the classic host render; bytes match."""
    monkeypatch.setenv("S2C_TAIL_ENCODING", "dense")
    monkeypatch.setenv("S2C_EPILOGUE", "host")
    cfg = RunConfig(prefix="t", thresholds=[0.25], shards=1)
    out_cpu, _ = _run(ins_heavy_text, CpuBackend(), cfg)
    out_jax, st = _run(ins_heavy_text, JaxBackend(), cfg)
    assert out_jax == out_cpu
    assert st.extra.get("epilogue/host_tails") == 1


def test_epilogue_env_typo_fails(ins_heavy_text, monkeypatch):
    monkeypatch.setenv("S2C_EPILOGUE", "dev")
    cfg = RunConfig(prefix="t", thresholds=[0.25], shards=1)
    with pytest.raises(ValueError, match="S2C_EPILOGUE"):
        _run(ins_heavy_text, JaxBackend(), cfg)


def test_epilogue_forced_device_rejects_unrepresentable_fill(
        ins_heavy_text, monkeypatch):
    """S2C_EPILOGUE=device must not silently measure the host path: an
    unrepresentable fill is a loud config conflict, not a fallback."""
    monkeypatch.setenv("S2C_EPILOGUE", "device")
    cfg = RunConfig(prefix="t", thresholds=[0.25], fill="xy", shards=1)
    with pytest.raises(ValueError, match="not.*representable"):
        _run(ins_heavy_text, JaxBackend(), cfg)
    # representable fill: forced device works and matches the oracle
    monkeypatch.setenv("S2C_TAIL_ENCODING", "dense")
    cfg = RunConfig(prefix="t", thresholds=[0.25], fill="N", shards=1)
    out_cpu, _ = _run(ins_heavy_text, CpuBackend(), cfg)
    out_jax, st = _run(ins_heavy_text, JaxBackend(), cfg)
    assert out_jax == out_cpu
    assert st.extra.get("epilogue/device_tails") == 1


def test_epilogue_donated_end_to_end(ins_heavy_text, monkeypatch):
    """Forced-on donation (a cpu no-op, but the code path is real):
    identical bytes, and the retry policy still sound after donation."""
    monkeypatch.setenv("S2C_TAIL_ENCODING", "dense")
    monkeypatch.setenv("S2C_DONATE_COUNTS", "on")
    cfg = RunConfig(prefix="t", thresholds=[0.25, 0.5], shards=1)
    out_cpu, _ = _run(ins_heavy_text, CpuBackend(), cfg)
    out_jax, st = _run(ins_heavy_text, JaxBackend(), cfg)
    assert out_jax == out_cpu


def test_epilogue_decision_in_ledger(ins_heavy_text, monkeypatch,
                                     tmp_path):
    """The epilogue placement is a ledger decision in the manifest,
    alternatives priced, measured joined against the render phase."""
    monkeypatch.setenv("S2C_TAIL_ENCODING", "dense")
    cfg = RunConfig(prefix="t", thresholds=[0.25], shards=1,
                    metrics_out=str(tmp_path / "m.jsonl"))
    _out, _st = _run(ins_heavy_text, JaxBackend(), cfg)
    import json

    man = json.load(open(tmp_path / "m.jsonl.manifest.json"))
    recs = {d["decision"]: d for d in man["decisions"]}
    assert recs["epilogue"]["chosen"] == "device"
    assert set(recs["epilogue"]["alternatives"]) == {"device", "host"}
    assert "sec" in recs["epilogue"]["predicted"]
