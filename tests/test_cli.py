"""CLI end-to-end tests: files on disk, names, wrapping, gzip, metrics."""

import gzip
import json
import os

from sam2consensus_tpu.cli import main
from sam2consensus_tpu.utils.simulate import sam_text, write_sam


def _fixture(tmp_path, name="sample.sam", gz=False):
    text = sam_text(
        [("geneA", 10), ("geneB", 6), ("empty", 4)],
        [
            ("geneA", 1, "4M", "ACGT"),
            ("geneA", 3, "2M", "GT"),
            ("geneB", 1, "3M", "TTT"),
            ("geneB", 1, "3M", "TTT"),
        ])
    path = str(tmp_path / (name + (".gz" if gz else "")))
    return write_sam(text, path)


def test_end_to_end_files(tmp_path):
    sam = _fixture(tmp_path)
    out = str(tmp_path / "out")
    assert main(["-i", sam, "-o", out, "--quiet"]) == 0
    files = sorted(os.listdir(out))
    assert files == ["geneA__sample.fasta", "geneB__sample.fasta"]
    content = open(os.path.join(out, "geneA__sample.fasta")).read()
    assert content == (">sample|c25 reference:geneA coverage:0.6 length:4"
                       " consensus_threshold:25%\nACGT------\n")


def test_gzip_input(tmp_path):
    sam = _fixture(tmp_path, gz=True)
    out = str(tmp_path / "out")
    assert main(["-i", sam, "-o", out, "--quiet"]) == 0
    assert "geneA__sample.fasta" in os.listdir(out)


def test_wrapping(tmp_path):
    sam = _fixture(tmp_path)
    out = str(tmp_path / "out")
    main(["-i", sam, "-o", out, "-n", "3", "--quiet"])
    content = open(os.path.join(out, "geneA__sample.fasta")).read()
    assert content.endswith("\nACG\nT--\n---\n-\n")


def test_multi_threshold_single_file(tmp_path):
    sam = _fixture(tmp_path)
    out = str(tmp_path / "out")
    main(["-i", sam, "-o", out, "-c", "0.25,0.75", "--quiet"])
    content = open(os.path.join(out, "geneB__sample.fasta")).read()
    assert content.count(">") == 2
    assert "|c25 " in content and "|c75 " in content


def test_prefix_flag(tmp_path):
    sam = _fixture(tmp_path)
    out = str(tmp_path / "out")
    main(["-i", sam, "-o", out, "-p", "xx", "--quiet"])
    assert "geneA__xx.fasta" in os.listdir(out)


def test_json_metrics(tmp_path):
    sam = _fixture(tmp_path)
    out = str(tmp_path / "out")
    metrics_path = str(tmp_path / "m.json")
    main(["-i", sam, "-o", out, "--quiet", "--json-metrics", metrics_path])
    m = json.loads(open(metrics_path).read())
    assert m["reads_mapped"] == 4
    assert m["references"] == 3
    assert m["references_with_output"] == 2
    assert m["backend"] == "cpu"


def test_py2_compat_maxdel(tmp_path):
    text = sam_text([("r", 8)], [("r", 1, "2M3D2M", "ACGT")])
    sam = write_sam(text, str(tmp_path / "d.sam"))
    out1 = str(tmp_path / "o1")
    out2 = str(tmp_path / "o2")
    # fixed semantics: -d 2 filters the 3-gap deletion
    main(["-i", sam, "-o", out1, "-d", "2", "--quiet"])
    c1 = open(os.path.join(out1, "r__d.fasta")).read()
    assert "coverage:0.5" in c1
    # py2-compat: an explicit -d disables the gate entirely (quirk 1)
    main(["-i", sam, "-o", out2, "-d", "2", "--py2-compat", "--quiet"])
    c2 = open(os.path.join(out2, "r__d.fasta")).read()
    assert "coverage:0.88" in c2


def test_jax_backend_cli_identical_output(tmp_path):
    sam = _fixture(tmp_path)
    out_cpu = str(tmp_path / "oc")
    out_jax = str(tmp_path / "oj")
    assert main(["-i", sam, "-o", out_cpu, "--quiet"]) == 0
    assert main(["-i", sam, "-o", out_jax, "--quiet", "--backend", "jax"]) == 0
    import filecmp
    match, mismatch, errors = filecmp.cmpfiles(
        out_cpu, out_jax, os.listdir(out_cpu), shallow=False)
    assert mismatch == [] and errors == []
    assert sorted(os.listdir(out_cpu)) == sorted(os.listdir(out_jax))


def test_shards_requires_jax_backend(tmp_path):
    sam = _fixture(tmp_path)
    import pytest
    with pytest.raises(SystemExit, match="requires --backend jax"):
        main(["-i", sam, "-o", str(tmp_path / "o"), "--shards", "4", "--quiet"])


def test_nonpositive_threshold_rejected(tmp_path):
    # the reference crashes on t <= 0 (amb[""] KeyError at
    # sam2consensus.py:367); the CLI rejects it with a clear error instead
    sam = _fixture(tmp_path)
    import pytest
    for bad in ("0", "-0.5", "0.25,0", "nan", "inf", "2e306"):
        with pytest.raises(SystemExit, match="must be finite"):
            main(["-i", sam, "-o", str(tmp_path / "o"), "-c", bad, "--quiet"])
    for bad in ("abc", "0.25,", ""):
        with pytest.raises(SystemExit, match="could not parse"):
            main(["-i", sam, "-o", str(tmp_path / "o"), "-c", bad, "--quiet"])


def test_xla_bridge_private_surface_still_exists():
    """_accelerator_client_live falls back to jax._src.xla_bridge's
    ``backends_are_initialized()`` + ``_backends`` cache (after probing
    the public jax.extend.backend namespace).  Pin the private surface:
    if a jax upgrade drops either attribute, fail HERE loudly instead
    of silently flipping CPU-only runs onto the conservative os._exit
    branch (ADVICE r5 #3)."""
    from jax._src import xla_bridge

    assert isinstance(xla_bridge._backends, dict)
    assert callable(getattr(xla_bridge, "backends_are_initialized", None))


def test_accelerator_client_live_cpu_only(monkeypatch):
    """A CPU-only process must exit normally (no os._exit): with only
    the cpu backend initialized, _accelerator_client_live is False; the
    S2C_SAFE_EXIT override flips it both ways.  Skipped when the
    process has a real accelerator client (e.g. the suite run without
    conftest's cpu pin on the TPU rig) — the conservative True is
    correct there."""
    import jax
    from jax._src import xla_bridge

    jax.devices()                     # ensure a backend client exists
    if any(p != "cpu" for p in xla_bridge._backends):
        import pytest

        pytest.skip("non-cpu accelerator client initialized")
    from sam2consensus_tpu.cli import _accelerator_client_live

    monkeypatch.delenv("S2C_SAFE_EXIT", raising=False)
    assert _accelerator_client_live() is False
    monkeypatch.setenv("S2C_SAFE_EXIT", "1")
    assert _accelerator_client_live() is True
    monkeypatch.setenv("S2C_SAFE_EXIT", "0")
    assert _accelerator_client_live() is False
