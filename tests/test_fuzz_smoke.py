"""Tier-1 slice of the differential ingest fuzzer (tools/fuzz_ingest.py).

The committed campaign artifact (campaign/fuzz_ingest_r06_*.jsonl)
carries the full run; this seeded smoke slice keeps the guarantee live
in tier-1: ~200 mutants over the fixture corpus, every mutant through
the strict + tolerant rung matrices (serial / byte-shard / streaming
gzip / pure-python, plus the BAM leg on every 4th), asserting 0
interpreter crashes, 0 hangs, 0 strict/tolerant rung divergences.
"""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(ROOT, "tools", "fuzz_ingest.py")


def test_fuzz_ingest_smoke(tmp_path):
    out = str(tmp_path / "fuzz.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, TOOL, "--smoke", "--no-progress", "--out", out],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
    assert proc.returncode == 0, \
        f"fuzz smoke found issues:\n{proc.stdout}\n{proc.stderr}"
    rows = [json.loads(ln) for ln in open(out)]
    summary = rows[-1]
    assert summary["kind"] == "summary"
    assert summary["schema"] == "s2c-fuzz-ingest/1"
    assert summary["mode"] == "smoke"
    assert summary["trials"] == 200
    assert (summary["crashes"], summary["hangs"],
            summary["divergences"]) == (0, 0, 0)
    assert summary["bam_legs"] > 0
    # the mutator actually exercised the flavor space
    assert len(summary["flavors"]) >= 6


def test_fuzz_ingest_network_smoke(tmp_path):
    """Tier-1 slice of the network-framing leg: the streaming-session
    front door under malformed chunked framing, truncated bodies,
    oversize declarations, slow trickle and mid-wave disconnects —
    the server must answer the taxonomy (400/408/413/422), never hang,
    and the journal audit must stay 0-lost/0-duplicated throughout."""
    out = str(tmp_path / "fuzz_net.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, TOOL, "--network", "--smoke", "--no-progress",
         "--out", out],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
    assert proc.returncode == 0, \
        f"network fuzz found issues:\n{proc.stdout}\n{proc.stderr}"
    rows = [json.loads(ln) for ln in open(out)]
    summary = rows[-1]
    assert summary["kind"] == "summary"
    assert summary["schema"] == "s2c-fuzz-ingest-net/1"
    assert summary["mode"] == "smoke"
    assert (summary["crashes"], summary["hangs"],
            summary["divergences"]) == (0, 0, 0)
    assert summary["flavors"] >= 8


def test_fuzz_harness_catches_a_planted_divergence(tmp_path):
    """The harness itself must be able to FAIL: a mutant with a bare
    NUL in SEQ must register as bad_alphabet on every rung — feed the
    checker a hand-built divergent pair via its own rung drivers and
    assert the comparison logic flags real disagreements (guards
    against the fuzzer rotting into a green rubber stamp)."""
    sys.path.insert(0, ROOT)
    from tools.fuzz_ingest import check_text_mutant

    # a clean mutant: no divergences
    ok = (b"@SQ\tSN:c1\tLN:100\n"
          b"r1\t0\tc1\t1\t60\t4M\t*\t0\t0\tACGT\t*\n")
    assert check_text_mutant(ok, str(tmp_path)) == []
    # one malformed record: still no divergence — every rung agrees
    # (strict: same typed first error; tolerant: same quarantine)
    bad = (b"@SQ\tSN:c1\tLN:100\n"
           b"r1\t0\tc1\t1\t60\t4M\t*\t0\t0\tAC\x00T\t*\n"
           b"r2\t0\tc1\t3\t60\t4M\t*\t0\t0\tACGT\t*\n")
    assert check_text_mutant(bad, str(tmp_path)) == []


@pytest.mark.slow
def test_fuzz_ingest_full_leg(tmp_path):
    """The campaign-sized leg (runs in step 9 of tools/tpu_campaign.sh;
    here for -m slow completeness)."""
    out = str(tmp_path / "fuzz_full.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, TOOL, "--trials", "1200", "--no-progress",
         "--out", out],
        capture_output=True, text=True, timeout=1800, env=env, cwd=ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr
