"""Continuous batching (serve/scheduler.py + serve/packing.py): the
cross-job slab-packing pins.

* packed-vs-serial byte identity across the small-job fixture families
  (short/deep, multi-contig target-capture, gzip container, py2-compat,
  mixed thresholds forcing the per-member extraction tail) at batch
  sizes 1/4/8 — the tentpole's exactness claim;
* a fault injected inside the packed dispatch demotes ONLY that batch
  back to the serial path (co-tenants' outputs stay byte-identical,
  ``batch/demotions`` counted);
* SIGKILL mid-batch under a journal: the restarted queue replays only
  uncommitted members, zero lost / zero duplicated, byte-identical;
* a tenant burning its SLO objective flushes the filling batch
  immediately (no ``--batch-window`` wait);
* default quarantine sidecars stay unique under packed (concurrent-
  commit) execution;
* the ``s2c_batch_*`` exposition family renders lint-clean and the
  batch policy decision lands in every packed job's manifest.
"""

import gzip
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.io.fasta import render_file
from sam2consensus_tpu.serve import JobSpec, journal as sjournal
from sam2consensus_tpu.serve.scheduler import parse_batch_mode
from sam2consensus_tpu.utils.simulate import SimSpec, simulate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_persistent_cache(monkeypatch):
    monkeypatch.setenv("S2C_JIT_CACHE", "")


def _sim(tmp, name, seed, contig_len=3000, n_reads=600, n_contigs=1,
         gz=False, **kw):
    spec = SimSpec(n_contigs=n_contigs, contig_len=contig_len,
                   n_reads=n_reads, read_len=100, contig_len_jitter=0.0,
                   seed=seed, contig_prefix=f"bt{seed}", **kw)
    path = os.path.join(str(tmp), name)
    text = simulate(spec)
    if gz:
        with gzip.open(path, "wb") as fh:
            fh.write(text.encode("ascii"))
    else:
        with open(path, "w") as fh:
            fh.write(text)
    return path


def _runner(**kw):
    from sam2consensus_tpu.serve import ServeRunner

    kw.setdefault("prewarm", "off")
    kw.setdefault("persistent_cache", False)
    return ServeRunner(**kw)


def _rendered(res):
    return {n: render_file(r, 0) for n, r in res.fastas.items()}


def _family_specs(tmp):
    """The small-job fixture families, one queue: short/deep phix-class,
    multi-contig target-capture class, a gzip container, a py2-compat
    job, and a pair with different thresholds (tail-incompatible with
    the rest, so the per-member extraction tail is exercised inside the
    same batch run)."""
    base = dict(backend="jax")
    specs = []
    for k, (name, seed, kw, cfg_kw) in enumerate([
            ("phix0.sam", 11, {}, {}),
            ("phix1.sam", 12, {"n_reads": 900}, {}),
            ("cap0.sam", 13, {"n_contigs": 6, "contig_len": 700}, {}),
            ("cap1.sam", 14, {"n_contigs": 4, "contig_len": 900}, {}),
            ("gz0.sam.gz", 15, {"gz": True}, {}),
            ("py2.sam", 16, {}, {"py2_compat": True, "maxdel": None}),
            ("thr0.sam", 17, {}, {"thresholds": [0.25, 0.5]}),
            ("thr1.sam", 18, {}, {"thresholds": [0.25, 0.5]}),
    ]):
        path = _sim(tmp, name, seed, **kw)
        specs.append(JobSpec(filename=path,
                             config=RunConfig(**base, **cfg_kw),
                             job_id=f"fam{k}"))
    return specs


# -- policy parsing --------------------------------------------------------
def test_parse_batch_mode():
    assert parse_batch_mode("off") == ("off", 1)
    assert parse_batch_mode(None) == ("off", 1)
    assert parse_batch_mode("0") == ("off", 1)
    assert parse_batch_mode("1") == ("off", 1)
    assert parse_batch_mode("6") == ("fixed", 6)
    mode, n = parse_batch_mode("auto")
    assert mode == "auto" and n >= 2
    with pytest.raises(ValueError):
        parse_batch_mode("many")
    with pytest.raises(ValueError):
        parse_batch_mode("-3")


def test_serve_cli_rejects_bad_batch():
    from sam2consensus_tpu.cli import serve_main

    with pytest.raises(SystemExit):
        serve_main(["-i", "x.sam", "--batch", "bogus"])


# -- the byte-identity matrix ----------------------------------------------
@pytest.mark.parametrize("batch", ["1", "4", "8"])
def test_packed_vs_serial_byte_identity_matrix(tmp_path, batch):
    """Every fixture family through batch sizes 1/4/8 equals the serial
    path byte-for-byte; packed jobs carry the serve_batch decision in
    their manifest and the serve/batch counters in their metrics."""
    specs = _family_specs(tmp_path)
    rs = _runner(batch="off")
    serial = rs.submit_jobs(specs)
    rs.close()
    rp = _runner(batch=batch)
    packed = rp.submit_jobs(specs)
    n_packed = rp.registry.value("batch/packed_jobs")
    rp.close()
    assert all(r.ok for r in serial), [r.error for r in serial]
    assert all(r.ok for r in packed), [r.error for r in packed]
    for a, b in zip(packed, serial):
        assert _rendered(a) == _rendered(b), a.job_id
    if batch == "1":
        assert n_packed == 0                  # 1 == off
        return
    assert n_packed >= 2
    for res in packed:
        if not res.metrics.get("serve/batched"):
            continue
        assert res.metrics.get("serve/batch_jobs", 0) >= 2
        assert res.metrics.get("serve/batch_wall_sec", 0) > 0
        decisions = [d for d in (res.manifest or {}).get(
            "decisions", []) if d.get("decision") == "serve_batch"]
        assert decisions, f"{res.job_id}: no serve_batch decision"
        d = decisions[0]
        assert d["measured"].get("jobs_per_sec", 0) > 0
        assert "occupancy" in d["inputs"]


def test_packed_matches_independent_cold_runs(tmp_path):
    """Packed outputs equal fresh cold-backend runs (not just the warm
    serial path) — the scheduler cannot be 'consistently wrong'."""
    from sam2consensus_tpu.backends.jax_backend import JaxBackend
    from sam2consensus_tpu.io.sam import ReadStream, opener, read_header

    specs = _family_specs(tmp_path)[:4]
    rp = _runner(batch="4")
    packed = rp.submit_jobs(specs)
    rp.close()
    for spec, res in zip(specs, packed):
        h = opener(spec.filename, binary=True)
        contigs, _n, first = read_header(h)
        cold = JaxBackend().run(contigs, ReadStream(h, first),
                                spec.config)
        h.close()
        assert _rendered(res) == {
            n: render_file(r, 0) for n, r in cold.fastas.items()}


# -- resilience ------------------------------------------------------------
def test_fault_in_packed_dispatch_demotes_batch_only(tmp_path):
    """An injected device fault inside the packed dispatch discards the
    shared tensor and re-runs every member through the serial path —
    outputs byte-identical, co-members uncorrupted, demotion counted."""
    paths = [_sim(tmp_path, f"f{i}.sam", 40 + i) for i in range(4)]

    def specs(fault_first):
        out = []
        for k, p in enumerate(paths):
            cfg = RunConfig(backend="jax")
            if fault_first and k == 0:
                # the scheduler configures the packed dispatch's
                # injector from the FIRST member's spec; one counted
                # rpc fault fires inside the shared dispatch
                cfg = RunConfig(backend="jax",
                                fault_inject="pileup_dispatch:rpc:0:1")
            out.append(JobSpec(filename=p, config=cfg, job_id=f"f{k}"))
        return out

    rs = _runner(batch="off")
    want = [_rendered(r) for r in rs.submit_jobs(specs(False))]
    rs.close()
    rp = _runner(batch="4")
    got = rp.submit_jobs(specs(True))
    assert rp.registry.value("batch/demotions") == 1
    assert rp.registry.value("batch/packed_jobs") == 0
    rp.close()
    assert all(r.ok for r in got), [r.error for r in got]
    assert [_rendered(r) for r in got] == want


def test_member_decode_failure_fails_alone(tmp_path):
    """A poison member (strict decode error) fails alone; co-members
    stay packed and byte-identical."""
    paths = [_sim(tmp_path, f"p{i}.sam", 50 + i) for i in range(3)]
    bad = os.path.join(str(tmp_path), "bad.sam")
    with open(paths[1]) as fh:
        text = fh.read()
    lines = text.splitlines()
    body = [ln for ln in lines if not ln.startswith("@")]
    hdr = [ln for ln in lines if ln.startswith("@")]
    f = body[0].split("\t")
    f[3] = "999999"                       # way out of bounds: IndexError
    with open(bad, "w") as fh:
        fh.write("\n".join(hdr + [("\t".join(f))] + body[1:]) + "\n")
    specs = [JobSpec(filename=paths[0], config=RunConfig(backend="jax"),
                     job_id="ok0"),
             JobSpec(filename=bad, config=RunConfig(backend="jax"),
                     job_id="poison"),
             JobSpec(filename=paths[2], config=RunConfig(backend="jax"),
                     job_id="ok1")]
    rs = _runner(batch="off")
    serial = rs.submit_jobs([specs[0], specs[2]])
    rs.close()
    rp = _runner(batch="3")
    packed = rp.submit_jobs(specs)
    rp.close()
    assert packed[0].ok and packed[2].ok
    assert not packed[1].ok
    assert "IndexError" in packed[1].error
    assert _rendered(packed[0]) == _rendered(serial[0])
    assert _rendered(packed[2]) == _rendered(serial[1])


# -- SIGKILL mid-batch under a journal -------------------------------------
def _serve_cmd(inputs, outdir, jdir, batch):
    cmd = [sys.executable, "-m", "sam2consensus_tpu.cli", "serve"]
    for p in inputs:
        cmd += ["-i", p]
    cmd += ["-o", outdir, "--journal", jdir, "--batch", batch,
            "--quiet"]
    return cmd


def _committed(jdir):
    n = 0
    for name in os.listdir(jdir) if os.path.isdir(jdir) else []:
        if name.startswith("ev-") and name.endswith(".json"):
            try:
                with open(os.path.join(jdir, name)) as fh:
                    if json.load(fh).get("ev") == "committed":
                        n += 1
            except Exception:
                pass
    return n


def test_sigkill_mid_batch_journal_resume(tmp_path):
    """Crash-mid-batch replay: SIGKILL a journaled batched queue after
    the first batch committed (mid-queue, second batch in flight); the
    restarted server replays ONLY uncommitted members — zero lost, zero
    duplicated, byte-identical output set."""
    inputs = [_sim(tmp_path, f"k{i}.sam", 300 + i, contig_len=6000,
                   n_reads=20000) for i in range(6)]
    env = dict(os.environ, JAX_PLATFORMS="cpu", S2C_JIT_CACHE="",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    clean = str(tmp_path / "clean")
    r = subprocess.run(_serve_cmd(inputs, clean, str(tmp_path / "jc"),
                                  "3"),
                       env=env, capture_output=True, text=True,
                       timeout=420)
    assert r.returncode == 0, r.stderr[-2000:]
    want = {f: open(os.path.join(clean, f), "rb").read()
            for f in sorted(os.listdir(clean))}
    assert len(want) == 6

    outdir, jdir = str(tmp_path / "out"), str(tmp_path / "j")
    proc = subprocess.Popen(_serve_cmd(inputs, outdir, jdir, "3"),
                            env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    deadline = time.monotonic() + 300
    killed = False
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            break
        if 1 <= _committed(jdir) < 6:
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
            killed = True
            break
        time.sleep(0.02)
    assert killed, "queue finished before the kill window (too fast)"
    n_before = _committed(jdir)
    assert n_before < 6                     # genuinely mid-queue

    r2 = subprocess.run(_serve_cmd(inputs, outdir, jdir, "3"), env=env,
                        capture_output=True, text=True, timeout=420)
    assert r2.returncode == 0, r2.stderr[-2000:]
    got = {f: open(os.path.join(outdir, f), "rb").read()
           for f in sorted(os.listdir(outdir))}
    assert got == want
    audit = sjournal.JobJournal(jdir).audit()
    assert audit["duplicated"] == []        # committed members NOT rerun
    assert audit["lost"] == []
    assert len(audit["commit_counts"]) == 6


# -- composition policy ----------------------------------------------------
def _plan_entry(i, tmp, tenant="", total_len=3000, nbytes=10_000):
    spec = JobSpec(filename=f"/nonexistent/j{i}.sam",
                   config=RunConfig(backend="jax"), job_id=f"c{i}",
                   tenant=tenant)
    return {"spec": spec, "job_id": spec.job_id, "key": None,
            "jobnum": i, "action": "run", "cfg": spec.config,
            "admission": None, "resume_ckpt": False,
            "batch_total_len": total_len, "batch_bytes": nbytes}


def test_burning_tenant_flushes_without_window(tmp_path):
    """A tenant with SLO burn gets LATENCY: its job flushes the filling
    batch immediately (flush_reason slo_burn) instead of waiting for
    the batch to fill or the window to lapse."""
    r = _runner(batch="8", batch_window=10_000.0)   # absurd window
    try:
        r.admission.slo_burn_by_tenant["hot"] = 2
        plan = [_plan_entry(0, tmp_path), _plan_entry(1, tmp_path),
                _plan_entry(2, tmp_path, tenant="hot"),
                _plan_entry(3, tmp_path), _plan_entry(4, tmp_path)]
        batches = r.scheduler.compose(plan, arrivals=[0.0] * len(plan))
        assert batches, "no batches composed"
        first = batches[0]
        assert first.flush_reason == "slo_burn"
        assert first.indices == [0, 1, 2]       # ships at the hot job,
        # NOT held until max_jobs=8 or the 10s window
        assert batches[1].indices == [3, 4]
    finally:
        r.close()


def test_window_bounds_batch_composition(tmp_path):
    """An arrival outside --batch-window starts the next batch."""
    r = _runner(batch="8", batch_window=50.0)
    try:
        plan = [_plan_entry(i, tmp_path) for i in range(4)]
        batches = r.scheduler.compose(
            plan, arrivals=[0.0, 0.010, 0.200, 0.205])
        assert [b.indices for b in batches] == [[0, 1], [2, 3]]
        assert batches[0].flush_reason == "window"
    finally:
        r.close()


def test_pinned_tenant_not_batchable(tmp_path):
    r = _runner(batch="8")
    try:
        r.admission.tenant_rungs["deg"] = "host"
        plan = [_plan_entry(0, tmp_path),
                _plan_entry(1, tmp_path, tenant="deg"),
                _plan_entry(2, tmp_path)]
        batches = r.scheduler.compose(plan)
        assert [b.indices for b in batches] == [[0, 2]]
    finally:
        r.close()


def test_oversize_member_not_batchable(tmp_path):
    r = _runner(batch="8")
    try:
        plan = [_plan_entry(0, tmp_path),
                _plan_entry(1, tmp_path, total_len=1 << 30),
                _plan_entry(2, tmp_path)]
        batches = r.scheduler.compose(plan)
        assert [b.indices for b in batches] == [[0, 2]]
    finally:
        r.close()


# -- sidecar naming under packed execution ---------------------------------
def test_default_quarantine_sidecars_unique_per_packed_job(tmp_path):
    """Two packed jobs over the SAME upload in quarantine mode get
    DISTINCT default sidecars (.job<N> keyed on the server-lifetime job
    number) — concurrent commits can never clobber evidence files."""
    good = _sim(tmp_path, "q.sam", 60)
    bad = os.path.join(str(tmp_path), "qbad.sam")
    with open(good) as fh:
        lines = fh.read().splitlines()
    body = [ln for ln in lines if not ln.startswith("@")]
    hdr = [ln for ln in lines if ln.startswith("@")]
    f = body[0].split("\t")
    f[3] = "999999"
    with open(bad, "w") as fh:
        fh.write("\n".join(hdr + ["\t".join(f)] + body) + "\n")
    out = str(tmp_path / "o")
    os.makedirs(out)
    cfg = RunConfig(backend="jax", on_bad_record="quarantine",
                    outfolder=out + "/", prefix="same")
    specs = [JobSpec(filename=bad, config=cfg, job_id="qa"),
             JobSpec(filename=bad, config=cfg, job_id="qb")]
    r = _runner(batch="2")
    results = r.submit_jobs(specs)
    r.close()
    assert all(res.ok for res in results), [res.error for res in results]
    assert all(res.quarantined == 1 for res in results)
    sidecars = sorted(f for f in os.listdir(out) if "quarantine" in f)
    assert sidecars == ["same_quarantine.job0.jsonl",
                        "same_quarantine.job1.jsonl"]


# -- observability surfaces ------------------------------------------------
def test_batch_exposition_family_and_health(tmp_path):
    """The s2c_batch_* family renders lint-clean with HELP/TYPE
    discipline, and the health snapshot carries the batch section
    tools/s2c_top.py renders."""
    from sam2consensus_tpu.observability.telemetry import (
        lint_openmetrics, parse_openmetrics, render_openmetrics)

    paths = [_sim(tmp_path, f"e{i}.sam", 70 + i) for i in range(4)]
    specs = [JobSpec(filename=p, config=RunConfig(backend="jax"),
                     job_id=f"e{k}") for k, p in enumerate(paths)]
    r = _runner(batch="4")
    results = r.submit_jobs(specs)
    assert all(res.ok for res in results)
    text = r.render_telemetry()
    assert lint_openmetrics(text) == []
    samples = parse_openmetrics(text)
    names = {s["name"] for s in samples}
    assert {"s2c_batch_size", "s2c_batch_occupancy_pct",
            "s2c_batch_jobs_per_sec", "s2c_batch_batches_total",
            "s2c_batch_packed_jobs_total"} <= names
    snap = r.health_snapshot()
    assert snap["batch"]["batches"] == 1
    assert snap["batch"]["packed_jobs"] == 4
    assert snap["batch"]["last_size"] == 4
    assert 0 < snap["batch"]["last_occupancy_pct"] <= 100
    # the s2c_top frame renders the batching line from either surface
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import s2c_top

    frame = s2c_top.render(snap, samples)
    assert any("batching:" in ln for ln in frame)
    r.close()


def test_batch_decision_residual_joins(tmp_path):
    """The serve_batch ledger decision joins its measured counters: a
    second (warm) batch's residual uses the self-calibrated rate from
    the first, so the prediction tracks the rig."""
    paths = [_sim(tmp_path, f"d{i}.sam", 80 + i) for i in range(4)]

    def specs():
        return [JobSpec(filename=p, config=RunConfig(backend="jax"),
                        job_id=f"d{k}") for k, p in enumerate(paths)]
    r = _runner(batch="4")
    r.submit_jobs(specs())                  # calibration batch
    results = r.submit_jobs(specs())
    r.close()
    d = [x for x in (results[0].manifest or {}).get("decisions", [])
         if x["decision"] == "serve_batch"][0]
    assert d["measured"]["sec"] > 0
    assert d["residual"]["sec"] > 0
    assert d["residual"]["jobs_per_sec"] > 0


def test_decode_ahead_skips_batched_entries(tmp_path):
    """A mixed queue (batched smalls + an ineligible job) completes
    with every output byte-identical to serial — the decode-ahead
    launcher and the batch scheduler never fight over an entry."""
    paths = [_sim(tmp_path, f"m{i}.sam", 90 + i) for i in range(3)]
    big = _sim(tmp_path, "host.sam", 99)
    specs = [
        JobSpec(filename=paths[0], config=RunConfig(backend="jax"),
                job_id="m0"),
        JobSpec(filename=big,
                config=RunConfig(backend="jax", pileup="host"),
                job_id="mhost"),         # ineligible: explicit host pin
        JobSpec(filename=paths[1], config=RunConfig(backend="jax"),
                job_id="m1"),
        JobSpec(filename=paths[2], config=RunConfig(backend="jax"),
                job_id="m2"),
    ]
    rs = _runner(batch="off")
    want = [_rendered(r) for r in rs.submit_jobs(specs)]
    rs.close()
    rp = _runner(batch="8")
    got = rp.submit_jobs(specs)
    n_packed = rp.registry.value("batch/packed_jobs")
    rp.close()
    assert all(r.ok for r in got), [r.error for r in got]
    assert [_rendered(r) for r in got] == want
    assert n_packed == 3                   # the host-pinned job ran serial
